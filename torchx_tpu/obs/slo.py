"""Declarative SLOs evaluated as multi-window burn rates.

The serve autoscaler and the fleet market act on instantaneous probes
(queue depth, a single p99 sample, demand units). This module gives them
— and operators — the standard SRE alternative: an **SLO spec** (an
objective over a metric already flowing through the telemetry
:class:`~torchx_tpu.obs.telemetry.MetricStore`) evaluated as **burn
rates** over two windows. Burn rate is ``error_fraction / error_budget``
(budget = ``1 - objective``): burn 1.0 spends the budget exactly at the
objective's natural pace, 14 spends a 30-day budget in ~2 days. An alert
fires only when BOTH windows exceed the threshold — the short window
gates on "is it still happening", the long window on "is it material" —
the classic multi-window multi-burn-rate recipe.

Two spec kinds:

* **latency** — ``name:metric<threshold@objective``: the fraction of
  histogram observations above ``threshold`` seconds is the error
  fraction (computed from windowed cumulative-bucket deltas);
* **ratio** — ``name:metric{good=labels}/metric@objective``: good over
  total counter increases (e.g. goodput from ``status="ok"`` vs all).

:class:`SloEngine` evaluates every spec per collector cycle, journals
``slo_alert`` firing/resolved transitions as JSONL (append-only,
journal-before-act like the fleet), and exposes :meth:`SloEngine.active`
for ``tpx top`` / ``/v1/alerts`` and :meth:`SloEngine.max_burn` as the
scalar signal the autoscaler and market consume.

stdlib-only and jax-free (control-plane module).
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Optional

from torchx_tpu.obs.telemetry import MetricStore
from torchx_tpu.util.jsonl import append_jsonl

logger = logging.getLogger(__name__)

__all__ = [
    "SloSpec",
    "parse_slo",
    "SLO_PRESETS",
    "Alert",
    "SloEngine",
    "ROLE_METADATA_KEY",
]

#: fast burn consumes the budget ~14x the sustainable pace (page),
#: slow burn ~6x (warn) — the canonical SRE-workbook thresholds.
FAST_BURN = 14.0
SLOW_BURN = 6.0

#: AppDef role metadata key declaring the SLO specs a serve role is
#: expected to meet (same grammar as ``tpx control --slo``); analyze
#: rule TPX214 cross-checks it against the backend's scrape reachability.
ROLE_METADATA_KEY = "tpx/slo"


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective over a telemetry metric.

    ``kind`` is ``"latency"`` (histogram ``metric``, error = observation
    above ``threshold_s``) or ``"ratio"`` (counter ``metric`` filtered by
    ``good_labels`` over the same counter filtered by ``total_labels``).
    ``objective`` is the target good fraction (0 < objective < 1)."""

    name: str
    metric: str
    objective: float
    kind: str = "latency"
    threshold_s: float = 0.0
    good_labels: dict = field(default_factory=dict)
    total_labels: dict = field(default_factory=dict)
    short_window_s: float = 60.0
    long_window_s: float = 600.0
    fast_burn: float = FAST_BURN
    slow_burn: float = SLOW_BURN

    @property
    def budget(self) -> float:
        """The error budget, ``1 - objective`` (floored at a tiny
        positive value so burn stays finite)."""
        return max(1e-9, 1.0 - self.objective)


# name : metric < threshold @ objective        (latency)
# name : metric{k=v,...} / metric[{k=v,...}] @ objective   (ratio)
_LATENCY_RE = re.compile(
    r"^(?P<name>[\w.-]+):(?P<metric>[a-zA-Z_:][\w:]*)"
    r"<(?P<thresh>[\d.]+(?:ms|s)?)@(?P<obj>[\d.]+)$"
)
_RATIO_RE = re.compile(
    r"^(?P<name>[\w.-]+):(?P<metric>[a-zA-Z_:][\w:]*)"
    r"(?:\{(?P<good>[^}]*)\})?/(?P<tmetric>[a-zA-Z_:][\w:]*)"
    r"(?:\{(?P<total>[^}]*)\})?@(?P<obj>[\d.]+)$"
)


def _parse_labels(raw: Optional[str]) -> dict:
    out: dict = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip().strip('"')
    return out


def _parse_threshold(raw: str) -> float:
    if raw.endswith("ms"):
        return float(raw[:-2]) / 1000.0
    return float(raw[:-1]) if raw.endswith("s") else float(raw)


#: named shorthands accepted anywhere a spec string is (``--slo p99-ttft``):
#: the ISSUE's four exemplar objectives over the metrics the stack
#: already emits.
SLO_PRESETS: dict[str, str] = {
    # serve: 99% of requests reach first token within 500ms
    "p99-ttft": "p99-ttft:tpx_serve_ttft_seconds<0.5@0.99",
    # serve: 99.9% of requests finish with status="ok"
    "goodput": (
        'goodput:tpx_serve_requests_total{status="ok"}'
        "/tpx_serve_requests_total@0.999"
    ),
    # train: 95% of steps complete within 30s
    "step-time": "step-time:tpx_step_seconds<30@0.95",
    # fleet: 90% of gangs wait under 60s for placement
    "gang-wait": "gang-wait:tpx_fleet_gang_wait_seconds<60@0.90",
}


def parse_slo(spec: str) -> SloSpec:
    """Parse one SLO spec string (or a :data:`SLO_PRESETS` name).

    Grammar: ``name:metric<threshold@objective`` (threshold in seconds,
    an ``ms``/``s`` suffix allowed) for latency, or
    ``name:metric{k=v}/metric@objective`` for good/total ratios. Raises
    ``ValueError`` on anything else."""
    spec = SLO_PRESETS.get(spec.strip(), spec.strip())
    m = _LATENCY_RE.match(spec)
    if m:
        obj = float(m.group("obj"))
        if not 0.0 < obj < 1.0:
            raise ValueError(f"SLO objective must be in (0,1): {spec!r}")
        return SloSpec(
            name=m.group("name"),
            metric=m.group("metric"),
            objective=obj,
            kind="latency",
            threshold_s=_parse_threshold(m.group("thresh")),
        )
    m = _RATIO_RE.match(spec)
    if m:
        if m.group("metric") != m.group("tmetric"):
            raise ValueError(
                f"ratio SLO must divide one metric by itself: {spec!r}"
            )
        obj = float(m.group("obj"))
        if not 0.0 < obj < 1.0:
            raise ValueError(f"SLO objective must be in (0,1): {spec!r}")
        return SloSpec(
            name=m.group("name"),
            metric=m.group("metric"),
            objective=obj,
            kind="ratio",
            good_labels=_parse_labels(m.group("good")),
            total_labels=_parse_labels(m.group("total")),
        )
    raise ValueError(
        f"unparseable SLO spec {spec!r}; expected"
        " name:metric<thresh@obj or name:metric{{k=v}}/metric@obj"
        f" or a preset ({', '.join(sorted(SLO_PRESETS))})"
    )


@dataclass
class Alert:
    """One firing (or just-resolved) SLO alert."""

    slo: str
    severity: str  # "page" (fast burn) | "warn" (slow burn)
    state: str  # "firing" | "resolved"
    burn_short: float
    burn_long: float
    since: float
    ts: float

    def to_json(self) -> dict:
        """The journal/API encoding (``kind: slo_alert``)."""
        return {
            "kind": "slo_alert",
            "slo": self.slo,
            "severity": self.severity,
            "state": self.state,
            "burn_short": round(self.burn_short, 3),
            "burn_long": round(self.burn_long, 3),
            "since": self.since,
            "ts": self.ts,
        }


class SloEngine:
    """Evaluate SLO specs against a :class:`MetricStore` and journal
    alert transitions.

    Hang :meth:`evaluate` off the telemetry collector's hook list so
    burn rates refresh once per scrape cycle. Transitions (off→warn,
    warn→page, any→resolved) append one JSONL line to ``journal_path``;
    steady states journal nothing, so a steady run leaves an empty
    journal."""

    def __init__(
        self,
        store: MetricStore,
        specs: list[SloSpec],
        journal_path: Optional[str] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.store = store
        self.specs = list(specs)
        self.journal_path = journal_path
        self.clock = clock
        self._active: dict[str, Alert] = {}
        self._burns: dict[str, tuple[float, float]] = {}

    # -- burn math ---------------------------------------------------------

    def _error_fraction(self, spec: SloSpec, window_s: float, now: float) -> float:
        """Window error fraction for one spec; 0.0 on zero traffic (no
        observations can't violate an objective)."""
        if spec.kind == "latency":
            good = bad = 0.0
            deltas = self.store.histogram_deltas(
                spec.metric, window_s, now=now
            )
            for buckets in deltas.values():
                total = buckets[-1][1] if buckets else 0.0
                under = 0.0
                for le, cum in buckets:
                    if le <= spec.threshold_s or math.isclose(
                        le, spec.threshold_s, rel_tol=1e-9
                    ):
                        under = cum
                    else:
                        break
                good += under
                bad += max(0.0, total - under)
        else:
            doc = self.store.query(
                spec.metric,
                labels=spec.good_labels or None,
                reduce="rate",
                range_s=window_s,
                now=now,
            )
            good = sum(r["value"] for r in doc.get("result", []))
            doc = self.store.query(
                spec.metric,
                labels=spec.total_labels or None,
                reduce="rate",
                range_s=window_s,
                now=now,
            )
            total_rate = sum(r["value"] for r in doc.get("result", []))
            bad = max(0.0, total_rate - good)
        denom = good + bad
        return bad / denom if denom > 0 else 0.0

    def burn_rates(self, spec: SloSpec, now: Optional[float] = None) -> tuple[float, float]:
        """(short-window, long-window) burn rates for one spec."""
        now = self.clock() if now is None else now
        return (
            self._error_fraction(spec, spec.short_window_s, now) / spec.budget,
            self._error_fraction(spec, spec.long_window_s, now) / spec.budget,
        )

    # -- evaluation / alerting ---------------------------------------------

    def _journal(self, alert: Alert) -> None:
        if not self.journal_path:
            return
        try:
            append_jsonl(self.journal_path, alert.to_json())
        except OSError as e:
            logger.warning("slo journal write failed: %s", e)

    def evaluate(self, now: Optional[float] = None) -> list[Alert]:
        """Evaluate every spec; journal and return the transitions.

        Severity requires BOTH windows over the threshold: ``page`` at
        ``fast_burn``, else ``warn`` at ``slow_burn``, else resolved."""
        now = self.clock() if now is None else now
        transitions: list[Alert] = []
        for spec in self.specs:
            short, long_ = self.burn_rates(spec, now=now)
            self._burns[spec.name] = (short, long_)
            if short >= spec.fast_burn and long_ >= spec.fast_burn:
                severity: Optional[str] = "page"
            elif short >= spec.slow_burn and long_ >= spec.slow_burn:
                severity = "warn"
            else:
                severity = None
            current = self._active.get(spec.name)
            if severity is not None:
                if current is None or current.severity != severity:
                    alert = Alert(
                        slo=spec.name,
                        severity=severity,
                        state="firing",
                        burn_short=short,
                        burn_long=long_,
                        since=current.since if current else now,
                        ts=now,
                    )
                    self._active[spec.name] = alert
                    self._journal(alert)
                    transitions.append(alert)
                else:
                    # still firing: refresh the burns without journaling
                    self._active[spec.name] = replace(
                        current, burn_short=short, burn_long=long_, ts=now
                    )
            elif current is not None:
                resolved = replace(
                    current,
                    state="resolved",
                    burn_short=short,
                    burn_long=long_,
                    ts=now,
                )
                del self._active[spec.name]
                self._journal(resolved)
                transitions.append(resolved)
        return transitions

    def active(self) -> list[Alert]:
        """Currently-firing alerts, pages first then by name."""
        return sorted(
            self._active.values(),
            key=lambda a: (a.severity != "page", a.slo),
        )

    def burns(self) -> dict[str, tuple[float, float]]:
        """Last-evaluated (short, long) burns per SLO name."""
        return dict(self._burns)

    def max_burn(self, metric_prefix: Optional[str] = None) -> float:
        """Max long-window burn across specs (optionally only those whose
        metric starts with ``metric_prefix``) — the scalar the serve
        autoscaler and fleet market take as their SLO signal. 0.0 when
        nothing matches or nothing has been evaluated."""
        best = 0.0
        for spec in self.specs:
            if metric_prefix and not spec.metric.startswith(metric_prefix):
                continue
            burns = self._burns.get(spec.name)
            if burns:
                best = max(best, burns[1])
        return best
