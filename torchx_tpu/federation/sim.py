"""Two-cell federation scenarios on virtual time, driving the real router.

Same contract as :class:`~torchx_tpu.sim.harness.SimHarness`: the
journal bytes are a pure function of ``(scenario, seed)`` — no wall
time, no unseeded randomness — so a control-plane change is
regression-tested by diffing two journals. What runs under the clock is
the **production** :class:`~torchx_tpu.federation.router.FederationRouter`
(and its per-cell breakers), not a model of it: each cell is a
:class:`_SimCellClient` that answers the router's probe/dispatch surface
with scripted health and a load-dependent TTFT, and the scenario's
faults (``cell_drain`` / ``cell_kill`` / ``cell_uncordon`` /
``cell_restore``) flip that state mid-trace.

This is the deterministic twin of ``scripts/bench_federation.py``: the
bench runs the same diurnal-partitioned two-cell drain against real
daemons and reports wall numbers; this harness replays the shape in
virtual time so CI can assert *zero drops* and bounded failover p99
without booting anything.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import random
import tempfile
import time
from typing import Any, Optional

from torchx_tpu.control.client import ControlClientError
from torchx_tpu.federation.cells import CellHandle, CellSpec
from torchx_tpu.federation.router import (
    FederationError,
    FederationRouter,
)
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.resilience.policy import CallPolicy
from torchx_tpu.sim.harness import SimReport

__all__ = ["FederationSimHarness"]

#: fault kinds this harness understands.
FED_FAULT_KINDS = ("cell_drain", "cell_uncordon", "cell_kill", "cell_restore")


def _p99(samples: list) -> float:
    """p99 of a sample list (0.0 when empty), nearest-rank."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = max(0, math.ceil(0.99 * len(ordered)) - 1)
    return float(ordered[idx])


class _SimCellClient:
    """One virtual cell answering the router's client surface.

    TTFT is load-dependent: at or under ``capacity_rps`` the cell serves
    at ``ttft_base_s``; past it, TTFT climbs linearly toward
    ``ttft_degraded_s`` — which is exactly what the surviving cell feels
    when it absorbs a drained region's traffic."""

    def __init__(
        self,
        name: str,
        capacity_rps: float,
        ttft_base_s: float,
        ttft_degraded_s: float,
        tick_s: float,
        harness: "FederationSimHarness",
    ) -> None:
        self.name = name
        self.capacity_rps = float(capacity_rps)
        self.ttft_base_s = float(ttft_base_s)
        self.ttft_degraded_s = float(ttft_degraded_s)
        self.tick_s = float(tick_s)
        self._h = harness
        self.draining = False
        self.killed = False
        self.rehydrated = True
        self.burn = 0.0
        self.tick_load = 0
        self.served = 0

    def _check_up(self) -> None:
        if self.killed:
            raise ControlClientError(
                0, f"cell {self.name}: connection refused"
            )

    def cell_status(self) -> dict:
        self._check_up()
        return {
            "cell": self.name,
            "state": "DRAINING" if self.draining else "HEALTHY",
            "draining": self.draining,
            "rehydrated": self.rehydrated,
            "rehydration": {},
            "inflight": 0,
        }

    def healthz(self) -> dict:
        self._check_up()
        return {"status": "ok", "cell": self.name, "rehydrated": True}

    def alerts(self) -> dict:
        self._check_up()
        b = round(self.burn, 3)
        return {
            "enabled": True,
            "alerts": [],
            "burns": {"ttft": {"short": b, "long": b}},
            "slos": ["ttft"],
        }

    def serve(self) -> float:
        """One request dial: TTFT in seconds, or the refusal verdicts
        the real daemon would give (transport when killed, 503 when
        draining). Every dial — refused or served — counts an attempt
        so the harness can charge failover latency."""
        self._h.attempts += 1
        self._check_up()
        if self.draining:
            raise ControlClientError(
                503, f"cell {self.name!r} is draining; submit elsewhere"
            )
        self.tick_load += 1
        capacity_per_tick = max(1.0, self.capacity_rps * self.tick_s)
        over = max(0.0, self.tick_load / capacity_per_tick - 1.0)
        self.served += 1
        return self.ttft_base_s + (
            self.ttft_degraded_s - self.ttft_base_s
        ) * min(1.0, over)


class FederationSimHarness:
    """Replay one federation scenario deterministically.

    Accepts the same ``(scenario, seed, state_dir, journal_path)``
    surface as :class:`~torchx_tpu.sim.harness.SimHarness` so
    ``tpx sim run`` routes here transparently when the scenario carries
    a ``cells`` list. Returns the same
    :class:`~torchx_tpu.sim.harness.SimReport`.
    """

    def __init__(
        self,
        scenario: dict,
        seed: Optional[int] = None,
        state_dir: Optional[str] = None,
        journal_path: Optional[str] = None,
    ) -> None:
        if not scenario.get("cells"):
            raise ValueError("federation scenario needs a 'cells' list")
        self.scenario = scenario
        self.seed = int(
            seed if seed is not None else scenario.get("seed", 11)
        )
        self.state_dir = state_dir or tempfile.mkdtemp(prefix="tpx-fedsim-")
        self.journal_path = journal_path or os.path.join(
            self.state_dir, "sim_journal.jsonl"
        )
        self._now = 0.0
        self._rows: list[str] = []
        self._rng = random.Random(self.seed)
        serve = dict(scenario.get("serve") or {})
        self.tick_s = float(scenario.get("metrics_interval_s", 60.0))
        self.dial_timeout_s = float(serve.get("dial_timeout_s", 0.1))
        self.slo_target_s = float(serve.get("slo_target_s", 0.5))
        self.requests_per_tick = float(serve.get("requests_per_tick", 4.0))
        self.attempts = 0
        self._clients: dict[str, _SimCellClient] = {}
        self._regions: dict[str, dict] = {}
        handles = []
        for spec in scenario["cells"]:
            name = str(spec["name"])
            client = _SimCellClient(
                name,
                capacity_rps=float(spec.get("capacity_rps", 0.1)),
                ttft_base_s=float(serve.get("ttft_base_s", 0.08)),
                ttft_degraded_s=float(serve.get("ttft_degraded_s", 0.4)),
                tick_s=self.tick_s,
                harness=self,
            )
            handle = CellHandle(
                CellSpec(name=name, addr=f"sim://{name}"),
                client=client,
                clock=self.clock,
            )
            # each region's requests carry its home chain, and the home
            # cell exports exactly those digests: affinity keeps traffic
            # regional until health says otherwise
            chain = [f"{name}:blk{i}" for i in range(8)]
            handle.update_prefix_digests(chain)
            self._clients[name] = client
            self._regions[name] = {
                "chain": chain,
                "phase_h": float(spec.get("phase_h", 0.0)),
            }
            handles.append(handle)
        self.router = FederationRouter(
            handles,
            burn_budget=float(scenario.get("burn_budget", 2.0)),
            policy=CallPolicy(backoff_seconds=0.2, backoff_max_seconds=2.0),
            probe_ttl_s=self.tick_s / 2.0,
            clock=self.clock,
            sleep=self._advance,
            rng=random.Random(self.seed ^ 0x51ED),
        )

    # -- virtual time --------------------------------------------------------

    def clock(self) -> float:
        """The virtual instant, seconds since scenario start."""
        return self._now

    def _advance(self, seconds: float) -> None:
        self._now += max(0.0, float(seconds))

    def _emit(self, kind: str, **fields: Any) -> None:
        row = {"t": round(self._now, 6), "kind": kind}
        row.update(fields)
        self._rows.append(json.dumps(row, sort_keys=True))

    # -- the run -------------------------------------------------------------

    def _rate(self, t: float, phase_h: float) -> float:
        """Diurnal request rate for one region at virtual ``t``: the
        same day-curve shape as the fleet trace, phase-shifted per
        region (partitioned traffic, not mirrored)."""
        day_frac = (t / 86400.0 + phase_h / 24.0) % 1.0
        return self.requests_per_tick * (
            0.65 + 0.35 * math.sin(2.0 * math.pi * (day_frac - 0.25))
        )

    def _apply_fault(self, fault: dict) -> None:
        kind = str(fault.get("kind", ""))
        cell = str(fault.get("cell", ""))
        client = self._clients.get(cell)
        if client is None or kind not in FED_FAULT_KINDS:
            return
        if kind == "cell_drain":
            client.draining = True
        elif kind == "cell_uncordon":
            client.draining = False
        elif kind == "cell_kill":
            client.killed = True
        elif kind == "cell_restore":
            client.killed = False
            client.rehydrated = True
        obs_metrics.SIM_FAULTS.inc(kind=kind)
        self._emit("fault", fault=kind, cell=cell)

    def run(self) -> SimReport:
        """Execute the scenario; returns the run report (journal bytes
        are a pure function of scenario + seed)."""
        wall_start = time.perf_counter()
        horizon = float(self.scenario.get("hours", 1.0)) * 3600.0
        faults = sorted(
            (dict(f) for f in self.scenario.get("faults", [])),
            key=lambda f: (float(f.get("t", 0.0)), str(f.get("kind", ""))),
        )
        disrupt_ts = [
            float(f.get("t", 0.0))
            for f in faults
            if f.get("kind") in ("cell_drain", "cell_kill")
        ]
        restore_ts = [
            float(f.get("t", 0.0))
            for f in faults
            if f.get("kind") in ("cell_uncordon", "cell_restore")
        ]
        drain_t = min(disrupt_ts) if disrupt_ts else None
        recover_t = min(restore_ts) if restore_ts else None
        self._emit(
            "begin",
            scenario=str(self.scenario.get("name", "")),
            seed=self.seed,
            cells=sorted(self._clients),
            hours=round(horizon / 3600.0, 6),
        )
        samples: dict[str, list[float]] = {"pre": [], "during": [], "post": []}
        dropped = 0
        spillovers = 0
        requests = 0
        t = 0.0
        while t < horizon:
            self._now = t
            while faults and float(faults[0].get("t", 0.0)) <= t:
                self._apply_fault(faults.pop(0))
            for client in self._clients.values():
                client.tick_load = 0
            tick_ttfts: dict[str, list[float]] = {
                n: [] for n in self._clients
            }
            for region in sorted(self._regions):
                info = self._regions[region]
                rate = self._rate(t, info["phase_h"])
                n = int(rate) + (
                    1 if self._rng.random() < (rate - int(rate)) else 0
                )
                for _ in range(n):
                    requests += 1
                    self.attempts = 0
                    start = self._now
                    try:
                        cell, ttft = self.router.dispatch(
                            lambda c: c.serve(), chain=info["chain"]
                        )
                    except FederationError:
                        dropped += 1
                        self._emit("drop", region=region)
                        self._now = start
                        continue
                    # a request pays one dial timeout per refused dial
                    # plus whatever backoff the router slept (already in
                    # virtual time via the injected sleep)
                    latency = ttft + self.dial_timeout_s * max(
                        0, self.attempts - 1
                    ) + (self._now - start)
                    self._now = start
                    if cell != region:
                        spillovers += 1
                    if drain_t is None or t < drain_t:
                        phase = "pre"
                    elif recover_t is None or t < recover_t:
                        phase = "during"
                    else:
                        phase = "post"
                    samples[phase].append(latency)
                    tick_ttfts[cell].append(latency)
                    self._emit(
                        "request",
                        region=region,
                        cell=cell,
                        ttft=round(latency, 6),
                        attempts=self.attempts,
                    )
            for name in sorted(self._clients):
                client = self._clients[name]
                # tick burn = how far this tick's p99 sits over the SLO
                # target; feeds the router's scoring via /v1/alerts
                client.burn = (
                    _p99(tick_ttfts[name]) / self.slo_target_s
                    if tick_ttfts[name]
                    else 0.0
                )
            self._emit(
                "tick",
                loads={
                    n: self._clients[n].tick_load
                    for n in sorted(self._clients)
                },
                burns={
                    n: round(self._clients[n].burn, 3)
                    for n in sorted(self._clients)
                },
            )
            t += self.tick_s
        self._now = horizon
        all_samples = samples["pre"] + samples["during"] + samples["post"]
        stats = {
            "requests": requests,
            "dropped": dropped,
            "spillovers": spillovers,
            "ttft_p99_s": round(_p99(all_samples), 6),
            "ttft_p99_pre_s": round(_p99(samples["pre"]), 6),
            "ttft_p99_during_s": round(_p99(samples["during"]), 6),
            "ttft_p99_post_s": round(_p99(samples["post"]), 6),
            "per_cell": {
                n: self._clients[n].served for n in sorted(self._clients)
            },
            "faults": len(disrupt_ts) + len(restore_ts),
        }
        self._emit("end", virtual_s=round(horizon, 6), **stats)
        return self._finalize(horizon, time.perf_counter() - wall_start, stats)

    def _finalize(
        self, virtual_s: float, wall_s: float, stats: dict
    ) -> SimReport:
        payload = ("\n".join(self._rows) + "\n").encode()
        os.makedirs(os.path.dirname(self.journal_path) or ".", exist_ok=True)
        with open(self.journal_path, "wb") as f:
            f.write(payload)
        digest = hashlib.sha256(payload).hexdigest()
        speedup = virtual_s / wall_s if wall_s > 0 else 0.0
        kinds: dict[str, int] = {}
        for line in self._rows:
            k = json.loads(line)["kind"]
            kinds[k] = kinds.get(k, 0) + 1
        for k, n in sorted(kinds.items()):
            obs_metrics.SIM_EVENTS.inc(n, kind=k)
        obs_metrics.SIM_VIRTUAL_SECONDS.set(virtual_s)
        obs_metrics.SIM_WALL_SECONDS.set(wall_s)
        obs_metrics.SIM_SPEEDUP.set(speedup)
        return SimReport(
            scenario=str(self.scenario.get("name", "")),
            seed=self.seed,
            virtual_s=virtual_s,
            wall_s=wall_s,
            speedup=speedup,
            journal_path=self.journal_path,
            journal_sha256=digest,
            stats=stats,
        )
