"""The federation router: burn-aware, affinity-aware, spill-to-survive.

:class:`FederationRouter` owns a set of
:class:`~torchx_tpu.federation.cells.CellHandle` and answers one
question per request: *which cell, in what order of preference*. The
ordering is two-tiered:

- **admissible** cells — reachable, journal-rehydrated, not
  draining/drained, breaker not OPEN — sorted by score;
- cells whose SLO burn exceeds the budget are **demoted** to a second
  tier, not excluded: a hot cell beats a dropped request.

Score within a tier = long-window burn minus an affinity bonus scaled
by prefix-chain overlap (PR 12's positional digests: the longest chain
prefix the cell's exported digest set already holds), name as the final
deterministic tie-break.

:meth:`FederationRouter.dispatch` walks candidates in order, records
each dial on the cell's circuit breaker, and sleeps a capped jittered
backoff between full passes — it raises
:class:`FederationError` only when every cell refused across every
round, which is the "no healthy cell anywhere" verdict, never a single
cell's failure. A 503 ``cell_draining`` verdict marks the cell drained
in the cached probe and moves on immediately (the daemon said
*don't retry here*, not *I am sick*).
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Iterable, Optional, Sequence

from torchx_tpu import settings
from torchx_tpu.control.client import ControlClientError
from torchx_tpu.federation.cells import CellHandle, DRAINING
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.resilience.breaker import BreakerState, STATE_VALUES
from torchx_tpu.resilience.policy import CallPolicy

__all__ = ["FederationError", "FederationRouter"]

#: HTTP verdicts that mean "try another cell" rather than "bad request":
#: transport (0), throttled past the client's own retries (429), and
#: draining/unavailable (503).
SPILL_CODES = frozenset({0, 429, 503})


class FederationError(RuntimeError):
    """Every cell refused: carries the per-cell last-error map."""

    def __init__(self, message: str, errors: Optional[dict] = None) -> None:
        super().__init__(message)
        self.errors = dict(errors or {})


class FederationRouter:
    """Routes requests across cells by SLO burn + prefix affinity.

    Args:
        handles: the cells, as :class:`CellHandle` (or anything
            duck-typing its ``name``/``client``/``breaker``/``probe``
            surface — the sim harness substitutes virtual cells).
        burn_budget: long-window burn at/above which a cell is demoted
            to the second preference tier.
        affinity_bonus: score credit for a full prefix-chain overlap
            (scaled linearly by overlap fraction).
        policy: backoff shape between full candidate passes.
        max_rounds: full passes over the candidate list before
            :class:`FederationError`.
        probe_ttl_s: probe cache lifetime; candidates re-probe lazily.
        clock/sleep/rng: injectable for tests and the virtual-time sim.
    """

    def __init__(
        self,
        handles: Iterable[CellHandle],
        burn_budget: float = settings.DEFAULT_FEDERATION_BURN_BUDGET,
        affinity_bonus: float = 0.25,
        policy: Optional[CallPolicy] = None,
        max_rounds: int = 3,
        probe_ttl_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._handles: dict[str, CellHandle] = {}
        for h in handles:
            self._handles[h.name] = h
        self.burn_budget = float(burn_budget)
        self.affinity_bonus = float(affinity_bonus)
        self.policy = policy or CallPolicy(
            backoff_seconds=0.2, backoff_max_seconds=2.0
        )
        self.max_rounds = max(1, int(max_rounds))
        self.probe_ttl_s = float(probe_ttl_s)
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()

    # -- membership --------------------------------------------------------

    def add_cell(self, handle: CellHandle) -> None:
        """Add (or replace) a cell."""
        self._handles[handle.name] = handle

    def remove_cell(self, name: str) -> bool:
        """Drop a cell; False when unknown."""
        return self._handles.pop(name, None) is not None

    def cells(self) -> list[CellHandle]:
        """All handles, name-sorted."""
        return [self._handles[k] for k in sorted(self._handles)]

    # -- scoring -----------------------------------------------------------

    def _fresh_probe(self, handle: CellHandle) -> dict:
        if self._clock() - handle.probed_at >= self.probe_ttl_s:
            snap = handle.probe()
            obs_metrics.FED_CELL_BURN.set(
                float(snap.get("burn", 0.0)), cell=handle.name
            )
            obs_metrics.FED_BREAKER_STATE.set(
                float(STATE_VALUES[handle.breaker.state]), cell=handle.name
            )
        return handle.last_probe

    def _overlap(self, handle: CellHandle, chain: Sequence[str]) -> float:
        """Fraction of the request's prefix chain this cell already
        holds, counted as the longest matching *prefix* (the chain is
        positional: a later block without its predecessors is no hit)."""
        if not chain or not handle.prefix_digests:
            return 0.0
        n = 0
        for digest in chain:
            if digest not in handle.prefix_digests:
                break
            n += 1
        return n / len(chain)

    def candidates(
        self, chain: Optional[Sequence[str]] = None
    ) -> list[CellHandle]:
        """Cells in dispatch preference order (may be empty).

        Tier 0: admissible and under the burn budget. Tier 1: admissible
        but burning over budget (degraded beats dropped). Excluded:
        unreachable, not rehydrated (treated as drained), draining or
        drained, breaker OPEN.
        """
        scored = []
        for handle in self.cells():
            snap = self._fresh_probe(handle)
            if not snap.get("reachable") or not snap.get("rehydrated"):
                continue
            if snap.get("draining") or snap.get("state") in (
                "DRAINING",
                "DRAINED",
            ):
                continue
            if handle.breaker.state is BreakerState.OPEN:
                continue
            burn = float(snap.get("burn", 0.0))
            tier = 0 if burn < self.burn_budget else 1
            score = burn - self.affinity_bonus * self._overlap(
                handle, chain or ()
            )
            scored.append((tier, score, handle.name, handle))
        scored.sort(key=lambda t: t[:3])
        return [t[3] for t in scored]

    def snapshot(self) -> dict:
        """Per-cell observed state for ``tpx cell list`` / ``tpx top``."""
        out = {}
        for handle in self.cells():
            snap = dict(self._fresh_probe(handle))
            snap["breaker"] = handle.breaker.state.value
            out[handle.name] = snap
        return out

    # -- dispatch ----------------------------------------------------------

    def dispatch(
        self,
        fn: Callable[[Any], Any],
        chain: Optional[Sequence[str]] = None,
    ) -> tuple[str, Any]:
        """Run ``fn(cell.client)`` on the best cell, spilling on failure.

        Returns ``(cell_name, result)``. Per dial: success closes the
        cell's breaker; a transport failure trips it a step; a
        :data:`SPILL_CODES` verdict moves to the next candidate (a 503
        additionally marks the cached probe draining so the cell drops
        out of the very next candidate list without waiting for the
        probe TTL). Any other HTTP error is the *request's* fault and
        re-raises immediately — a malformed submit must not be replayed
        against every region. Between rounds the candidate list is
        rebuilt (probes refresh) after a capped jittered backoff.
        Raises :class:`FederationError` when all rounds exhaust.
        """
        errors: dict[str, str] = {}
        for round_no in range(1, self.max_rounds + 1):
            first_choice = True
            for handle in self.candidates(chain):
                if not handle.breaker.allow():
                    errors[handle.name] = "breaker open"
                    first_choice = False
                    continue
                if not first_choice:
                    obs_metrics.FED_SPILLOVERS.inc(reason="spill")
                try:
                    result = fn(handle.client)
                except ControlClientError as e:
                    errors[handle.name] = f"{e.code}: {e.message}"
                    if e.code == 0:
                        handle.breaker.record_failure()
                        obs_metrics.FED_REQUESTS.inc(
                            cell=handle.name, outcome="error"
                        )
                    else:
                        # the daemon answered: transport is fine
                        handle.breaker.record_success()
                        obs_metrics.FED_REQUESTS.inc(
                            cell=handle.name, outcome="refused"
                        )
                    if e.code == 503:
                        handle.last_probe = dict(
                            handle.last_probe, draining=True, state=DRAINING
                        )
                    if e.code not in SPILL_CODES:
                        raise
                    first_choice = False
                    continue
                handle.breaker.record_success()
                obs_metrics.FED_REQUESTS.inc(
                    cell=handle.name, outcome="ok"
                )
                return handle.name, result
            if round_no < self.max_rounds:
                self._sleep(self.policy.backoff_delay(round_no, self._rng))
        raise FederationError(
            f"no cell accepted the request after {self.max_rounds}"
            f" round(s): {errors or 'no admissible cells'}",
            errors=errors,
        )

    def submit(
        self,
        component: str,
        args: list[str],
        scheduler: str,
        chain: Optional[Sequence[str]] = None,
        **kw: Any,
    ) -> tuple[str, dict]:
        """Submit a job through the best cell; returns
        ``(cell_name, daemon_reply)``."""
        return self.dispatch(
            lambda client: client.submit_job(
                component, args, scheduler, **kw
            ),
            chain=chain,
        )
