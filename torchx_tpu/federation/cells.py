"""Cell identity, the durable cell registry, and per-cell handles.

A *cell* is one control daemon (plus whatever fleet/serve planes it
owns) addressed by name. The registry is the federation's address book:
an append-only JSONL journal under ``$TPX_FEDERATION_DIR`` replayed on
load, same idiom as every other tpx store. It records *where cells are*
— their lifecycle state (draining/drained) is owned by each cell's own
daemon and survives that daemon's restarts via its ``cell.json``, so a
registry copied between operator machines never disagrees with the
cells themselves about health.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Callable, Optional

from torchx_tpu import settings
from torchx_tpu.control.client import ControlClient, ControlClientError
from torchx_tpu.resilience.breaker import CircuitBreaker

__all__ = [
    "HEALTHY",
    "DRAINING",
    "DRAINED",
    "UNCORDONED",
    "LIFECYCLE",
    "CellSpec",
    "CellHandle",
    "CellRegistry",
    "federation_dir",
]

#: lifecycle label: accepting traffic.
HEALTHY = "HEALTHY"
#: lifecycle label: refusing new work, finishing in-flight work.
DRAINING = "DRAINING"
#: lifecycle label: draining finished — nothing in flight, nothing new.
DRAINED = "DRAINED"
#: lifecycle label: the transitional acknowledgment of an uncordon
#: (subsequent reads say HEALTHY).
UNCORDONED = "UNCORDONED"

#: the full cell lifecycle, in order.
LIFECYCLE = (HEALTHY, DRAINING, DRAINED, UNCORDONED)


def federation_dir() -> str:
    """State root for the federation layer: ``$TPX_FEDERATION_DIR``,
    default ``~/.torchx_tpu/federation``."""
    raw = os.environ.get(settings.ENV_TPX_FEDERATION_DIR)
    if raw and raw.strip():
        return raw
    return os.path.join(os.path.expanduser("~"), ".torchx_tpu", "federation")


@dataclass(frozen=True)
class CellSpec:
    """One registry entry: how to reach one cell's daemon."""

    #: cell name (the daemon's ``--cell`` identity).
    name: str
    #: daemon base URL, e.g. ``http://127.0.0.1:PORT``.
    addr: str
    #: bearer token for the daemon's ``/v1`` routes.
    token: str = ""

    def to_json(self) -> dict:
        """Plain-dict form for the registry journal."""
        return {"cell": self.name, "addr": self.addr, "token": self.token}


class CellRegistry:
    """The durable cell address book.

    Append-only JSONL journal (``cells.jsonl``, 0600 — it carries
    tokens) replayed on load: ``add`` rows upsert, ``remove`` rows
    delete, last writer wins. Mutations journal-then-apply, so a crash
    between the two replays to the journaled state.
    """

    JOURNAL = "cells.jsonl"

    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root or federation_dir()
        self.path = os.path.join(self.root, self.JOURNAL)
        self._cells: dict[str, CellSpec] = {}
        self._rehydrate()

    def _rehydrate(self) -> None:
        from torchx_tpu.util.jsonl import iter_jsonl

        for row in iter_jsonl(self.path):
            op = str(row.get("op", ""))
            name = str(row.get("cell", ""))
            if not name:
                continue
            if op == "add":
                self._cells[name] = CellSpec(
                    name=name,
                    addr=str(row.get("addr", "")),
                    token=str(row.get("token", "")),
                )
            elif op == "remove":
                self._cells.pop(name, None)

    def _journal(self, row: dict) -> None:
        from torchx_tpu.util.jsonl import append_jsonl

        os.makedirs(self.root, exist_ok=True)
        append_jsonl(self.path, row)
        os.chmod(self.path, 0o600)

    def add(self, name: str, addr: str, token: str = "") -> CellSpec:
        """Register (or re-address) a cell."""
        if not name or not addr:
            raise ValueError("cell add needs a name and an addr")
        spec = CellSpec(name=name, addr=addr.rstrip("/"), token=token)
        self._journal({"op": "add", **spec.to_json()})
        self._cells[name] = spec
        return spec

    def remove(self, name: str) -> bool:
        """Forget a cell; False when it was never registered."""
        if name not in self._cells:
            return False
        self._journal({"op": "remove", "cell": name})
        del self._cells[name]
        return True

    def get(self, name: str) -> Optional[CellSpec]:
        """One cell's spec, or None."""
        return self._cells.get(name)

    def cells(self) -> list[CellSpec]:
        """All registered cells, name-sorted (deterministic routing
        tie-break order)."""
        return [self._cells[k] for k in sorted(self._cells)]

    def __len__(self) -> int:
        return len(self._cells)


class CellHandle:
    """One cell as the router sees it: client + breaker + cached probe.

    The probe collapses ``/healthz`` + ``/v1/cell`` + ``/v1/alerts``
    into one snapshot dict; dial failures feed the per-cell
    :class:`~torchx_tpu.resilience.breaker.CircuitBreaker` so a dead
    daemon fails fast instead of stacking timeouts on every request.
    ``prefix_digests`` holds the cell's exported prefix-cache chain
    digests (PR 12) for the router's affinity score — fed by
    :meth:`update_prefix_digests` from each cell's serve pool summary.
    """

    def __init__(
        self,
        spec: CellSpec,
        client: Optional[ControlClient] = None,
        breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.spec = spec
        # probes must not block routing: short timeout, no 429 loitering
        self.client = client or ControlClient(
            spec.addr, spec.token, timeout=5.0, retry_429=0
        )
        self.breaker = breaker or CircuitBreaker(
            f"cell:{spec.name}",
            trip_after=settings.FEDERATION_BREAKER_TRIP_AFTER,
            cooldown_seconds=settings.FEDERATION_BREAKER_COOLDOWN_SECONDS,
            clock=clock,
        )
        self.prefix_digests: set[str] = set()
        #: last probe snapshot (see :meth:`probe`); starts pessimistic.
        self.last_probe: dict = {"reachable": False}
        #: clock() stamp of the last probe, -inf = never.
        self.probed_at: float = float("-inf")
        self._clock = clock

    @property
    def name(self) -> str:
        """The cell's registry name."""
        return self.spec.name

    def update_prefix_digests(self, digests) -> None:
        """Replace the cell's exported prefix-chain digest set (from its
        serve pool's ``federation_summary()``)."""
        self.prefix_digests = set(str(d) for d in digests)

    def probe(self) -> dict:
        """Refresh and return the cached health snapshot.

        ``{"reachable", "rehydrated", "draining", "state", "burn"}`` —
        ``state`` is the daemon's lifecycle label, ``burn`` the max
        long-window SLO burn across its SLOs (0.0 when none evaluate).
        A transport failure records on the breaker and yields
        ``reachable: False``; a not-yet-rehydrated daemon is reachable
        but the router treats it as drained.
        """
        snap: dict = {
            "reachable": False,
            "rehydrated": False,
            "draining": False,
            "state": DRAINED,
            "burn": 0.0,
        }
        try:
            cell = self.client.cell_status()
            snap["reachable"] = True
            snap["rehydrated"] = bool(cell.get("rehydrated"))
            snap["draining"] = bool(cell.get("draining"))
            snap["state"] = str(cell.get("state", HEALTHY))
            self.breaker.record_success()
        except ControlClientError as e:
            if e.code == 0:
                self.breaker.record_failure()
            elif e.code == 404:
                # pre-federation daemon: no /v1/cell route — reachable,
                # never drains, rehydration unknown -> assume complete
                snap.update(
                    reachable=True, rehydrated=True, state=HEALTHY
                )
                self.breaker.record_success()
            self.last_probe = snap
            self.probed_at = self._clock()
            return snap
        try:
            alerts = self.client.alerts()
            burns = alerts.get("burns") or {}
            snap["burn"] = max(
                (float(b.get("long", 0.0)) for b in burns.values()),
                default=0.0,
            )
        except ControlClientError:
            pass  # burn stays 0.0: no telemetry is not unhealth
        self.last_probe = snap
        self.probed_at = self._clock()
        return snap
