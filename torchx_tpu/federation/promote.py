"""Region-by-region promotion waves with per-cell rollback.

A single-cell promotion is PR 15's pipeline engine: train → eval →
canary → promote-or-rollback inside one daemon.
:class:`FederationPromoter` lifts that to N cells *sequentially*: the
candidate rolls into one region at a time, and the wave halts the
moment any cell's pipeline rolls back (its own canary gate fired) or
the cell's observed SLO burn crosses the threshold — the remaining
regions never see the candidate. Each cell's rollback is the engine's
own (PR 15 journal-before-act), so a halted wave leaves every touched
cell either fully promoted or fully restored, never half-rolled.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from torchx_tpu import settings
from torchx_tpu.control.client import ControlClientError
from torchx_tpu.federation.router import FederationRouter

__all__ = ["FederationPromoter", "WaveResult"]

#: pipeline terminal states that halt the wave.
_HALTING_STATES = frozenset({"ROLLED_BACK", "FAILED", "CANCELLED"})
#: pipeline terminal states that advance the wave.
_ADVANCE_STATES = frozenset({"PROMOTED", "SUCCEEDED"})


@dataclass
class WaveResult:
    """One wave's outcome, cell by cell."""

    #: cells whose pipeline reached PROMOTED/SUCCEEDED.
    promoted: list[str] = field(default_factory=list)
    #: cells the wave never reached (halted earlier).
    skipped: list[str] = field(default_factory=list)
    #: per-cell record: {"pipeline", "state", "reason"}.
    cells: dict = field(default_factory=dict)
    #: True when the wave stopped before the last cell.
    halted: bool = False
    #: why the wave halted ("" when it ran to completion).
    halt_reason: str = ""

    def to_dict(self) -> dict:
        """JSON form for the CLI."""
        return {
            "promoted": list(self.promoted),
            "skipped": list(self.skipped),
            "cells": dict(self.cells),
            "halted": self.halted,
            "halt_reason": self.halt_reason,
        }


class FederationPromoter:
    """Drives one pipeline spec through cells in order.

    Args:
        router: the federation router (cell handles + probes).
        burn_threshold: observed per-cell long-window burn at/above
            which the wave halts even if the cell's pipeline promoted —
            the next region must not inherit a candidate that is
            burning its first region's SLO.
        poll_interval_s: pipeline status poll cadence.
        timeout_s: per-cell ceiling from submit to terminal.
        clock/sleep: injectable for tests.
    """

    def __init__(
        self,
        router: FederationRouter,
        burn_threshold: float = settings.DEFAULT_FEDERATION_BURN_BUDGET,
        poll_interval_s: float = 0.5,
        timeout_s: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.router = router
        self.burn_threshold = float(burn_threshold)
        self.poll_interval_s = float(poll_interval_s)
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self._sleep = sleep

    def _wave_order(self, order: Optional[list[str]]) -> list[str]:
        """Explicit order, else healthiest-first (lowest burn): the cell
        most likely to absorb a bad candidate cheaply goes first."""
        if order:
            return list(order)
        snap = self.router.snapshot()
        return sorted(snap, key=lambda n: (snap[n].get("burn", 0.0), n))

    def run_wave(
        self, spec: dict, order: Optional[list[str]] = None
    ) -> WaveResult:
        """Submit ``spec`` (a PipelineSpec dict) to each cell in turn.

        A cell whose daemon refuses the submit (draining, unreachable)
        is recorded as skipped *without* halting the wave — routing away
        from a drained region is normal operation, not a bad candidate.
        A pipeline that rolls back, fails, times out, or leaves the cell
        burning at/over ``burn_threshold`` halts the wave.
        """
        result = WaveResult()
        names = self._wave_order(order)
        handles = {h.name: h for h in self.router.cells()}
        for i, name in enumerate(names):
            handle = handles.get(name)
            if handle is None:
                result.cells[name] = {"state": "UNKNOWN_CELL", "reason": ""}
                continue
            if result.halted:
                result.skipped.append(name)
                continue
            try:
                reply = handle.client.pipeline_submit(spec)
                pid = str(reply.get("pipeline", ""))
            except ControlClientError as e:
                result.cells[name] = {
                    "state": "UNREACHED",
                    "reason": f"{e.code}: {e.message}",
                }
                continue
            record = self._await_terminal(handle, pid)
            state = str(record.get("state", ""))
            result.cells[name] = {
                "pipeline": pid,
                "state": state,
                "reason": str(record.get("reason", "")),
            }
            burn = float(handle.probe().get("burn", 0.0))
            if state in _ADVANCE_STATES and burn < self.burn_threshold:
                result.promoted.append(name)
                continue
            result.halted = True
            result.halt_reason = (
                f"cell {name}: pipeline {state or 'TIMEOUT'}"
                if state not in _ADVANCE_STATES
                else f"cell {name}: burn {burn:.2f} >="
                f" {self.burn_threshold:.2f} after promote"
            )
            result.skipped.extend(names[i + 1 :])
            break
        return result

    def _await_terminal(self, handle, pid: str) -> dict:
        """Poll one cell's pipeline to terminal (bounded)."""
        deadline = self._clock() + self.timeout_s
        record: dict = {}
        while self._clock() < deadline:
            try:
                record = handle.client.pipeline_status(pid)
            except ControlClientError as e:
                if e.code != 0:
                    return {"state": "FAILED", "reason": e.message}
                # transport blip: the daemon may be restarting; its
                # journal will answer once rehydrated
            state = str(record.get("state", ""))
            if state in _HALTING_STATES or state in _ADVANCE_STATES:
                return record
            self._sleep(self.poll_interval_s)
        return dict(record, state=record.get("state", "") or "TIMEOUT")
