"""Multi-cell federation: SLO-burn-aware global routing with drain failover.

One control daemon owning one fleet is one failure domain. This package
puts a thin federation layer over N regional *cells* — each cell is an
ordinary ``tpx control`` daemon (plus its fleet and serve pool) made
cell-addressable by PR 19's ``--cell`` identity — whose headline
property is **graceful degradation under cell loss**: a drained,
partitioned, or killed cell costs latency, never requests.

The pieces:

- :class:`~torchx_tpu.federation.cells.CellRegistry` — the durable
  address book (``$TPX_FEDERATION_DIR/cells.jsonl``), journaled with the
  same append-only idiom as every other tpx store. Lifecycle state lives
  in each cell's daemon (durable across its restarts), not here — the
  registry only answers *where the cells are*.
- :class:`~torchx_tpu.federation.cells.CellHandle` — one cell's client +
  per-cell :class:`~torchx_tpu.resilience.breaker.CircuitBreaker` +
  cached health/burn probe.
- :class:`~torchx_tpu.federation.router.FederationRouter` — scores cells
  by SLO burn rate (each daemon's ``/v1/alerts`` long-window burns) and
  prefix-cache affinity (PR 12's positional digest chains, exported
  cross-cell), dispatches to the best admissible cell, and spills to the
  next-best on drain/overload/unreachability with capped jittered
  backoff. Not-yet-rehydrated cells count as drained; a cell over its
  burn budget is demoted, not excluded.
- :class:`~torchx_tpu.federation.promote.FederationPromoter` — rolls a
  train→eval→promote pipeline region by region, halting the wave the
  moment any cell rolls back or exceeds the burn threshold.
- :class:`~torchx_tpu.federation.sim.FederationSimHarness` — the
  two-cell drain/kill scenario replayed deterministically in virtual
  time (``tpx sim run --scenario federation-two-cell``), driving the
  *production* router.

Cell lifecycle: ``HEALTHY → DRAINING → DRAINED → UNCORDONED`` (uncordon
returns the cell to HEALTHY; the UNCORDONED label is the transitional
acknowledgment). ``tpx cell`` drives it from the CLI.
"""

from torchx_tpu.federation.cells import (
    CellHandle,
    CellRegistry,
    CellSpec,
    DRAINED,
    DRAINING,
    HEALTHY,
    LIFECYCLE,
    UNCORDONED,
    federation_dir,
)
from torchx_tpu.federation.promote import FederationPromoter, WaveResult
from torchx_tpu.federation.router import FederationError, FederationRouter

__all__ = [
    "HEALTHY",
    "DRAINING",
    "DRAINED",
    "UNCORDONED",
    "LIFECYCLE",
    "CellSpec",
    "CellHandle",
    "CellRegistry",
    "FederationError",
    "FederationRouter",
    "FederationPromoter",
    "WaveResult",
    "federation_dir",
]
