"""Event-driven pipeline engine over the control plane's watch stream.

:class:`PipelineEngine` advances :class:`~torchx_tpu.pipelines.dag.PipelineSpec`
DAGs off :meth:`Reconciler.subscribe <torchx_tpu.control.reconciler.Reconciler.subscribe>`
watch events — no stage is ever polled. A stage submission returns
immediately; the terminal :class:`~torchx_tpu.control.events.StateEvent`
for its app is what harvests the artifact (checkpoint manifest for train
stages, score record for eval stages), applies the eval gate, and submits
the next generation.

Durability follows the fleet journal's contract exactly (it *is* the same
:class:`~torchx_tpu.fleet.queue.FleetJournal` class): every decision —
submit, stage submit, stage completion, gate verdict, each canary
replica rolled, rollback, promotion, terminal pipeline state — is an
fsync'd JSONL line written *before* the action it records is considered
done. :meth:`rehydrate` replays that journal after a daemon restart:
completed stages never re-run, running stages are re-attached to their
watch streams, and a pipeline killed mid-canary resumes its promotion
with the already-rolled replica set instead of re-rolling.

The engine is deliberately daemon-agnostic: submission goes through an
injected *executor* (``submit``/``resolve``/``cancel`` duck type — the
daemon's wires stages through the fleet scheduler with per-kind priority
classes), the serve pool for promote stages comes from an injectable
``pool_provider``, and the SLO burn signal is a plain callable.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from torchx_tpu.fleet.queue import FleetJournal
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.pipelines.dag import (
    Artifact,
    PipelineSpec,
    PipelineStage,
    checkpoint_artifact,
    resolve_args,
    score_artifact,
)
from torchx_tpu.pipelines.promote import PROMOTED, PromotionController

__all__ = [
    "PIPELINE_STATES",
    "STAGE_STATES",
    "StageRun",
    "PipelineRun",
    "PipelineEngine",
]

logger = logging.getLogger(__name__)

#: pipeline lifecycle states (terminal: PROMOTED, SUCCEEDED, FAILED,
#: ROLLED_BACK, CANCELLED).
PIPELINE_STATES = (
    "PENDING",
    "RUNNING",
    "CANARY",
    "PROMOTED",
    "SUCCEEDED",
    "FAILED",
    "ROLLED_BACK",
    "CANCELLED",
)

_TERMINAL = {"PROMOTED", "SUCCEEDED", "FAILED", "ROLLED_BACK", "CANCELLED"}

#: per-stage states.
STAGE_STATES = (
    "PENDING",
    "QUEUED",
    "RUNNING",
    "SUCCEEDED",
    "FAILED",
    "CANCELLED",
    "ROLLED_BACK",
)


@dataclass
class StageRun:
    """One stage's runtime record inside a :class:`PipelineRun`."""

    stage: PipelineStage
    state: str = "PENDING"
    handle: str = ""
    scheduler: str = ""
    app_id: str = ""
    fleet_job: str = ""
    error: str = ""
    artifact: Optional[Artifact] = None
    started_usec: int = 0
    finished_usec: int = 0

    def to_dict(self) -> dict:
        """Status-payload form (spec fields + runtime state)."""
        return {
            "name": self.stage.name,
            "kind": self.stage.kind,
            "state": self.state,
            "handle": self.handle,
            "fleet_job": self.fleet_job,
            "error": self.error,
            "artifact": self.artifact.to_dict() if self.artifact else None,
        }


@dataclass
class PipelineRun:
    """One submitted pipeline: its spec, per-stage runs, and lifecycle."""

    pid: str
    spec: PipelineSpec
    tenant: str = ""
    state: str = "PENDING"
    stages: dict[str, StageRun] = field(default_factory=dict)
    #: replica ids rolled by this run's promotion attempt(s) — journaled,
    #: so a restart resumes the canary instead of re-rolling.
    rolled: set[int] = field(default_factory=set)
    reason: str = ""

    @property
    def terminal(self) -> bool:
        """True once the run reached a terminal lifecycle state."""
        return self.state in _TERMINAL

    def to_dict(self) -> dict:
        """Status-payload form."""
        return {
            "pipeline": self.pid,
            "name": self.spec.name,
            "tenant": self.tenant,
            "state": self.state,
            "reason": self.reason,
            "rolled": sorted(self.rolled),
            "stages": [
                self.stages[s.name].to_dict() for s in self.spec.stages
            ],
        }


class PipelineEngine:
    """The DAG engine: journal-backed, watch-event-driven, restartable.

    Args:
        journal_path: fsync'd JSONL decision journal (same durability
            class as the fleet queue journal).
        executor: stage submitter — ``submit(tenant, pid, stage, args)
            -> {"handle": ...}`` or ``{"queued": True, "fleet_job":
            ...}``; optional ``resolve(fleet_job) -> handle`` and
            ``cancel(handle)``. Bind later with :meth:`bind`.
        reconciler: optional; lets :meth:`rehydrate` recover terminal
            events recorded while the daemon was down.
        slo_signal: current worst SLO burn rate (promotion burn gate).
        pool_provider: ``pool_provider(stage) -> ServePool | None`` —
            where a promote stage finds the serve pool to roll.
        clock: injectable wall clock (stage start/finish stamps and the
            promotion controller's observation window run on it — the sim
            harness passes a :class:`~torchx_tpu.sim.clock.VirtualClock`).
        sleep: injectable sleep, paired with ``clock`` (promotion canary
            observation windows).
    """

    def __init__(
        self,
        journal_path: str,
        executor: Optional[Any] = None,
        *,
        reconciler: Optional[Any] = None,
        slo_signal: Optional[Callable[[], Optional[float]]] = None,
        pool_provider: Optional[Callable[[PipelineStage], Any]] = None,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self._journal = FleetJournal(journal_path)
        self._executor = executor
        self._reconciler = reconciler
        self._slo_signal = slo_signal
        self._pool_provider = pool_provider
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.RLock()
        self._runs: dict[str, PipelineRun] = {}
        self._handles: dict[tuple[str, str], tuple[str, str]] = {}
        self._seq = 0
        self._incumbent: Optional[dict] = None
        self._threads: list[threading.Thread] = []
        self._closed = False

    # -- wiring ------------------------------------------------------------

    def bind(self, executor: Any) -> None:
        """Attach (or replace) the stage executor."""
        with self._lock:
            self._executor = executor

    def set_slo_signal(self, signal: Callable[[], Optional[float]]) -> None:
        """Attach the burn-rate feed used by promotion gates."""
        with self._lock:
            self._slo_signal = signal

    def set_pool_provider(
        self, provider: Callable[[PipelineStage], Any]
    ) -> None:
        """Attach the serve-pool lookup used by promote stages."""
        with self._lock:
            self._pool_provider = provider

    @property
    def incumbent(self) -> Optional[dict]:
        """The last promoted checkpoint (``ckpt``/``digest``/``step``/
        ``score``) — the baseline the next candidate is gated against."""
        with self._lock:
            return dict(self._incumbent) if self._incumbent else None

    def active_threads(self) -> list[threading.Thread]:
        """Promotion threads started by this engine (live and dead). The
        sim harness waits on these between virtual-time steps so canary
        outcomes land deterministically."""
        with self._lock:
            return list(self._threads)

    def close(self) -> None:
        """Stop accepting work and give in-flight promotion threads a
        moment to reach their next journal point."""
        with self._lock:
            self._closed = True
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)

    # -- submission --------------------------------------------------------

    def submit(self, spec: PipelineSpec, tenant: str = "") -> str:
        """Validate, journal, and start a pipeline; returns its id."""
        spec.validate()
        with self._lock:
            if self._closed:
                raise RuntimeError("pipeline engine is closed")
            self._seq += 1
            pid = f"pl_{self._seq}"
            self._journal.append(
                "submit", pipeline=pid, tenant=tenant, spec=spec.to_dict()
            )
            run = PipelineRun(pid=pid, spec=spec, tenant=tenant)
            run.stages = {s.name: StageRun(stage=s) for s in spec.stages}
            self._runs[pid] = run
            obs_metrics.PIPELINE_ACTIVE.set(self._active_count())
            with obs_trace.span(
                "pipeline.submit", pipeline=pid, spec=spec.name
            ):
                self._advance(run)
        return pid

    def cancel(self, pid: str) -> dict:
        """Cancel a pipeline: running stage apps are cancelled on their
        backends, the decision is journaled, the state goes CANCELLED."""
        with self._lock:
            run = self._runs.get(pid)
            if run is None:
                raise KeyError(f"unknown pipeline {pid!r}")
            if run.terminal:
                return run.to_dict()
            for srun in run.stages.values():
                if srun.state in ("QUEUED", "RUNNING") and srun.handle:
                    self._cancel_handle(srun.handle)
                if srun.state in ("PENDING", "QUEUED", "RUNNING"):
                    srun.state = "CANCELLED"
            self._set_state(run, "CANCELLED", reason="cancelled by client")
            return run.to_dict()

    def status(self, pid: Optional[str] = None) -> dict:
        """One pipeline's full record, or a summary of all of them."""
        with self._lock:
            if pid is not None:
                run = self._runs.get(pid)
                if run is None:
                    raise KeyError(f"unknown pipeline {pid!r}")
                doc = run.to_dict()
                doc["incumbent"] = (
                    dict(self._incumbent) if self._incumbent else None
                )
                return doc
            return {
                "pipelines": [
                    self._runs[k].to_dict() for k in sorted(self._runs)
                ],
                "incumbent": dict(self._incumbent) if self._incumbent else None,
            }

    # -- the event path ----------------------------------------------------

    def on_event(self, event: Any) -> None:
        """Reconciler subscriber: advance DAGs off watch events.

        Exceptions never propagate past here by the reconciler's
        subscriber contract, but the engine still catches per-run errors
        so one poisoned pipeline cannot stall the rest.
        """
        with self._lock:
            if self._closed:
                return
            self._resolve_queued()
            key = (str(event.scheduler), str(event.app_id))
            owner = self._handles.get(key)
            if owner is None:
                return
            pid, stage_name = owner
            run = self._runs.get(pid)
            if run is None or run.terminal:
                return
            srun = run.stages[stage_name]
            state_name = getattr(event.state, "name", str(event.state))
            if state_name == "SUCCEEDED":
                self._handles.pop(key, None)
                self._complete_stage(run, srun)
            elif state_name in ("FAILED", "CANCELLED", "UNKNOWN"):
                self._handles.pop(key, None)
                self._finish_stage(
                    run,
                    srun,
                    "CANCELLED" if state_name == "CANCELLED" else "FAILED",
                    error=f"stage app reached {state_name}",
                )
                self._fail(run, f"stage {srun.stage.name} {state_name}")

    def _resolve_queued(self) -> None:
        """Fleet-queued stages get their handle once the market places the
        gang; resolution is lazy, on every event tick."""
        if self._executor is None or not hasattr(self._executor, "resolve"):
            return
        for run in self._runs.values():
            if run.terminal:
                continue
            for srun in run.stages.values():
                if srun.state != "QUEUED" or not srun.fleet_job:
                    continue
                try:
                    handle = self._executor.resolve(srun.fleet_job)
                except Exception as e:  # noqa: BLE001 - keep the queue state
                    logger.debug("resolve %s failed: %s", srun.fleet_job, e)
                    continue
                if handle:
                    self._record_handle(run, srun, str(handle))

    # -- stage mechanics ---------------------------------------------------

    def _advance(self, run: PipelineRun) -> None:
        """Submit every stage whose dependencies are all satisfied; called
        with the lock held, idempotent, re-entrant-safe."""
        if run.terminal:
            return
        for stage in run.spec.stages:
            srun = run.stages[stage.name]
            if srun.state != "PENDING":
                continue
            deps = [run.stages[d] for d in stage.depends_on]
            if any(d.state in ("FAILED", "CANCELLED", "ROLLED_BACK") for d in deps):
                continue
            if not all(d.state == "SUCCEEDED" for d in deps):
                continue
            if stage.kind == "promote":
                self._start_promotion(run, srun)
            else:
                self._submit_stage(run, srun)
        if run.state == "PENDING" and any(
            s.state in ("QUEUED", "RUNNING") for s in run.stages.values()
        ):
            self._set_state(run, "RUNNING", terminal_metric=False)
        if not run.terminal and all(
            s.state == "SUCCEEDED" for s in run.stages.values()
        ):
            # a DAG without a promote stage still has a clean terminal
            self._set_state(run, "SUCCEEDED", reason="all stages succeeded")

    def _submit_stage(self, run: PipelineRun, srun: StageRun) -> None:
        if self._executor is None:
            raise RuntimeError("pipeline engine has no executor bound")
        stage = srun.stage
        artifacts = {
            name: sr.artifact
            for name, sr in run.stages.items()
            if sr.artifact is not None
        }
        try:
            args = resolve_args(stage.args, artifacts)
            result = self._executor.submit(
                run.tenant, run.pid, stage, args
            )
        except Exception as e:  # noqa: BLE001 - a bad stage fails its run
            srun.state = "FAILED"
            srun.error = f"{type(e).__name__}: {e}"
            self._journal.append(
                "stage_done",
                pipeline=run.pid,
                stage=stage.name,
                state="FAILED",
                error=srun.error,
            )
            obs_metrics.PIPELINE_STAGES.inc(kind=stage.kind, state="FAILED")
            self._fail(run, f"stage {stage.name} submit failed: {srun.error}")
            return
        srun.started_usec = int(self._clock() * 1e6)
        if result.get("handle"):
            self._record_handle(run, srun, str(result["handle"]))
        else:
            srun.state = "QUEUED"
            srun.fleet_job = str(result.get("fleet_job", ""))
            self._journal.append(
                "stage_submit",
                pipeline=run.pid,
                stage=stage.name,
                fleet_job=srun.fleet_job,
                handle="",
            )

    def _record_handle(
        self, run: PipelineRun, srun: StageRun, handle: str
    ) -> None:
        from torchx_tpu.specs.api import parse_app_handle

        scheduler, _, app_id = parse_app_handle(handle)
        srun.state = "RUNNING"
        srun.handle = handle
        srun.scheduler = scheduler
        srun.app_id = app_id
        if not srun.started_usec:
            srun.started_usec = int(self._clock() * 1e6)
        self._handles[(scheduler, app_id)] = (run.pid, srun.stage.name)
        self._journal.append(
            "stage_submit",
            pipeline=run.pid,
            stage=srun.stage.name,
            handle=handle,
            scheduler=scheduler,
            app_id=app_id,
            fleet_job=srun.fleet_job,
        )
        obs_metrics.PIPELINE_STAGES.inc(kind=srun.stage.kind, state="RUNNING")

    def _complete_stage(self, run: PipelineRun, srun: StageRun) -> None:
        """A stage's app succeeded: harvest its artifact, apply the eval
        gate, journal, and advance the DAG."""
        stage = srun.stage
        try:
            if stage.kind == "train" and stage.ckpt_dir:
                srun.artifact = checkpoint_artifact(stage.ckpt_dir)
            elif stage.kind == "eval":
                srun.artifact = score_artifact(stage.score_file)
        except ValueError as e:
            self._finish_stage(run, srun, "FAILED", error=str(e))
            self._fail(run, f"stage {stage.name}: {e}")
            return
        if stage.kind == "eval" and stage.threshold is not None:
            score = srun.artifact.score if srun.artifact else None
            passed = score is not None and score >= stage.threshold
            self._journal.append(
                "gate",
                pipeline=run.pid,
                stage=stage.name,
                passed=passed,
                score=score,
                threshold=stage.threshold,
            )
            obs_metrics.PIPELINE_GATES.inc(
                decision="pass" if passed else "fail"
            )
            if not passed:
                self._finish_stage(
                    run,
                    srun,
                    "FAILED",
                    error=(
                        f"eval gate failed: score {score} <"
                        f" threshold {stage.threshold}"
                    ),
                    artifact=srun.artifact,
                )
                self._fail(run, f"eval gate failed at stage {stage.name}")
                return
        self._finish_stage(run, srun, "SUCCEEDED", artifact=srun.artifact)
        self._advance(run)

    def _finish_stage(
        self,
        run: PipelineRun,
        srun: StageRun,
        state: str,
        error: str = "",
        artifact: Optional[Artifact] = None,
    ) -> None:
        srun.state = state
        srun.error = error
        srun.finished_usec = int(self._clock() * 1e6)
        self._journal.append(
            "stage_done",
            pipeline=run.pid,
            stage=srun.stage.name,
            state=state,
            error=error,
            artifact=artifact.to_dict() if artifact else None,
        )
        obs_metrics.PIPELINE_STAGES.inc(kind=srun.stage.kind, state=state)
        if srun.started_usec:
            obs_metrics.PIPELINE_STAGE_SECONDS.observe(
                max(0.0, (srun.finished_usec - srun.started_usec) / 1e6),
                kind=srun.stage.kind,
            )

    def _fail(self, run: PipelineRun, reason: str) -> None:
        if run.terminal:
            return
        for srun in run.stages.values():
            if srun.state in ("QUEUED", "RUNNING") and srun.handle:
                self._cancel_handle(srun.handle)
                srun.state = "CANCELLED"
        self._set_state(run, "FAILED", reason=reason)

    def _cancel_handle(self, handle: str) -> None:
        if self._executor is None or not hasattr(self._executor, "cancel"):
            return
        try:
            self._executor.cancel(handle)
        except Exception as e:  # noqa: BLE001 - cancel is best-effort
            logger.debug("cancel of %s failed: %s", handle, e)

    def _set_state(
        self,
        run: PipelineRun,
        state: str,
        reason: str = "",
        terminal_metric: bool = True,
    ) -> None:
        run.state = state
        if reason:
            run.reason = reason
        self._journal.append(
            "pipeline_state", pipeline=run.pid, state=state, reason=reason
        )
        if run.terminal and terminal_metric:
            obs_metrics.PIPELINE_RUNS.inc(state=state)
        obs_metrics.PIPELINE_ACTIVE.set(self._active_count())

    def _active_count(self) -> int:
        return sum(1 for r in self._runs.values() if not r.terminal)

    # -- promotion ---------------------------------------------------------

    def _start_promotion(self, run: PipelineRun, srun: StageRun) -> None:
        srun.state = "RUNNING"
        srun.started_usec = int(self._clock() * 1e6)
        self._journal.append(
            "stage_submit",
            pipeline=run.pid,
            stage=srun.stage.name,
            handle="",
            promote=True,
        )
        obs_metrics.PIPELINE_STAGES.inc(kind="promote", state="RUNNING")
        self._set_state(run, "CANARY", terminal_metric=False)
        t = threading.Thread(
            target=self._run_promotion,
            args=(run, srun),
            daemon=True,
            name=f"tpx-promote-{run.pid}",
        )
        self._threads.append(t)
        t.start()

    def _dependency_closure(
        self, run: PipelineRun, stage: PipelineStage
    ) -> list[StageRun]:
        out, seen, frontier = [], set(), list(stage.depends_on)
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            srun = run.stages[name]
            out.append(srun)
            frontier.extend(srun.stage.depends_on)
        return out

    def _run_promotion(self, run: PipelineRun, srun: StageRun) -> None:
        stage = srun.stage
        with self._lock:
            closure = self._dependency_closure(run, stage)
            candidate = next(
                (
                    s.artifact
                    for s in closure
                    if s.artifact is not None and s.artifact.kind == "checkpoint"
                ),
                None,
            )
            score_art = next(
                (
                    s.artifact
                    for s in closure
                    if s.artifact is not None and s.artifact.kind == "score"
                ),
                None,
            )
            wants_baseline = any(
                s.stage.kind == "eval" and s.stage.baseline == "incumbent"
                for s in closure
            )
            incumbent = dict(self._incumbent) if self._incumbent else None
            rolled = set(run.rolled)
        if candidate is None:
            with self._lock:
                self._finish_stage(
                    run,
                    srun,
                    "FAILED",
                    error="promote stage has no upstream checkpoint artifact",
                )
                self._fail(run, f"stage {stage.name}: no checkpoint to promote")
            return
        score = score_art.score if score_art is not None else None
        baseline = (
            incumbent.get("score")
            if wants_baseline and incumbent is not None
            else None
        )
        pool = None
        if self._pool_provider is not None:
            try:
                pool = self._pool_provider(stage)
            except Exception as e:  # noqa: BLE001 - degrade to gate-only
                logger.warning("pool provider failed for %s: %s", stage.name, e)

        def journal(event: str, **fields: Any) -> None:
            with self._lock:
                self._journal.append(
                    "promote_step",
                    pipeline=run.pid,
                    stage=stage.name,
                    event=event,
                    **fields,
                )
                if event == "replica_rolled" and fields.get("why") in (
                    "canary",
                    "promote",
                ):
                    run.rolled.add(int(fields["replica"]))
                elif event == "rollback":
                    obs_metrics.PIPELINE_ROLLBACKS.inc(
                        reason=str(fields.get("reason", ""))
                    )
                elif event == "gate":
                    obs_metrics.PIPELINE_GATES.inc(
                        decision="pass" if fields.get("passed") else "fail"
                    )

        controller = PromotionController(
            pool,
            slo_signal=self._slo_signal,
            canary_fraction=stage.canary_fraction,
            burn_threshold=stage.burn_threshold,
            observe_s=stage.observe_s,
            # bound the observe window to ~200 burn samples so long
            # windows (hours of virtual time in the simulator) don't
            # degenerate into tens of thousands of poll wakeups
            poll_s=max(0.05, stage.observe_s / 200.0),
            journal=journal,
            already_rolled=rolled,
            clock=self._clock,
            sleep=self._sleep,
        )
        with obs_trace.span(
            "pipeline.promote", pipeline=run.pid, stage=stage.name
        ):
            try:
                result = controller.run(
                    candidate,
                    score=score,
                    baseline_score=baseline,
                    incumbent_ckpt=incumbent.get("ckpt", "") if incumbent else "",
                )
            except Exception as e:  # noqa: BLE001 - a dead canary rolls back
                logger.exception("promotion crashed for %s", run.pid)
                with self._lock:
                    self._finish_stage(run, srun, "FAILED", error=str(e))
                    self._fail(run, f"promotion crashed: {e}")
                return
        with self._lock:
            if run.terminal:
                return
            if result == PROMOTED:
                self._finish_stage(run, srun, "SUCCEEDED", artifact=candidate)
                self._incumbent = {
                    "ckpt": candidate.path,
                    "digest": candidate.digest,
                    "step": candidate.step,
                    "score": score,
                }
                self._journal.append(
                    "incumbent", pipeline=run.pid, **self._incumbent
                )
                self._set_state(run, "PROMOTED", reason="canary gates passed")
            else:
                self._finish_stage(
                    run, srun, "ROLLED_BACK", error="canary gate rolled back"
                )
                self._set_state(
                    run, "ROLLED_BACK", reason="canary gate rolled back"
                )

    # -- rehydration -------------------------------------------------------

    def rehydrate(self) -> list[dict]:
        """Replay the journal after a restart.

        Rebuilds every run, re-maps running stage handles, restores the
        incumbent baseline and the pipeline-id sequence, recovers stage
        completions that landed in the reconciler's store while the
        daemon was down, and resumes mid-canary promotions with their
        journaled already-rolled replica set. Returns the handles the
        caller must re-attach to watch streams:
        ``[{"handle", "scheduler", "app_id", "tenant"}, ...]``.
        """
        with self._lock:
            for entry in self._journal.entries():
                try:
                    self._replay(entry)
                except Exception as e:  # noqa: BLE001 - skip poison entries
                    logger.warning(
                        "pipeline journal replay skipped %r: %s",
                        entry.get("kind"),
                        e,
                    )
            retrack = []
            for run in self._runs.values():
                if run.terminal:
                    continue
                for srun in run.stages.values():
                    if srun.state in ("QUEUED", "RUNNING") and srun.handle:
                        retrack.append(
                            {
                                "handle": srun.handle,
                                "scheduler": srun.scheduler,
                                "app_id": srun.app_id,
                                "tenant": run.tenant,
                            }
                        )
            obs_metrics.PIPELINE_ACTIVE.set(self._active_count())
            # completions recorded while we were down: the store already
            # holds the terminal event, the watch stream won't repeat it
            if self._reconciler is not None:
                for item in list(retrack):
                    event = self._reconciler.latest(
                        item["scheduler"], item["app_id"]
                    )
                    if event is not None and getattr(event, "terminal", False):
                        self.on_event(event)
            for run in list(self._runs.values()):
                if run.terminal:
                    continue
                promote = next(
                    (
                        s
                        for s in run.stages.values()
                        if s.stage.kind == "promote" and s.state == "RUNNING"
                    ),
                    None,
                )
                if promote is not None:
                    logger.info(
                        "resuming mid-canary promotion of %s (rolled=%s)",
                        run.pid,
                        sorted(run.rolled),
                    )
                    t = threading.Thread(
                        target=self._run_promotion,
                        args=(run, promote),
                        daemon=True,
                        name=f"tpx-promote-{run.pid}",
                    )
                    self._threads.append(t)
                    t.start()
                else:
                    self._advance(run)
            return retrack

    def _replay(self, entry: dict) -> None:
        kind = entry.get("kind")
        pid = str(entry.get("pipeline", ""))
        if kind == "submit":
            spec = PipelineSpec.from_dict(entry.get("spec") or {})
            run = PipelineRun(
                pid=pid, spec=spec, tenant=str(entry.get("tenant", ""))
            )
            run.stages = {s.name: StageRun(stage=s) for s in spec.stages}
            self._runs[pid] = run
            try:
                self._seq = max(self._seq, int(pid.split("_", 1)[1]))
            except (IndexError, ValueError):
                pass
            return
        run = self._runs.get(pid)
        if run is None:
            return
        if kind == "stage_submit":
            srun = run.stages.get(str(entry.get("stage", "")))
            if srun is None:
                return
            handle = str(entry.get("handle", ""))
            srun.fleet_job = str(entry.get("fleet_job", "") or srun.fleet_job)
            if handle:
                from torchx_tpu.specs.api import parse_app_handle

                scheduler, _, app_id = parse_app_handle(handle)
                srun.state = "RUNNING"
                srun.handle = handle
                srun.scheduler = scheduler
                srun.app_id = app_id
                self._handles[(scheduler, app_id)] = (pid, srun.stage.name)
            elif entry.get("promote"):
                srun.state = "RUNNING"
                run.state = "CANARY"
            else:
                srun.state = "QUEUED"
        elif kind == "stage_done":
            srun = run.stages.get(str(entry.get("stage", "")))
            if srun is None:
                return
            srun.state = str(entry.get("state", "FAILED"))
            srun.error = str(entry.get("error", "") or "")
            if entry.get("artifact"):
                srun.artifact = Artifact.from_dict(entry["artifact"])
            if srun.handle:
                self._handles.pop((srun.scheduler, srun.app_id), None)
        elif kind == "promote_step":
            if entry.get("event") == "replica_rolled" and entry.get(
                "why"
            ) in ("canary", "promote"):
                run.rolled.add(int(entry.get("replica", -1)))
        elif kind == "pipeline_state":
            run.state = str(entry.get("state", run.state))
            run.reason = str(entry.get("reason", "") or run.reason)
        elif kind == "incumbent":
            self._incumbent = {
                "ckpt": str(entry.get("ckpt", "")),
                "digest": str(entry.get("digest", "")),
                "step": int(entry.get("step", -1)),
                "score": entry.get("score"),
            }
