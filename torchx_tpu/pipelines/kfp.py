"""Kubeflow Pipelines adapter: Pipeline DAG -> Argo Workflow spec.

The reference's pipelines namespace promises provider adapters without
shipping one (torchx/pipelines/__init__.py:1-14); this module delivers the
KFP path for the TPU build: each stage's AppDef role becomes an Argo
Workflow template (container + TPU resource limits + node selectors,
reusing the GKE scheduler's pod materialization), and the DAG wires
dependencies. The result is a plain dict — submit it with `argo submit`,
the Argo REST API, or mount it into a KFP v2 pipeline; no kfp package is
required to materialize it.

Multi-host TPU stages inside a linear workflow engine: Argo steps are
single pods, so a stage whose role needs a multi-host slice is emitted as
a ``resource`` template creating the same JobSet the GKE scheduler would
submit, with success/failure conditions watching the JobSet status.
"""

from __future__ import annotations

import json
from typing import Any

from torchx_tpu.pipelines.api import Pipeline, topo_order
from torchx_tpu.schedulers.gke_scheduler import (
    app_to_jobset,
    role_to_pod_template,
    sanitize_name,
)
from torchx_tpu.specs.api import AppDef


def _stage_template(name: str, app: AppDef, namespace: str) -> dict[str, Any]:
    role = app.roles[0]
    multi_host = (
        (role.resource.tpu is not None and role.resource.tpu.hosts > 1)
        or len(app.roles) > 1
        or role.num_replicas > 1
    )
    if multi_host:
        jobset = app_to_jobset(
            app,
            # same 40-char budget as GKEScheduler._submit_dryrun: leaves
            # room in the 63-char pod-name cap for the role name plus
            # job/pod index suffixes
            app_name=sanitize_name(f"{name}-{app.name}", max_len=40),
            namespace=namespace,
            queue=None,
            service_account=None,
        )
        return {
            "name": name,
            "resource": {
                "action": "create",
                "setOwnerReference": True,
                "successCondition": "status.terminalState == Completed",
                "failureCondition": "status.terminalState == Failed",
                # Argo's resource.manifest field is a string (YAML/JSON)
                "manifest": json.dumps(jobset, indent=2),
            },
        }
    pod = role_to_pod_template(
        role,
        app_name=sanitize_name(app.name),
        coordinator_host="localhost",
        coordinator_port=8476,
        service_account=None,
    )
    return {
        "name": name,
        "container": pod["spec"]["containers"][0],
        "metadata": pod["metadata"],
        "nodeSelector": pod["spec"].get("nodeSelector", {}),
        "tolerations": pod["spec"].get("tolerations", []),
        "volumes": pod["spec"].get("volumes", []),
    }


def pipeline_to_workflow(
    pipeline: Pipeline, namespace: str = "default"
) -> dict[str, Any]:
    """-> Argo Workflow resource dict implementing the DAG."""
    topo_order(pipeline)  # validates names/cycles
    # sanitize each stage name once and reuse the result so template/task/
    # dependency refs all carry the identical string
    names = {s.name: sanitize_name(s.name) for s in pipeline.stages}
    templates = [
        _stage_template(names[s.name], s.app, namespace) for s in pipeline.stages
    ]
    dag_tasks = [
        {
            "name": names[s.name],
            "template": names[s.name],
            "dependencies": [names[d] for d in s.depends_on],
        }
        for s in pipeline.stages
    ]
    return {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": {
            "generateName": f"{sanitize_name(pipeline.name)}-",
            "namespace": namespace,
        },
        "spec": {
            "entrypoint": "dag",
            "templates": [
                {"name": "dag", "dag": {"tasks": dag_tasks}},
                *templates,
            ],
        },
    }
