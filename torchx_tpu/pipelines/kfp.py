"""Deprecated shim: moved to :mod:`torchx_tpu.pipelines.legacy`.

The DAG engine (:mod:`torchx_tpu.pipelines.engine`) owns the pipelines
namespace now; the KFP/Argo workflow materializer lives on unchanged in
``legacy`` and stays importable from here.
"""

from torchx_tpu.deprecations import deprecated_module
from torchx_tpu.pipelines.legacy import pipeline_to_workflow  # noqa: F401

deprecated_module(__name__, replacement="torchx_tpu.pipelines.legacy")
