"""Canary promotion: roll a verified checkpoint into the serve pool, or back.

:class:`PromotionController` is the in-process actuator behind a
``promote`` stage. It drives the serve pool's per-replica rollout seam
(:meth:`torchx_tpu.serve.pool.ServePool.rollout_replica`: drain →
restart on the new ``--ckpt`` → health-confirm) over a canary fraction of
replicas, weights the :class:`~torchx_tpu.serve.pool.LeastLoadedRouter`'s
traffic split toward the canary cohort, watches the SLO engine's
burn-rate signal for an observation window, and then either promotes to
100% or rolls the canaries back onto the incumbent checkpoint.

Two gates, both journaled through the engine's fsync'd pipeline journal:

* **eval-score regression** — the candidate's eval score fell below the
  incumbent's recorded baseline;
* **SLO burn** — the worst burn rate sampled during the canary window
  reached the stage's ``burn_threshold``.

Either one triggers automatic rollback; neither firing promotes. With no
serve pool attached (a daemon running without serving, e.g. the tier-1
smoke) the controller degrades to the score+burn gates alone — exactly
the condition the analyzer's TPX603 rule warns about when the *metrics*
half is also missing.

Every side effect is reported through the injected ``journal`` callback
*before* the next one is taken, so a daemon killed mid-canary resumes
from the journal with the ``already_rolled`` replica set instead of
re-rolling (or orphaning) replicas.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Callable, Optional, Sequence

from torchx_tpu.pipelines.dag import Artifact

__all__ = ["PromotionController"]

logger = logging.getLogger(__name__)

#: promotion outcomes returned by :meth:`PromotionController.run`.
PROMOTED = "promoted"
ROLLED_BACK = "rolled_back"


class PromotionController:
    """One promote stage's canary rollout, gate and rollback policy.

    Args:
        pool: a :class:`~torchx_tpu.serve.pool.ServePool` (or anything
            with ``replicas``/``router``/``rollout_replica``); None
            degrades to gate-only promotion (no replicas to roll).
        slo_signal: callable returning the current worst SLO burn rate
            (e.g. ``daemon.slo_engine.max_burn``); None skips the burn
            gate.
        canary_fraction: fraction of the pool rolled before the gate.
        canary_weight: router weight applied to canary replicas during
            the observation window (restored to 1.0 afterwards).
        burn_threshold: burn rate at/above which the canary rolls back.
        observe_s: seconds to watch ``slo_signal`` after the canary is up.
        poll_s: burn-signal sampling interval inside the window.
        journal: ``journal(event, **fields)`` callback; every decision is
            journaled before the action that follows it.
        already_rolled: replica ids a previous attempt already rolled
            (rehydration after a daemon restart) — they are not re-rolled
            but still counted as canaries for rollback.
        clock/sleep: injectable time sources for deterministic tests.
    """

    def __init__(
        self,
        pool: Optional[Any] = None,
        *,
        slo_signal: Optional[Callable[[], Optional[float]]] = None,
        canary_fraction: float = 0.25,
        canary_weight: float = 1.0,
        burn_threshold: float = 1.0,
        observe_s: float = 0.0,
        poll_s: float = 0.05,
        journal: Optional[Callable[..., None]] = None,
        already_rolled: Optional[Sequence[int]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self._pool = pool
        self._slo_signal = slo_signal
        self._canary_fraction = max(0.0, min(1.0, canary_fraction))
        self._canary_weight = canary_weight
        self._burn_threshold = burn_threshold
        self._observe_s = observe_s
        self._poll_s = max(1e-3, poll_s)
        self._journal = journal or (lambda event, **fields: None)
        self.already_rolled = set(already_rolled or ())
        self._clock = clock
        self._sleep = sleep

    # -- helpers -----------------------------------------------------------

    def _replica_ids(self) -> list[int]:
        if self._pool is None:
            return []
        return list(range(int(self._pool.replicas)))

    def _router(self) -> Optional[Any]:
        return getattr(self._pool, "router", None)

    def _roll(self, rid: int, ckpt: str, reason: str) -> bool:
        """One replica through the pool's drain→restart→confirm seam; the
        journal entry lands only after the replica is confirmed healthy."""
        ok = bool(self._pool.rollout_replica(rid, ckpt))
        if ok:
            self._journal("replica_rolled", replica=rid, ckpt=ckpt, why=reason)
            self.already_rolled.add(rid)
        return ok

    def _observe_burn(self) -> float:
        """Worst burn rate over the observation window (early exit the
        moment the threshold is reached — no point burning longer)."""
        worst = 0.0
        if self._slo_signal is None:
            if self._observe_s > 0:
                self._sleep(self._observe_s)
            return worst
        deadline = self._clock() + self._observe_s
        while True:
            try:
                burn = self._slo_signal()
            except Exception as e:  # noqa: BLE001 - a dead signal gates nothing
                logger.debug("slo signal failed during canary: %s", e)
                burn = None
            if burn is not None:
                worst = max(worst, float(burn))
                if worst >= self._burn_threshold:
                    return worst
            if self._clock() >= deadline:
                return worst
            self._sleep(min(self._poll_s, max(0.0, deadline - self._clock())))

    # -- the promotion ----------------------------------------------------

    def run(
        self,
        candidate: Artifact,
        *,
        score: Optional[float] = None,
        baseline_score: Optional[float] = None,
        incumbent_ckpt: str = "",
    ) -> str:
        """Canary → observe → gate → promote or roll back.

        Returns ``"promoted"`` or ``"rolled_back"``. The incumbent
        checkpoint path (``incumbent_ckpt``) is what canaries are rolled
        *back* onto; empty means there is nothing to restore (first ever
        promotion) and rollback only restores router weights.
        """
        replicas = self._replica_ids()
        n_canary = (
            min(len(replicas), max(1, math.ceil(len(replicas) * self._canary_fraction)))
            if replicas
            else 0
        )
        canaries = replicas[:n_canary]
        self._journal(
            "canary_start",
            ckpt=candidate.path,
            digest=candidate.digest,
            step=candidate.step,
            canaries=canaries,
            resumed=sorted(self.already_rolled),
        )
        router = self._router()
        try:
            for rid in canaries:
                if rid in self.already_rolled:
                    continue
                if not self._roll(rid, candidate.path, "canary"):
                    self._rollback(canaries, incumbent_ckpt, "rollout_failed")
                    return ROLLED_BACK
                if router is not None and hasattr(router, "set_weight"):
                    router.set_weight(rid, self._canary_weight)

            worst_burn = self._observe_burn()
            regressed = (
                score is not None
                and baseline_score is not None
                and score < baseline_score
            )
            burned = (
                self._slo_signal is not None
                and worst_burn >= self._burn_threshold
            )
            if regressed or burned:
                reason = "eval_regression" if regressed else "slo_burn"
                self._journal(
                    "gate",
                    passed=False,
                    reason=reason,
                    score=score,
                    baseline=baseline_score,
                    burn=worst_burn,
                    burn_threshold=self._burn_threshold,
                )
                self._rollback(canaries, incumbent_ckpt, reason)
                return ROLLED_BACK

            self._journal(
                "gate",
                passed=True,
                score=score,
                baseline=baseline_score,
                burn=worst_burn,
                burn_threshold=self._burn_threshold,
            )
            for rid in replicas:
                if rid in self.already_rolled:
                    continue
                if not self._roll(rid, candidate.path, "promote"):
                    self._rollback(replicas, incumbent_ckpt, "rollout_failed")
                    return ROLLED_BACK
            self._journal("promoted", ckpt=candidate.path, digest=candidate.digest)
            return PROMOTED
        finally:
            if router is not None and hasattr(router, "set_weight"):
                for rid in replicas:
                    router.set_weight(rid, 1.0)

    def _rollback(
        self, cohort: Sequence[int], incumbent_ckpt: str, reason: str
    ) -> None:
        """Journal the rollback decision, then restore every replica this
        attempt (or a resumed prior attempt) rolled."""
        rolled = sorted(set(cohort) & self.already_rolled)
        self._journal(
            "rollback",
            reason=reason,
            replicas=rolled,
            incumbent=incumbent_ckpt,
        )
        if self._pool is None or not incumbent_ckpt:
            return
        for rid in rolled:
            try:
                self._pool.rollout_replica(rid, incumbent_ckpt)
            except Exception as e:  # noqa: BLE001 - restore the rest anyway
                logger.warning("rollback of replica %d failed: %s", rid, e)
