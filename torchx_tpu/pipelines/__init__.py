"""Train→eval→promote pipelines: DAG orchestration over the control plane.

"Jobs are not products" — this package turns the launcher's primitives
into lifecycles. A :class:`PipelineSpec` declares a DAG of typed stages
(``train`` → ``eval`` → ``promote``) whose edges carry
:class:`Artifact` records: the train stage publishes its verified
checkpoint (path + MANIFEST.json content digest + step), the eval stage
scores it (``apps/eval_main.py`` re-verifies the digest first), and the
promote stage rolls it onto a canary fraction of the serve pool, gated
by the eval score and the SLO engine's burn rate — promote to 100% or
automatic rollback.

:class:`PipelineEngine` executes the DAG event-driven off the control
daemon's reconciler watch stream (no polling), journals every decision
to fsync'd JSONL with the fleet journal's durability contract, and
rehydrates mid-pipeline — including mid-canary — after a daemon
restart. Submit through the daemon (``POST /v1/pipelines``) or the
``tpx pipeline`` CLI.

The kfp-era runners (``kfp.py``, ``local_runner.py``) are retired into
:mod:`torchx_tpu.pipelines.legacy` behind deprecation shims; the legacy
:class:`Pipeline`/:class:`Stage` builder model they consume remains in
:mod:`torchx_tpu.pipelines.api`.
"""

from torchx_tpu.pipelines.api import Pipeline, Stage, topo_order  # noqa: F401
from torchx_tpu.pipelines.dag import (  # noqa: F401
    ROLE_METADATA_KEY,
    STAGE_KINDS,
    Artifact,
    PipelineSpec,
    PipelineStage,
    checkpoint_artifact,
    resolve_args,
    score_artifact,
)
from torchx_tpu.pipelines.engine import (  # noqa: F401
    PIPELINE_STATES,
    STAGE_STATES,
    PipelineEngine,
    PipelineRun,
    StageRun,
)
from torchx_tpu.pipelines.promote import PromotionController  # noqa: F401

__all__ = [
    "Pipeline",
    "Stage",
    "topo_order",
    "ROLE_METADATA_KEY",
    "STAGE_KINDS",
    "Artifact",
    "PipelineStage",
    "PipelineSpec",
    "checkpoint_artifact",
    "score_artifact",
    "resolve_args",
    "PIPELINE_STATES",
    "STAGE_STATES",
    "StageRun",
    "PipelineRun",
    "PipelineEngine",
    "PromotionController",
]
