"""Pipeline adapters: run component DAGs on pipeline providers.

Reference analog: torchx/pipelines/__init__.py — in the reference this is
only a namespace docstring ("transform the component into something
understandable by the specific pipeline provider") with no concrete
adapter in the snapshot. Here we ship a concrete data model plus two
adapters:

* :mod:`torchx_tpu.pipelines.local_runner` — executes the DAG through the
  Runner on any registered scheduler (stage-level fan-out, fail-fast,
  tracker lineage chaining),
* :mod:`torchx_tpu.pipelines.kfp` — materializes the DAG as an Argo
  Workflow spec (the engine under Kubeflow Pipelines), emitted as a plain
  dict with no kfp dependency.
"""

from torchx_tpu.pipelines.api import Pipeline, Stage, topo_order  # noqa: F401
