"""Pre-DAG-engine pipeline runners, kept for import compatibility.

These are the kfp-era execution paths that predate the journaled,
event-driven :mod:`torchx_tpu.pipelines.engine`: a KFP/Argo workflow
materializer and a blocking generation-by-generation local runner over a
:class:`~torchx_tpu.pipelines.api.Pipeline`. The old module paths
(``torchx_tpu.pipelines.kfp``, ``torchx_tpu.pipelines.local_runner``)
re-export them behind deprecation warnings; new code should submit a
:class:`~torchx_tpu.pipelines.dag.PipelineSpec` through the control
daemon instead.
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional

from torchx_tpu.pipelines.api import Pipeline, topo_order
from torchx_tpu.specs.api import AppDef, AppHandle, AppState, AppStatus, CfgVal

logger = logging.getLogger(__name__)


# =========================================================================
# KFP/Argo materialization (was pipelines/kfp.py)
# =========================================================================


def _stage_template(name: str, app: AppDef, namespace: str) -> dict[str, Any]:
    from torchx_tpu.schedulers.gke_scheduler import (
        app_to_jobset,
        role_to_pod_template,
        sanitize_name,
    )

    role = app.roles[0]
    multi_host = (
        (role.resource.tpu is not None and role.resource.tpu.hosts > 1)
        or len(app.roles) > 1
        or role.num_replicas > 1
    )
    if multi_host:
        jobset = app_to_jobset(
            app,
            # same 40-char budget as GKEScheduler._submit_dryrun: leaves
            # room in the 63-char pod-name cap for the role name plus
            # job/pod index suffixes
            app_name=sanitize_name(f"{name}-{app.name}", max_len=40),
            namespace=namespace,
            queue=None,
            service_account=None,
        )
        return {
            "name": name,
            "resource": {
                "action": "create",
                "setOwnerReference": True,
                "successCondition": "status.terminalState == Completed",
                "failureCondition": "status.terminalState == Failed",
                # Argo's resource.manifest field is a string (YAML/JSON)
                "manifest": json.dumps(jobset, indent=2),
            },
        }
    pod = role_to_pod_template(
        role,
        app_name=sanitize_name(app.name),
        coordinator_host="localhost",
        coordinator_port=8476,
        service_account=None,
    )
    return {
        "name": name,
        "container": pod["spec"]["containers"][0],
        "metadata": pod["metadata"],
        "nodeSelector": pod["spec"].get("nodeSelector", {}),
        "tolerations": pod["spec"].get("tolerations", []),
        "volumes": pod["spec"].get("volumes", []),
    }


def pipeline_to_workflow(
    pipeline: Pipeline, namespace: str = "default"
) -> dict[str, Any]:
    """-> Argo Workflow resource dict implementing the DAG.

    Each stage's AppDef role becomes an Argo template (container + TPU
    resource limits + node selectors, reusing the GKE scheduler's pod
    materialization); multi-host TPU stages are emitted as ``resource``
    templates creating the same JobSet the GKE scheduler would submit.
    The result is a plain dict — submit it with ``argo submit``, the Argo
    REST API, or mount it into a KFP v2 pipeline.
    """
    from torchx_tpu.schedulers.gke_scheduler import sanitize_name

    topo_order(pipeline)  # validates names/cycles
    # sanitize each stage name once and reuse the result so template/task/
    # dependency refs all carry the identical string
    names = {s.name: sanitize_name(s.name) for s in pipeline.stages}
    templates = [
        _stage_template(names[s.name], s.app, namespace) for s in pipeline.stages
    ]
    dag_tasks = [
        {
            "name": names[s.name],
            "template": names[s.name],
            "dependencies": [names[d] for d in s.depends_on],
        }
        for s in pipeline.stages
    ]
    return {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": {
            "generateName": f"{sanitize_name(pipeline.name)}-",
            "namespace": namespace,
        },
        "spec": {
            "entrypoint": "dag",
            "templates": [
                {"name": "dag", "dag": {"tasks": dag_tasks}},
                *templates,
            ],
        },
    }


# =========================================================================
# Blocking local runner (was pipelines/local_runner.py)
# =========================================================================


@dataclass
class PipelineRun:
    """Per-stage handles + terminal statuses of one :func:`run_pipeline`."""

    pipeline: str
    handles: dict[str, AppHandle] = field(default_factory=dict)
    statuses: dict[str, AppStatus] = field(default_factory=dict)

    @property
    def state(self) -> AppState:
        """FAILED if any stage failed/cancelled, RUNNING while stages are
        outstanding, else SUCCEEDED."""
        if any(
            s.state in (AppState.FAILED, AppState.CANCELLED)
            for s in self.statuses.values()
        ):
            return AppState.FAILED
        if len(self.statuses) < len(self.handles) or not self.handles:
            return AppState.RUNNING
        return AppState.SUCCEEDED


def run_pipeline(
    runner: Any,
    pipeline: Pipeline,
    scheduler: str,
    cfg: Optional[Mapping[str, CfgVal]] = None,
    wait_interval: float = 1.0,
    sleep: Callable[[float], None] = time.sleep,
) -> PipelineRun:
    """Execute the DAG generation-by-generation; returns per-stage handles
    + terminal statuses. All stages of a generation are submitted
    concurrently, then awaited; a failed stage fails the pipeline and
    cancels its in-flight siblings (fail-fast). Each stage's run is
    lineage-linked to its dependencies via the tracker's parent-run
    mechanism."""
    run = PipelineRun(pipeline=pipeline.name)
    for generation in topo_order(pipeline):
        # submit the whole generation
        for stage in generation:
            parent = (
                run.handles.get(stage.depends_on[0]) if stage.depends_on else None
            )
            handle = runner.run(
                stage.app, scheduler, cfg, parent_run_id=parent
            )
            run.handles[stage.name] = handle
            _link_extra_parents(run, stage, handle)
            logger.info("pipeline %s: stage %s -> %s", pipeline.name, stage.name, handle)

        # poll the generation concurrently: first failure cancels the
        # still-running siblings (fail-fast — a dead stage must not let a
        # 3-hour TPU sibling run to completion)
        pending = {s.name for s in generation}
        failed = False
        while pending:
            for name in list(pending):
                status = runner.status(run.handles[name])
                if status is None:
                    raise RuntimeError(f"stage {name} vanished ({run.handles[name]})")
                if status.is_terminal():
                    pending.discard(name)
                    run.statuses[name] = status
                    if status.state != AppState.SUCCEEDED:
                        failed = True
            if failed and pending:
                for name in list(pending):
                    logger.warning("cancelling in-flight stage %s", name)
                    runner.cancel(run.handles[name])
                    st = runner.status(run.handles[name])
                    if st is not None:
                        run.statuses[name] = st
                    pending.discard(name)
                break
            if pending:
                sleep(wait_interval)
        if failed:
            logger.error("pipeline %s failed; skipping downstream stages", pipeline.name)
            return run
    return run


def _link_extra_parents(run: PipelineRun, stage, handle: AppHandle) -> None:  # noqa: ANN001
    """Stages with multiple dependencies get lineage to ALL parents: the
    first rides the runner's parent_run_id env; the rest are written
    client-side into the configured trackers (best-effort)."""
    extra = [run.handles[d] for d in stage.depends_on[1:] if d in run.handles]
    if not extra:
        return
    try:
        from torchx_tpu.runner.config import load_tracker_sections
        from torchx_tpu.tracker.api import _load_tracker

        for name, config in load_tracker_sections().items():
            tracker = _load_tracker(name, config)
            if tracker is None:
                continue
            for parent in extra:
                tracker.add_source(handle, parent)
    except Exception as e:  # noqa: BLE001 - lineage is best-effort
        logger.warning("could not record extra lineage for %s: %s", stage.name, e)
