"""Deprecated shim: moved to :mod:`torchx_tpu.pipelines.legacy`.

The blocking generation-by-generation runner predates the journaled,
event-driven :mod:`torchx_tpu.pipelines.engine`; it lives on unchanged
in ``legacy`` and stays importable from here.
"""

from torchx_tpu.deprecations import deprecated_module
from torchx_tpu.pipelines.legacy import (  # noqa: F401
    PipelineRun,
    run_pipeline,
)

deprecated_module(__name__, replacement="torchx_tpu.pipelines.legacy")
