"""Run a Pipeline DAG through the Runner on any registered scheduler.

Generations run stage-by-stage: all stages of a generation are submitted
concurrently, then awaited; a failed stage fails the pipeline and cancels
its in-flight siblings (fail-fast). Each stage's run is lineage-linked to
its dependencies via the tracker's parent-run mechanism.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Mapping, Optional

from torchx_tpu.pipelines.api import Pipeline, topo_order
from torchx_tpu.runner.api import Runner
from torchx_tpu.specs.api import AppHandle, AppState, AppStatus, CfgVal

logger = logging.getLogger(__name__)


@dataclass
class PipelineRun:
    pipeline: str
    handles: dict[str, AppHandle] = field(default_factory=dict)
    statuses: dict[str, AppStatus] = field(default_factory=dict)

    @property
    def state(self) -> AppState:
        if any(
            s.state in (AppState.FAILED, AppState.CANCELLED)
            for s in self.statuses.values()
        ):
            return AppState.FAILED
        if len(self.statuses) < len(self.handles) or not self.handles:
            return AppState.RUNNING
        return AppState.SUCCEEDED


def run_pipeline(
    runner: Runner,
    pipeline: Pipeline,
    scheduler: str,
    cfg: Optional[Mapping[str, CfgVal]] = None,
    wait_interval: float = 1.0,
) -> PipelineRun:
    """Execute the DAG; returns per-stage handles + terminal statuses."""
    run = PipelineRun(pipeline=pipeline.name)
    for generation in topo_order(pipeline):
        # submit the whole generation
        for stage in generation:
            parent = (
                run.handles.get(stage.depends_on[0]) if stage.depends_on else None
            )
            handle = runner.run(
                stage.app, scheduler, cfg, parent_run_id=parent
            )
            run.handles[stage.name] = handle
            logger.info("pipeline %s: stage %s -> %s", pipeline.name, stage.name, handle)
        # await it
        failed = False
        for stage in generation:
            status = runner.wait(run.handles[stage.name], wait_interval=wait_interval)
            if status is None:
                raise RuntimeError(
                    f"stage {stage.name} vanished ({run.handles[stage.name]})"
                )
            run.statuses[stage.name] = status
            if status.state != AppState.SUCCEEDED:
                failed = True
        if failed:
            # cancel anything from this generation still running + stop
            for stage in generation:
                st = run.statuses.get(stage.name)
                if st is not None and not st.is_terminal():
                    runner.cancel(run.handles[stage.name])
            logger.error("pipeline %s failed; skipping downstream stages", pipeline.name)
            return run
    return run
