"""Run a Pipeline DAG through the Runner on any registered scheduler.

Generations run stage-by-stage: all stages of a generation are submitted
concurrently, then awaited; a failed stage fails the pipeline and cancels
its in-flight siblings (fail-fast). Each stage's run is lineage-linked to
its dependencies via the tracker's parent-run mechanism.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Mapping, Optional

from torchx_tpu.pipelines.api import Pipeline, topo_order
from torchx_tpu.runner.api import Runner
from torchx_tpu.specs.api import AppHandle, AppState, AppStatus, CfgVal

logger = logging.getLogger(__name__)


@dataclass
class PipelineRun:
    pipeline: str
    handles: dict[str, AppHandle] = field(default_factory=dict)
    statuses: dict[str, AppStatus] = field(default_factory=dict)

    @property
    def state(self) -> AppState:
        if any(
            s.state in (AppState.FAILED, AppState.CANCELLED)
            for s in self.statuses.values()
        ):
            return AppState.FAILED
        if len(self.statuses) < len(self.handles) or not self.handles:
            return AppState.RUNNING
        return AppState.SUCCEEDED


def run_pipeline(
    runner: Runner,
    pipeline: Pipeline,
    scheduler: str,
    cfg: Optional[Mapping[str, CfgVal]] = None,
    wait_interval: float = 1.0,
) -> PipelineRun:
    """Execute the DAG; returns per-stage handles + terminal statuses."""
    run = PipelineRun(pipeline=pipeline.name)
    for generation in topo_order(pipeline):
        # submit the whole generation
        for stage in generation:
            parent = (
                run.handles.get(stage.depends_on[0]) if stage.depends_on else None
            )
            handle = runner.run(
                stage.app, scheduler, cfg, parent_run_id=parent
            )
            run.handles[stage.name] = handle
            _link_extra_parents(run, stage, handle)
            logger.info("pipeline %s: stage %s -> %s", pipeline.name, stage.name, handle)

        # poll the generation concurrently: first failure cancels the
        # still-running siblings (fail-fast — a dead stage must not let a
        # 3-hour TPU sibling run to completion)
        pending = {s.name for s in generation}
        failed = False
        while pending:
            for name in list(pending):
                status = runner.status(run.handles[name])
                if status is None:
                    raise RuntimeError(f"stage {name} vanished ({run.handles[name]})")
                if status.is_terminal():
                    pending.discard(name)
                    run.statuses[name] = status
                    if status.state != AppState.SUCCEEDED:
                        failed = True
            if failed and pending:
                for name in list(pending):
                    logger.warning("cancelling in-flight stage %s", name)
                    runner.cancel(run.handles[name])
                    st = runner.status(run.handles[name])
                    if st is not None:
                        run.statuses[name] = st
                    pending.discard(name)
                break
            if pending:
                time.sleep(wait_interval)
        if failed:
            logger.error("pipeline %s failed; skipping downstream stages", pipeline.name)
            return run
    return run


def _link_extra_parents(run: PipelineRun, stage, handle: AppHandle) -> None:  # noqa: ANN001
    """Stages with multiple dependencies get lineage to ALL parents: the
    first rides the runner's parent_run_id env; the rest are written
    client-side into the configured trackers (best-effort)."""
    extra = [run.handles[d] for d in stage.depends_on[1:] if d in run.handles]
    if not extra:
        return
    try:
        from torchx_tpu.runner.config import load_tracker_sections
        from torchx_tpu.tracker.api import _load_tracker

        for name, config in load_tracker_sections().items():
            tracker = _load_tracker(name, config)
            if tracker is None:
                continue
            for parent in extra:
                tracker.add_source(handle, parent)
    except Exception as e:  # noqa: BLE001 - lineage is best-effort
        logger.warning("could not record extra lineage for %s: %s", stage.name, e)
