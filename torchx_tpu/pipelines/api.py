"""Pipeline data model: a DAG of AppDef stages."""

from __future__ import annotations

from dataclasses import dataclass, field
from graphlib import CycleError, TopologicalSorter

from torchx_tpu.specs.api import AppDef


@dataclass
class Stage:
    """One node of the DAG: an app plus the names of stages it needs."""

    name: str
    app: AppDef
    depends_on: list[str] = field(default_factory=list)


@dataclass
class Pipeline:
    name: str
    stages: list[Stage] = field(default_factory=list)

    def stage(self, name: str, app: AppDef, depends_on: list[str] | None = None) -> "Pipeline":
        """Builder-style stage append (returns self for chaining)."""
        self.stages.append(Stage(name=name, app=app, depends_on=depends_on or []))
        return self


def topo_order(pipeline: Pipeline) -> list[list[Stage]]:
    """-> stages grouped into parallel-executable generations, dependency
    order. Raises ValueError on cycles or unknown dependencies."""
    by_name = {s.name: s for s in pipeline.stages}
    if len(by_name) != len(pipeline.stages):
        raise ValueError("duplicate stage names in pipeline")
    for s in pipeline.stages:
        for dep in s.depends_on:
            if dep not in by_name:
                raise ValueError(f"stage {s.name!r} depends on unknown stage {dep!r}")
    ts: TopologicalSorter = TopologicalSorter(
        {s.name: set(s.depends_on) for s in pipeline.stages}
    )
    try:
        ts.prepare()
    except CycleError as e:
        raise ValueError(f"pipeline has a dependency cycle: {e}") from e
    generations: list[list[Stage]] = []
    while ts.is_active():
        ready = list(ts.get_ready())
        generations.append([by_name[n] for n in ready])
        ts.done(*ready)
    return generations
