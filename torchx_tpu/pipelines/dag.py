"""The pipeline DAG model: typed stages wired by artifact edges.

A :class:`PipelineSpec` is the declarative half of the train→eval→promote
subsystem: a named DAG whose nodes are component submissions (``train``,
``eval``) or an in-process promotion action (``promote``), and whose edges
carry typed :class:`Artifact` records — a train stage publishes the
PR 7-verified checkpoint (path + MANIFEST.json content digest + step), an
eval stage publishes a score. Downstream stage args reference upstream
artifacts with ``{stage.field}`` placeholders (``{train.path}``,
``{train.digest}``, ``{eval.score}``), resolved by the engine at submit
time so a stage never starts before its inputs exist.

Everything here is stdlib-only and jax-free (enforced by
``scripts/lint_internal.py``): specs travel over the daemon's HTTP API
and through the fsync'd pipeline journal as plain dicts
(:meth:`PipelineSpec.to_dict` / :meth:`PipelineSpec.from_dict`).
"""

from __future__ import annotations

import graphlib
import json
import os
import re
from dataclasses import asdict, dataclass, field
from typing import Any, Mapping, Optional

__all__ = [
    "ROLE_METADATA_KEY",
    "STAGE_KINDS",
    "Artifact",
    "PipelineStage",
    "PipelineSpec",
    "checkpoint_artifact",
    "score_artifact",
    "resolve_args",
]

#: role-metadata key the engine stamps on every submitted stage role with
#: the stage kind (``train``/``eval``/``promote``) — the analyzer's TPX603
#: promotion-observability rule keys off it.
ROLE_METADATA_KEY = "tpx/pipeline"

#: valid :attr:`PipelineStage.kind` values.
STAGE_KINDS = ("train", "eval", "promote")

_PLACEHOLDER = re.compile(r"\{([A-Za-z0-9_.-]+)\.(path|digest|step|score)\}")


@dataclass
class Artifact:
    """A typed edge payload produced by a finished stage.

    ``kind`` is ``"checkpoint"`` (train stages: ``path``/``digest``/``step``
    from the checkpoint MANIFEST.json) or ``"score"`` (eval stages:
    ``score`` plus the checkpoint identity it was measured on).
    """

    kind: str
    path: str = ""
    digest: str = ""
    step: int = -1
    score: Optional[float] = None

    def to_dict(self) -> dict:
        """Plain-dict form for the journal and the HTTP status payload."""
        return asdict(self)

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any]) -> "Artifact":
        """Inverse of :meth:`to_dict`."""
        return cls(
            kind=str(doc.get("kind", "")),
            path=str(doc.get("path", "")),
            digest=str(doc.get("digest", "")),
            step=int(doc.get("step", -1)),
            score=(
                float(doc["score"]) if doc.get("score") is not None else None
            ),
        )

    def field(self, name: str) -> str:
        """Placeholder field lookup (``path``/``digest``/``step``/``score``)."""
        value = getattr(self, name)
        if value is None:
            raise KeyError(f"artifact has no {name!r} value")
        return str(value)


#: default fleet priority class per stage kind: training rides the batch
#: queue (preemptible, checkpointing), eval gates are interactive (a human
#: decision waits on them), promotion touches the serve pool.
_DEFAULT_PRIORITY = {"train": "batch", "eval": "interactive", "promote": "serve"}


@dataclass
class PipelineStage:
    """One DAG node.

    ``train``/``eval`` stages are component submissions (``component`` +
    ``args`` + ``scheduler``/``cfg``), submitted through the fleet
    scheduler when one is attached (``priority`` defaults per kind:
    train=batch, eval=interactive, promote=serve). ``promote`` stages run
    in-process in the daemon: they roll the upstream checkpoint onto a
    canary fraction of the serve pool and gate on eval score + SLO burn.
    """

    name: str
    kind: str
    component: str = ""
    args: list[str] = field(default_factory=list)
    scheduler: str = "local"
    cfg: dict = field(default_factory=dict)
    depends_on: list[str] = field(default_factory=list)
    priority: str = ""
    replicas: int = 1
    #: train: directory whose MANIFEST.json publishes the checkpoint edge.
    ckpt_dir: str = ""
    #: eval: JSON file the eval app writes its score record to.
    score_file: str = ""
    #: eval: absolute score floor — below it the gate fails the pipeline
    #: before any canary starts.
    threshold: Optional[float] = None
    #: eval: ``"incumbent"`` additionally compares the score against the
    #: last promoted checkpoint's score during the canary phase.
    baseline: str = ""
    #: promote: fraction of serve replicas rolled as the canary cohort.
    canary_fraction: float = 0.25
    #: promote: SLO burn rate at/above which the canary rolls back.
    burn_threshold: float = 1.0
    #: promote: how long to watch the canary's burn signal before deciding.
    observe_s: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in STAGE_KINDS:
            raise ValueError(
                f"stage {self.name!r}: kind must be one of {STAGE_KINDS},"
                f" got {self.kind!r}"
            )
        if not self.name:
            raise ValueError("stage name must be non-empty")
        if not self.priority:
            self.priority = _DEFAULT_PRIORITY[self.kind]
        if self.kind == "eval" and not self.score_file:
            raise ValueError(
                f"eval stage {self.name!r} needs score_file (where the"
                " eval app writes its score record)"
            )

    def to_dict(self) -> dict:
        """Plain-dict form for the journal and the HTTP API."""
        return asdict(self)

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any]) -> "PipelineStage":
        """Inverse of :meth:`to_dict`; unknown keys are ignored."""
        kwargs = {
            k: doc[k]
            for k in (
                "name",
                "kind",
                "component",
                "args",
                "scheduler",
                "cfg",
                "depends_on",
                "priority",
                "replicas",
                "ckpt_dir",
                "score_file",
                "threshold",
                "baseline",
                "canary_fraction",
                "burn_threshold",
                "observe_s",
            )
            if k in doc
        }
        return cls(**kwargs)


@dataclass
class PipelineSpec:
    """A named, validated DAG of :class:`PipelineStage` nodes."""

    name: str
    stages: list[PipelineStage] = field(default_factory=list)

    def stage(self, name: str) -> PipelineStage:
        """Stage lookup by name (KeyError when absent)."""
        for s in self.stages:
            if s.name == name:
                return s
        raise KeyError(f"pipeline {self.name!r} has no stage {name!r}")

    def validate(self) -> None:
        """Reject duplicate names, unknown dependencies and cycles."""
        if not self.name:
            raise ValueError("pipeline name must be non-empty")
        if not self.stages:
            raise ValueError(f"pipeline {self.name!r} has no stages")
        names = [s.name for s in self.stages]
        if len(names) != len(set(names)):
            raise ValueError(f"pipeline {self.name!r} has duplicate stage names")
        known = set(names)
        graph: dict[str, set[str]] = {}
        for s in self.stages:
            missing = [d for d in s.depends_on if d not in known]
            if missing:
                raise ValueError(
                    f"stage {s.name!r} depends on unknown stage(s) {missing}"
                )
            graph[s.name] = set(s.depends_on)
        try:
            tuple(graphlib.TopologicalSorter(graph).static_order())
        except graphlib.CycleError as e:
            raise ValueError(f"pipeline {self.name!r} has a cycle: {e}") from e

    def generations(self) -> list[list[PipelineStage]]:
        """Stages grouped into dependency generations (topological)."""
        self.validate()
        sorter = graphlib.TopologicalSorter(
            {s.name: set(s.depends_on) for s in self.stages}
        )
        sorter.prepare()
        out: list[list[PipelineStage]] = []
        while sorter.is_active():
            ready = list(sorter.get_ready())
            out.append([self.stage(n) for n in sorted(ready)])
            sorter.done(*ready)
        return out

    def to_dict(self) -> dict:
        """Plain-dict form for the journal and the HTTP API."""
        return {
            "name": self.name,
            "stages": [s.to_dict() for s in self.stages],
        }

    @classmethod
    def from_dict(cls, doc: Mapping[str, Any]) -> "PipelineSpec":
        """Inverse of :meth:`to_dict`; validates the result."""
        spec = cls(
            name=str(doc.get("name", "")),
            stages=[
                PipelineStage.from_dict(s) for s in doc.get("stages", [])
            ],
        )
        spec.validate()
        return spec


def checkpoint_artifact(ckpt_dir: str) -> Artifact:
    """The checkpoint edge published by a finished train stage.

    Reads the directory's MANIFEST.json sidecar (written by
    :mod:`torchx_tpu.parallel.checkpoint`, digests included) without
    importing any accelerator code: ``latest_step`` names the newest
    finalized save, ``steps[str(step)]["digest"]`` is its sha256 content
    digest. Raises ``ValueError`` when the manifest is missing, unreadable
    or has no finalized step — a train stage that "succeeded" without a
    verifiable checkpoint must fail its pipeline, not promote garbage.
    """
    from torchx_tpu import settings

    path = os.path.join(ckpt_dir, settings.CHECKPOINT_MANIFEST)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"no readable checkpoint manifest at {path}: {e}") from e
    step = doc.get("latest_step")
    if not isinstance(step, int) or step < 0:
        raise ValueError(f"{path} records no finalized step")
    rec = doc.get("steps", {}).get(str(step))
    digest = str(rec.get("digest", "")) if isinstance(rec, dict) else ""
    return Artifact(kind="checkpoint", path=ckpt_dir, digest=digest, step=step)


def score_artifact(score_file: str) -> Artifact:
    """The score edge published by a finished eval stage.

    Reads the JSON record ``apps/eval_main.py`` writes (``score`` required;
    ``ckpt``/``digest``/``step`` echo the evaluated checkpoint identity).
    Raises ``ValueError`` when missing or scoreless.
    """
    try:
        with open(score_file) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"no readable score record at {score_file}: {e}") from e
    if doc.get("score") is None:
        raise ValueError(f"{score_file} has no 'score' field")
    return Artifact(
        kind="score",
        path=str(doc.get("ckpt", "")),
        digest=str(doc.get("digest", "")),
        step=int(doc.get("step", -1)),
        score=float(doc["score"]),
    )


def resolve_args(
    args: list[str], artifacts: Mapping[str, Artifact]
) -> list[str]:
    """Substitute ``{stage.field}`` placeholders with upstream artifact
    values (fields: ``path``/``digest``/``step``/``score``). An unknown
    stage or a field the artifact doesn't carry raises ``KeyError`` — a
    stage must never launch with a dangling input."""

    def _sub(match: "re.Match[str]") -> str:
        stage, fld = match.group(1), match.group(2)
        if stage not in artifacts:
            raise KeyError(
                f"arg references {stage}.{fld} but stage {stage!r} published"
                " no artifact"
            )
        return artifacts[stage].field(fld)

    return [_PLACEHOLDER.sub(_sub, str(a)) for a in args]
