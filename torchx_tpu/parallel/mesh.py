"""Device mesh construction + sharding helpers for SPMD training.

The canonical 6-axis mesh for TPU LLM training (scaling-book recipe: pick a
mesh, annotate shardings, let XLA insert the collectives over ICI/DCN):

* ``pp``   — pipeline parallelism (layer stages; between slices, DCN),
* ``dp``   — pure data parallelism (between slices, rides DCN),
* ``fsdp`` — data parallelism with parameter sharding (rides ICI),
* ``ep``   — expert parallelism (MoE expert axis; dense models leave it 1),
* ``tp``   — tensor (model) parallelism within attention/MLP blocks,
* ``sp``   — sequence/context parallelism for long sequences.

Axis sizes multiply to the device count; unused axes get size 1 so
PartitionSpecs can always name every axis. MoE expert weights shard over
``("ep", "tp")`` combined (models/moe.py), so ep and tp can be sized
independently — tp=1, ep=8 for a small MoE, or tp=4, ep=2 to split both
ways.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the shape model is pure arithmetic and is shared with the jax-free
# client side (supervisor elastic reshape); it lives in mesh_config
from torchx_tpu.parallel.mesh_config import AXES, MeshConfig

__all__ = [
    "AXES",
    "MeshConfig",
    "make_mesh",
    "named_sharding",
    "shard_map",
    "enable_shardy_if_supported",
    "manual_axes",
    "BATCH_SPEC",
    "ACT_SPEC",
    "ACT_TP_SPEC",
]


def make_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the 6-axis mesh over all (or the given) devices.

    Axis order is (pp, dp, fsdp, ep, tp, sp) — outermost-to-innermost
    matches slowest-to-fastest interconnect: pp/dp between slices over DCN,
    tp on the innermost ICI dimension where its all-reduces are cheapest;
    ep sits just outside tp so the MoE all-to-all also rides ICI.
    """
    devs = list(devices) if devices is not None else jax.devices()
    sizes = config.resolve(len(devs))
    shape = tuple(sizes[a] for a in AXES)
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, AXES)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """Shorthand: ``NamedSharding(mesh, P(*spec))``."""
    return NamedSharding(mesh, P(*spec))


def shard_map(
    f,  # noqa: ANN001
    *,
    in_specs,  # noqa: ANN001
    out_specs,  # noqa: ANN001
    mesh: Optional[Mesh] = None,
    axis_names: Optional[frozenset] = None,
    check_vma: bool = False,
):
    """``jax.shard_map`` across the JAX versions in play.

    Modern JAX exports ``jax.shard_map`` (``axis_names`` = the manual
    axes, ``check_vma``); 0.4.x only has the experimental spelling, where
    partial manualization is the complement (``auto`` = the axes left
    automatic) and the replication check is ``check_rep``. The inherited-
    mesh form (``mesh=None`` inside a parent manual region) needs modern
    JAX — 0.4.x callers never reach it because partial-auto nesting is
    rejected there anyway.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return sm(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
            **kwargs,
        )
    from jax.experimental.shard_map import shard_map as legacy_sm

    if mesh is None:
        raise NotImplementedError(
            "shard_map with an inherited mesh (mesh=None) requires"
            " jax.shard_map (jax >= 0.5)"
        )
    kwargs = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    return legacy_sm(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        **kwargs,
    )


def enable_shardy_if_supported() -> bool:
    """Opt into the Shardy partitioner on JAX versions that can carry it.

    Every sharding construct in this repo (partial-auto ``shard_map``,
    nested-manual-region rules, the embedding gather constraints) is
    written against Shardy semantics; compiling through the legacy GSPMD
    pipeline instead logs a deprecation warning per compile
    (``sharding_propagation.cc``) and its gather heuristics are the source
    of the involuntary-full-rematerialization warnings
    (``spmd_partitioner.cc:652``). Gate on ``jax.shard_map`` existing: the
    0.4.x stack pairs Shardy with the legacy ``auto=`` shard_map spelling,
    which miscompiles (PartitionId) — there we stay on GSPMD. Returns
    whether Shardy is now active; safe to call repeatedly.
    """
    if getattr(jax, "shard_map", None) is None:
        return False
    try:
        jax.config.update("jax_use_shardy_partitioner", True)
        return True
    except Exception:  # pragma: no cover - option absent on this jax
        return False


def manual_axes() -> frozenset:
    """Axis names manualized by an enclosing ``shard_map``, across the JAX
    versions in play: ``jax.sharding.get_abstract_mesh`` where exported,
    falling back to the ``jax._src.mesh`` spelling (0.4.x — where the
    no-mesh sentinel is a bare tuple rather than an AbstractMesh carrying
    ``.empty``/``.manual_axes``). Empty set = not inside a manual region."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is None:
        try:
            from jax._src.mesh import get_abstract_mesh as get
        except ImportError:  # pragma: no cover - very old jax
            return frozenset()
    ctx = get()
    if not hasattr(ctx, "manual_axes") or getattr(ctx, "empty", False):
        return frozenset()
    return frozenset(ctx.manual_axes)


# Canonical PartitionSpecs for transformer training state. Batch shards over
# both data axes; sequence over sp (Megatron-style sequence parallelism for
# the residual stream; attention itself uses ring attention over sp).
# raw token batches shard on batch only: the seq axis of data often has
# odd lengths (seq+1 for next-token targets) and activations pick up their
# sp sharding from the in-model constraints instead
BATCH_SPEC = P(("dp", "fsdp"), None)  # tokens [batch, seq]
ACT_SPEC = P(("dp", "fsdp"), "sp", None)  # activations [batch, seq, dim]
ACT_TP_SPEC = P(("dp", "fsdp"), None, "tp")  # attn/mlp inner activations
