"""Double-buffered device input prefetch for the training loop.

The step-time budget of an SPMD trainer has exactly two host-visible
pieces: time the device spends computing, and time the host spends
producing the next batch (memmap reads, crop stacking, the host->device
transfer) while the device sits idle. :class:`Prefetcher` moves the second
piece off the critical path: a producer thread stays up to ``depth``
batches ahead of the consumer, running batch assembly AND the sharded
``device_put`` of batch N+1 concurrently with the device computing batch
N. The consumer's ``next()`` then usually finds a finished device array
waiting in the queue — and every microsecond it *does* block is accounted
in :attr:`Prefetcher.data_wait_s`, so the trainer can report the
data-wait vs compute split instead of guessing (bench.py surfaces it as
``data_wait_frac``).

Depth semantics:

* ``depth >= 1`` — a daemon producer thread plus a FIFO queue of that
  size; ordering is preserved (one producer, one queue), so seeded,
  resumable data streams stay deterministic.
* ``depth == 0`` — synchronous passthrough: no thread, ``next()`` runs
  the source and placement inline (the pre-prefetch behavior), still
  timed as data wait.

Errors raised by the source or placement propagate to the consumer's
``next()`` call — a data error fails the job loudly rather than hanging
the loop. :meth:`Prefetcher.close` (also the context-manager exit) drains
and joins the producer so early loop exits never leak a thread blocked on
a full queue.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from torchx_tpu.parallel.mesh import BATCH_SPEC

_DONE = object()  # source exhausted


class _Failure:
    """Exception crossing the thread boundary (kept distinct from batch
    values so an iterator of exception *objects* would still round-trip)."""

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class Prefetcher:
    """Iterator staying up to ``depth`` placed batches ahead of its consumer.

    ``source`` is any iterable of batches; ``place`` (optional) maps each
    raw batch to its device-resident form — e.g. a sharded ``device_put``
    (see :func:`device_prefetch`) — and runs ON THE PRODUCER THREAD, so
    transfers overlap compute. With ``depth=0`` everything runs inline in
    ``next()`` (passthrough mode).
    """

    def __init__(
        self,
        source: Iterable[Any],
        *,
        depth: int = 2,
        place: Optional[Callable[[Any], Any]] = None,
        name: str = "tpx-prefetch",
    ) -> None:
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self._source = iter(source)
        self._place = place if place is not None else (lambda x: x)
        self._depth = depth
        self._wait_s = 0.0
        self._wait_observer: Optional[Callable[[float], None]] = None
        self._served = 0
        self._closed = False
        self._exhausted = False
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if depth > 0:
            self._queue = queue.Queue(maxsize=depth)
            self._thread = threading.Thread(
                target=self._produce, daemon=True, name=name
            )
            self._thread.start()

    # -- producer side -----------------------------------------------------

    def _offer(self, item: Any) -> None:
        # bounded put that stays responsive to close(): never block forever
        # on a queue the consumer stopped draining
        assert self._queue is not None
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    def _produce(self) -> None:
        try:
            for raw in self._source:
                self._offer(self._place(raw))
                if self._stop.is_set():
                    return
            self._offer(_DONE)
        except BaseException as e:  # noqa: BLE001 - re-raised on the consumer side
            self._offer(_Failure(e))

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._closed or self._exhausted:
            raise StopIteration
        t0 = time.monotonic()
        try:
            if self._queue is None:  # depth=0 passthrough
                try:
                    return self._place(next(self._source))
                except StopIteration:
                    self._exhausted = True
                    raise
            item = self._queue.get()
            if item is _DONE:
                self._exhausted = True
                raise StopIteration
            if isinstance(item, _Failure):
                self._exhausted = True
                raise item.exc
            self._served += 1
            return item
        finally:
            dt = time.monotonic() - t0
            self._wait_s += dt
            if self._wait_observer is not None:
                try:
                    self._wait_observer(dt)
                except Exception:  # noqa: BLE001 - observers never break the loop
                    pass

    def set_wait_observer(
        self, observer: Optional[Callable[[float], None]]
    ) -> None:
        """Install a per-``next()`` wait callback (seconds blocked).

        Runs on the CONSUMER thread inside ``next()`` — the step
        profiler's ``observe_wait`` hook, which credits each blocked
        interval to the current step's ``data_wait`` phase instead of
        only the run-total :attr:`data_wait_s`. Best-effort: observer
        exceptions are swallowed. Pass None to uninstall.
        """
        self._wait_observer = observer

    @property
    def data_wait_s(self) -> float:
        """Cumulative seconds the consumer spent blocked waiting for data
        (queue waits, or inline production time in passthrough mode)."""
        return self._wait_s

    @property
    def batches_served(self) -> int:
        """Batches handed to the consumer so far (excludes queued ones)."""
        return self._served

    def close(self) -> None:
        """Stop the producer and join its thread (idempotent).

        Safe at any point — including mid-stream early exit with the
        producer blocked on a full queue: the stop event breaks its
        bounded put, the queue is drained, and the thread is joined.
        """
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._queue is not None:
            while True:  # unblock a producer waiting on a full queue
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc_info: Any) -> None:
        self.close()


def sharded_put(mesh: Mesh, spec: PartitionSpec = BATCH_SPEC) -> Callable[[Any], Any]:
    """A ``place`` callable moving host batches onto ``mesh`` under ``spec``.

    Dict batches place each leaf; host numpy arrays go through
    ``make_array_from_process_local_data`` (each process contributes only
    its local rows — same multi-host contract as examples/data.py);
    already-committed ``jax.Array`` leaves pass through untouched.
    """
    sharding = NamedSharding(mesh, spec)

    def put_leaf(x: Any) -> Any:
        if isinstance(x, jax.Array) and getattr(x, "sharding", None) == sharding:
            return x
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    def place(batch: Any) -> Any:
        if isinstance(batch, dict):
            return {k: put_leaf(v) for k, v in batch.items()}
        return put_leaf(batch)

    return place


def device_prefetch(
    source: Iterable[Any],
    mesh: Mesh,
    *,
    depth: int = 2,
    spec: PartitionSpec = BATCH_SPEC,
    name: str = "tpx-prefetch",
) -> Prefetcher:
    """:class:`Prefetcher` over host batches with sharded placement onto
    ``mesh`` — the one-call spelling the trainer uses."""
    return Prefetcher(source, depth=depth, place=sharded_put(mesh, spec), name=name)
