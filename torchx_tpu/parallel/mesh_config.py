"""Jax-free mesh shape model: axis sizes, specs, and elastic reshaping.

:class:`MeshConfig` is pure arithmetic over the canonical 6-axis TPU
training mesh (see :mod:`torchx_tpu.parallel.mesh` for the jax side), so
it lives in its own module that never imports jax: the client-side
supervisor computes *degraded* shapes after a preemption or hang
(``dp``/``fsdp`` shrink, ``tp``/``ep``/``sp``/``pp`` are preserved — model
and expert sharding cannot change without re-planning the program) and
injects the result as a ``TPX_MESH`` spec string into the resubmitted
attempt, all without touching a jax runtime.

Spec strings are the CLI ``--mesh`` syntax (``dp=2,fsdp=-1,tp=4``), the
shared currency between the launcher, the attempt ledger, and the in-job
trainer.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

AXES = ("pp", "dp", "fsdp", "ep", "tp", "sp")

#: axes an elastic reshape may shrink (pure data parallelism): losing
#: capacity reduces throughput, not the model's sharding plan.
DATA_AXES = ("dp", "fsdp")

#: axes an elastic reshape must preserve: resizing any of these changes
#: how parameters/experts are laid out and needs a full re-plan.
MODEL_AXES = ("pp", "ep", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Mesh axis sizes; -1 on at most one axis means "all remaining
    devices"."""

    pp: int = 1
    dp: int = 1
    fsdp: int = -1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Concrete axis sizes for ``n_devices`` (the single -1 axis
        absorbs the remainder); raises when sizes don't multiply out."""
        sizes = {a: getattr(self, a) for a in AXES}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}"
            )
        return sizes


def axis_networks(
    sizes: dict[str, int], chips_per_slice: int
) -> dict[str, str]:
    """Classify each mesh axis as ``"ici"``, ``"dcn"`` or ``"mixed"``
    (``"none"`` for size-1 axes) from the slice topology.

    ``make_mesh`` (parallel/mesh.py) reshapes the device list in
    :data:`AXES` order, outermost (``pp``) to innermost (``sp``), and the
    device list enumerates slices contiguously — so an axis's position in
    the flat device order decides which interconnect its collectives
    traverse. With ``stride(axis)`` = product of the sizes of the axes
    *after* it:

    * ``stride * size <= chips_per_slice`` — every hop along the axis
      stays inside one slice: **ici**.
    * ``stride >= chips_per_slice`` — every hop crosses a slice boundary:
      **dcn**.
    * otherwise the axis straddles the boundary: **mixed** (part of each
      ring is ICI, part DCN — the DCN segment paces the collective).

    ``sizes`` must be fully resolved (no -1); extra keys are ignored.
    """
    out: dict[str, str] = {}
    stride = 1
    for axis in reversed(AXES):
        size = int(sizes.get(axis, 1))
        if size <= 1:
            out[axis] = "none"
            continue
        extent = stride * size
        if extent <= chips_per_slice:
            out[axis] = "ici"
        elif stride >= chips_per_slice:
            out[axis] = "dcn"
        else:
            out[axis] = "mixed"
        stride = extent
    return out


def parse_mesh_spec(spec: str) -> MeshConfig:
    """``"dp=2,fsdp=-1,tp=4"`` -> :class:`MeshConfig` (unnamed axes keep
    their defaults; unknown axis names raise)."""
    kwargs: dict[str, int] = {}
    for pair in spec.split(","):
        if not pair.strip():
            continue
        k, _, v = pair.partition("=")
        k = k.strip()
        if k not in AXES:
            raise ValueError(f"unknown mesh axis {k!r}; valid axes: {AXES}")
        kwargs[k] = int(v)
    return MeshConfig(**kwargs)


def mesh_sizes_spec(sizes: dict[str, int]) -> str:
    """Resolved axis sizes -> a fully-explicit spec string (every axis
    named, no -1), suitable for the attempt ledger and ``TPX_MESH``."""
    return ",".join(f"{a}={int(sizes[a])}" for a in AXES)


def shrink_data_axes(
    sizes: dict[str, int], target_devices: Optional[int] = None
) -> dict[str, int]:
    """A degraded mesh shape after capacity loss: shrink ``dp`` first,
    then ``fsdp``, never the model axes.

    ``sizes`` are fully-resolved axis sizes (no -1). With
    ``target_devices`` the data axes are refit to exactly that device
    count (used when the gang monitor knows how many replicas survive);
    without it the shape degrades one binary step — halve ``dp`` when it
    can shrink, else halve ``fsdp`` (used when all the supervisor knows is
    "the attempt was preempted"). Raises :class:`ValueError` when the
    target cannot preserve the model axes or there is no data parallelism
    left to give up — the caller then resubmits at the current shape.
    """
    model = math.prod(sizes[a] for a in MODEL_AXES)
    cur_data = sizes["dp"] * sizes["fsdp"]
    if target_devices is None:
        if sizes["dp"] > 1:
            return {**sizes, "dp": sizes["dp"] // 2}
        if sizes["fsdp"] > 1:
            return {**sizes, "fsdp": sizes["fsdp"] // 2}
        raise ValueError(
            f"mesh {mesh_sizes_spec(sizes)} has no data parallelism left to"
            " shrink (dp=fsdp=1)"
        )
    if target_devices < model or target_devices % model:
        raise ValueError(
            f"{target_devices} surviving devices cannot preserve the model"
            f" axes of {mesh_sizes_spec(sizes)} (pp*ep*tp*sp={model})"
        )
    data = target_devices // model
    if data >= cur_data:
        raise ValueError(
            f"target {target_devices} devices is not a shrink of"
            f" {mesh_sizes_spec(sizes)}"
        )
    # preserve the fsdp extent when possible (parameter shards stay the
    # same size across the restore), folding the loss into dp; otherwise
    # collapse dp and give fsdp whatever data parallelism remains
    fsdp = sizes["fsdp"]
    if fsdp > 0 and data % fsdp == 0:
        return {**sizes, "dp": data // fsdp, "fsdp": fsdp}
    return {**sizes, "dp": 1, "fsdp": data}
