"""Sharded checkpoint save/restore for SPMD training state.

The launcher's failure story (SURVEY §5): schedulers restart the whole
gang on failure (RetryPolicy.APPLICATION / JobSet failurePolicy / slurm
requeue), and the *application* makes itself resumable — same stance as
the reference, with orbax as the blessed library. This module is the
in-job half: an orbax ``CheckpointManager`` wrapper that saves/restores a
pytree with its ``NamedSharding``s intact (each host writes only its
shards; restore re-shards onto the current mesh), so

    launcher retry  +  Checkpointer.restore_or_init(...)

is the complete preemption-recovery loop (BASELINE config 4).

Falls back to a single-host pickle format when orbax is unavailable.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import re
import threading
from typing import Any, Optional

import jax

from torchx_tpu import settings

logger = logging.getLogger(__name__)


def _digest_path(path: str) -> Optional[str]:
    """sha256 content digest of one finalized step payload: a file hashes
    its bytes; a directory hashes every file's relpath + bytes in sorted
    order (so the digest is stable across listdir order and catches both
    truncated payloads and missing shard files). None when unreadable."""
    h = hashlib.sha256()
    try:
        if os.path.isdir(path):
            for root, dirs, files in sorted(os.walk(path)):
                dirs.sort()
                for name in sorted(files):
                    fp = os.path.join(root, name)
                    h.update(os.path.relpath(fp, path).encode())
                    with open(fp, "rb") as f:
                        for chunk in iter(lambda: f.read(1 << 20), b""):
                            h.update(chunk)
        else:
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
    except OSError:
        return None
    return h.hexdigest()


def _host_copy(x: Any) -> Any:
    """Device→host leaf transfer that owns its memory (see _pickle_save)."""
    import numpy as np

    h = jax.device_get(x)
    return h.copy() if isinstance(h, np.ndarray) else h


class Checkpointer:
    """Async orbax checkpointing for sharded train state: non-blocking
    saves on an interval, retention, corrupt-step fallback on restore,
    and restore-to-the-live-shardings (see restore_latest)."""

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ) -> None:
        """``async_save`` (default on) makes ``save()`` return as soon as
        the on-device state is snapshotted to host memory; serialization
        and writes proceed in orbax's background thread so the train step
        never blocks on checkpoint I/O (the HBM-bandwidth win: a 1B-param
        sharded save overlaps entirely with the next steps). ``wait()`` /
        ``close()`` are the synchronization points; restore paths wait
        automatically."""
        self.directory = os.path.abspath(os.path.expanduser(directory))
        self._mgr = None
        self._max_to_keep = max_to_keep
        self._save_interval = save_interval_steps
        self._async = async_save
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            os.makedirs(self.directory, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep,
                    save_interval_steps=save_interval_steps,
                    enable_async_checkpointing=async_save,
                ),
            )
        except ImportError:
            logger.warning("orbax not available; using single-host pickle fallback")
            self._ocp = None
            os.makedirs(self.directory, exist_ok=True)
        # steps whose content digest still needs computing: async saves
        # are not on disk at save() time, so digests finalize at the next
        # synchronization point (wait/close/latest_step/restore)
        self._pending_digests: set[int] = set()
        # snapshot-then-write state for the pickle fallback: at most one
        # background writer in flight; its failure is latched and re-raised
        # at the next synchronization point (wait/save) rather than lost
        # on a daemon thread
        self._writer: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        # guards _pending_digests and the latched _writer_error: the join
        # fences already serialize writer vs. step loop, but the latch is
        # written from the writer thread while the step loop may read it
        self._lock = threading.Lock()

    # -- orbax path --------------------------------------------------------

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if the interval policy says so (or ``force``, e.g. the final
        state regardless of interval); returns whether a save was STARTED
        (async mode) or completed (sync mode)."""
        if self._mgr is not None:
            saved = self._mgr.save(
                step, args=self._ocp.args.StandardSave(state), force=force
            )
            if saved:
                with self._lock:
                    self._pending_digests.add(step)
                self._write_manifest(step)
            if not self._async:
                self._mgr.wait_until_finished()
                self._finalize_digests()
            return bool(saved)
        return self._pickle_save(step, state, force=force)

    def wait(self) -> None:
        """Block until in-flight async saves are durably on disk, then
        record their content digests in the manifest. A failed background
        pickle write surfaces HERE (latched from the writer thread) — the
        SIGTERM force-flush path calls save(force=True) + wait(), so a
        dying job still learns its final checkpoint did not land."""
        self._join_writer()
        if self._mgr is not None:
            self._mgr.wait_until_finished()
        self._finalize_digests()
        self._raise_writer_error()

    # -- manifest + digests ------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.directory, settings.CHECKPOINT_MANIFEST)

    def _read_manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def _mutate_manifest(self, latest: Any = "keep", **digest_ops: Any) -> None:
        """Atomically rewrite MANIFEST.json (process 0 only, advisory —
        never fails a save). ``latest`` replaces ``latest_step`` unless
        ``"keep"``; ``set_digests``/``drop_steps`` kwargs update the
        per-step ``steps`` digest table."""
        if jax.process_index() != 0:
            return
        doc = self._read_manifest()
        if latest != "keep":
            doc["latest_step"] = latest
        steps = doc.get("steps")
        if not isinstance(steps, dict):
            steps = {}
        for step, digest in (digest_ops.get("set_digests") or {}).items():
            steps[str(step)] = {"digest": digest}
        for step in digest_ops.get("drop_steps") or ():
            steps.pop(str(step), None)
        doc["steps"] = steps
        path = self._manifest_path()
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:  # advisory: never fail a save over the sidecar
            logger.warning("could not write checkpoint manifest %s: %s", path, e)

    def _write_manifest(self, step: int) -> None:
        """Record ``step`` as the latest save in the MANIFEST.json sidecar.

        The manifest is the jax-free half of the checkpoint-resume contract:
        the client-side supervisor reads it (supervisor/api.py) to inject
        ``TPX_RESUME_STEP`` on resubmit without importing this module. It is
        advisory — in async mode the step may still be finalizing, so in-job
        restore always trusts the real step listing over the manifest — and
        written atomically by process 0 only. Per-step content digests
        (``steps`` table) land later, at the synchronization point where
        the payload is durably on disk (:meth:`wait`)."""
        self._mutate_manifest(latest=step)

    def _step_path(self, step: int) -> Optional[str]:
        """On-disk payload for a step (orbax dir or pickle file), or None."""
        for path in (
            os.path.join(self.directory, str(step)),
            os.path.join(self.directory, f"step_{step}.pkl"),
        ):
            if os.path.exists(path):
                return path
        return None

    def _finalize_digests(self) -> None:
        """Digest every finalized pending step into the manifest, and drop
        digest entries for steps retention has pruned."""
        with self._lock:
            pending, self._pending_digests = self._pending_digests, set()
        if jax.process_index() != 0:
            return
        known = (
            set(self._mgr.all_steps())
            if self._mgr is not None
            else set(self._pickle_steps())
        )
        digests = {}
        for step in sorted(pending):
            if step not in known:
                continue  # pruned (or never finalized) before digesting
            path = self._step_path(step)
            digest = _digest_path(path) if path else None
            if digest:
                digests[step] = digest
        stale = [
            s
            for s in self._read_manifest().get("steps", {})
            if s.isdigit() and int(s) not in known
        ]
        if digests or stale:
            self._mutate_manifest(set_digests=digests, drop_steps=stale)

    def verify_step(self, step: int) -> Optional[bool]:
        """Check a step's on-disk payload against its recorded digest:
        True = verified, False = MISMATCH (corrupt / tampered / truncated),
        None = no digest recorded (pre-digest checkpoint) — callers treat
        None as "unverifiable, proceed"."""
        rec = self._read_manifest().get("steps", {}).get(str(step))
        digest = rec.get("digest") if isinstance(rec, dict) else None
        if not digest:
            return None
        path = self._step_path(step)
        if path is None:
            return False
        return _digest_path(path) == digest

    @staticmethod
    def resume_step_from_env() -> Optional[int]:
        """Step the supervisor asked this (resubmitted) run to resume from,
        or None on a fresh run. Reads ``TPX_RESUME_STEP``; training loops
        pass it to ``restore(...)`` instead of ``restore_latest`` when they
        want the launcher-chosen step rather than the newest on disk."""
        raw = os.environ.get(settings.ENV_TPX_RESUME_STEP, "")
        try:
            return int(raw)
        except ValueError:
            return None

    def latest_step(self) -> Optional[int]:
        """Newest complete checkpoint step, or None (waits for an
        in-flight save first)."""
        if self._mgr is not None:
            self.wait()  # an in-flight save IS the latest once finalized
            return self._mgr.latest_step()
        return next(iter(self._all_steps()), None)

    def restore(self, step: int, abstract_state: Any) -> Any:
        """Restore onto the shardings/dtypes of ``abstract_state`` (a pytree
        of jax.ShapeDtypeStruct with shardings, or a live donated state)."""
        if self._mgr is not None:
            self.wait()
            target = jax.tree.map(
                lambda x: (
                    jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                    if hasattr(x, "sharding")
                    else x
                ),
                abstract_state,
            )
            restored = self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(target)
            )
            # orbax may bring scalars back committed to a single device;
            # re-place every leaf onto the target sharding (no-op when
            # already correct) so the train step sees one device set
            return jax.tree.map(
                lambda r, t: (
                    jax.device_put(r, t.sharding) if hasattr(t, "sharding") else r
                ),
                restored,
                abstract_state,
            )
        return self._pickle_restore(step, abstract_state)

    def _pickle_steps(self) -> list[int]:
        """Steps present in the pickle layout, newest first (the ONE place
        the ``step_N.pkl`` naming is parsed)."""
        return sorted(
            (
                int(m.group(1))
                for p in os.listdir(self.directory)
                if (m := re.fullmatch(r"step_(\d+)\.pkl", p))
            ),
            reverse=True,
        )

    def _all_steps(self) -> list[int]:
        """Known finalized steps, newest first."""
        if self._mgr is not None:
            self.wait()
            return sorted(self._mgr.all_steps(), reverse=True)
        self._join_writer()  # an in-flight save IS a step once finalized
        return self._pickle_steps()

    def restore_latest(self, abstract_state: Any) -> tuple[Optional[int], Any]:
        """-> (step, state) from the newest RESTORABLE checkpoint, or
        (None, None).

        A preemption can kill the process mid-write, leaving the newest
        step present-but-corrupt; resume must not die on it, so restore
        walks newest -> oldest, logging and skipping steps that fail to
        load. Steps with a recorded content digest are verified BEFORE the
        (expensive, possibly silently-wrong) load — a mismatch quarantines
        the step exactly like a load failure. Only when every retained
        step is unreadable does the error propagate (silently
        reinitializing from scratch with corrupt checkpoints on disk would
        hide real data loss).

        ``abstract_state`` carries the *current* mesh's shardings, which
        need not match the mesh the checkpoint was saved on — restore
        re-shards onto whatever the caller built, so a run resumed after an
        elastic reshape (8-device save, 4-device resume) loads cleanly."""
        steps = self._all_steps()
        if not steps:
            return None, None
        if jax.process_count() > 1:
            # the fallback decision must be GANG-COORDINATED: orbax restore
            # is collective, so hosts independently skipping different
            # corrupt steps would enter mismatched collectives (hang) or
            # resume from different params (silent divergence). Restore the
            # newest step on every host and let a failure surface; the
            # launcher's retry policy restarts the gang, and an operator
            # can prune the corrupt step dir to fall back explicitly.
            step = steps[0]
            return step, self.restore(step, abstract_state)
        last_err: Optional[Exception] = None
        for step in steps:
            if self.verify_step(step) is False:
                logger.warning(
                    "checkpoint step %d fails digest verification; trying"
                    " the previous step",
                    step,
                )
                last_err = RuntimeError(
                    f"step {step} content digest mismatch"
                )
                self._quarantine(step)
                continue
            try:
                return step, self.restore(step, abstract_state)
            except Exception as e:  # noqa: BLE001 - per-step corruption
                logger.warning(
                    "checkpoint step %d is unreadable (%s: %s); trying the"
                    " previous step",
                    step,
                    type(e).__name__,
                    e,
                )
                last_err = e
                # quarantine the corrupt step: training resumed from an
                # older step will reach this step number again, and a
                # lingering dir would make the re-save crash
                # (orbax StepAlreadyExistsError) — a permanent crash loop
                # under gang-restart retries
                self._quarantine(step)
        raise RuntimeError(
            f"all {len(steps)} retained checkpoints under {self.directory}"
            " failed to restore; refusing to silently reinitialize"
        ) from last_err

    def _quarantine(self, step: int) -> None:
        """Move an unreadable step aside (never delete: it is evidence,
        and an operator may still salvage shards from it)."""
        candidates = [
            os.path.join(self.directory, str(step)),
            os.path.join(self.directory, f"step_{step}.pkl"),
        ]
        for path in candidates:
            if not os.path.exists(path):
                continue
            dst = f"{path}.corrupt"
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = f"{path}.corrupt{n}"
            try:
                os.rename(path, dst)
                logger.warning("quarantined corrupt checkpoint %s -> %s", path, dst)
            except OSError as e:
                logger.error("could not quarantine %s: %s", path, e)
        if self._mgr is not None:
            # orbax caches the step list; re-open so the quarantined step
            # disappears from all_steps()/latest_step() and save() works
            self._mgr.close()
            with self._lock:
                self._mgr = self._ocp.CheckpointManager(
                    self.directory,
                    options=self._ocp.CheckpointManagerOptions(
                        max_to_keep=self._max_to_keep,
                        save_interval_steps=self._save_interval,
                        enable_async_checkpointing=self._async,
                    ),
                )
        # repair the manifest: drop the step's digest and point latest_step
        # at the newest surviving step, so the client-side supervisor never
        # injects a quarantined step as TPX_RESUME_STEP on the next attempt
        survivors = (
            sorted(self._mgr.all_steps(), reverse=True)
            if self._mgr is not None
            else self._pickle_steps()
        )
        self._mutate_manifest(
            latest=survivors[0] if survivors else None, drop_steps=[step]
        )

    def close(self) -> None:
        """Flush in-flight saves (both backends) and release the manager;
        a latched background-write failure surfaces here like at wait()."""
        self.wait()
        if self._mgr is not None:
            self._mgr.close()

    # -- pickle fallback ---------------------------------------------------

    def _pickle_save(self, step: int, state: Any, force: bool = False) -> bool:
        """Snapshot-then-write: the device→host transfer is fenced inside
        this call (after it returns, the train loop may donate/overwrite
        the device buffers), but in async mode serialization, fsync,
        digesting and manifest finalization all happen on a background
        thread — the step loop never stalls on checkpoint I/O."""
        if jax.process_count() > 1:
            # process-0-only pickle files desync hosts on restore (each host
            # must see the same latest step); multi-host requires orbax
            raise RuntimeError(
                "pickle checkpoint fallback is single-process only;"
                " install orbax for multi-host checkpointing"
            )
        if step % self._save_interval and not force:
            return False
        if self._async:
            # at most one write in flight: back-to-back saves fence on the
            # previous write rather than racing it for the manifest
            self._join_writer()
            self._raise_writer_error()
        # the snapshot must OWN its memory: device_get can hand back a
        # view of a live buffer (CPU backend, or an already-host leaf),
        # and the caller is free to donate/overwrite it the moment save()
        # returns — copy ndarray leaves so the background writer
        # serializes the state as of this fence, not of some later step
        host_state = jax.tree.map(_host_copy, state)
        with self._lock:
            self._pending_digests.add(step)
        if not self._async:
            self._pickle_write(step, host_state)
            self._write_manifest(step)
            self._finalize_digests()
            return True
        t = threading.Thread(
            target=self._writer_main,
            args=(step, host_state),
            name=f"tpx-ckpt-writer-{step}",
            daemon=True,
        )
        with self._lock:
            self._writer = t
        t.start()
        return True

    def _writer_main(self, step: int, host_state: Any) -> None:
        """Background finalization of one pickle save. The manifest's
        ``latest_step`` is only advanced AFTER the payload is durably on
        disk, so a crash mid-write can never leave the manifest pointing
        at a step that does not restore."""
        try:
            self._pickle_write(step, host_state)
            self._write_manifest(step)
            self._finalize_digests()
        except BaseException as e:  # noqa: BLE001 - latched, re-raised at wait
            with self._lock:
                self._writer_error = e

    def _join_writer(self) -> None:
        with self._lock:
            t = self._writer
        if t is None or t is threading.current_thread():
            # the writer itself walks the step listing while pruning —
            # never join yourself
            return
        t.join()
        with self._lock:
            self._writer = None

    def _raise_writer_error(self) -> None:
        with self._lock:
            err, self._writer_error = self._writer_error, None
        if err is not None:
            raise RuntimeError(
                "background checkpoint write failed"
            ) from err

    def _pickle_write(self, step: int, host_state: Any) -> None:
        path = os.path.join(self.directory, f"step_{step}.pkl")
        # tmp + fsync + atomic rename: a process killed mid-write (the
        # exact moment a preemption lands) must never leave a truncated
        # step_N.pkl that restore_latest would pick up — the .tmp name
        # never matches the step_N.pkl pattern
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(host_state, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._prune()

    def _pickle_restore(self, step: int, abstract_state: Any) -> Any:
        self._join_writer()  # the requested step may still be in flight
        with open(os.path.join(self.directory, f"step_{step}.pkl"), "rb") as f:
            host_state = pickle.load(f)
        # re-shard onto the current mesh layout
        return jax.tree.map(
            lambda h, a: (
                jax.device_put(h, a.sharding) if hasattr(a, "sharding") else h
            ),
            host_state,
            abstract_state,
        )

    def _prune(self) -> None:
        steps = sorted(self._all_steps())
        pruned = steps[: -self._max_to_keep]
        for old in pruned:
            os.unlink(os.path.join(self.directory, f"step_{old}.pkl"))
        if pruned:
            self._mutate_manifest(drop_steps=pruned)
