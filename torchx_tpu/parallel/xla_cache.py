"""Persistent XLA compilation cache setup.

Compilation dominates launch-to-first-step (the BASELINE north-star): the
1B-model train step costs ~25 s to compile cold but ~4 s with a warm
persistent cache (measured on v5e — docs/performance.md). Every relaunch
— preemption recovery, elastic resize, hyperparameter sweeps over the
same shapes — hits the cache, so the trainer enables it by default.

Set ``TPX_XLA_CACHE_DIR=""`` (empty) to disable, or point it at a shared
filesystem (e.g. a GCS-fused path) so all hosts of a slice — and future
jobs — share one cache.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

ENV_TPX_XLA_CACHE_DIR = "TPX_XLA_CACHE_DIR"
DEFAULT_CACHE_DIR = "~/.cache/tpx/xla"

_configured = False
_cache_dir_used: str | None = None


def setup_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Enable the persistent compilation cache (idempotent).

    Resolution: explicit arg > $TPX_XLA_CACHE_DIR > default under ~/.cache.
    An empty value disables. Returns the directory in use (or None).

    Variant configs of one model (e.g. the int8 bench leg, a remat-policy
    sweep) lower to DISTINCT programs, each with its own cache entry — the
    cache keys on the optimized HLO — so every variant must be allowed to
    persist: the entry-size floor is zeroed and any compile over 1s
    qualifies. A variant's first compile is honest cold time; every
    relaunch after that is a cache hit.
    """
    global _configured, _cache_dir_used
    if _configured:
        return _cache_dir_used
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get(ENV_TPX_XLA_CACHE_DIR, DEFAULT_CACHE_DIR)
    if not cache_dir:
        return None
    cache_dir = os.path.expanduser(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        try:
            # never skip persisting an entry because it is "small": the
            # medium-sized variant programs are exactly the relaunch wins
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:  # noqa: BLE001 - knob absent on older jax
            pass
        _configured = True
        _cache_dir_used = cache_dir
        logger.info("persistent XLA compilation cache at %s", cache_dir)
        return cache_dir
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        logger.warning("could not enable compilation cache: %s", e)
        return None
