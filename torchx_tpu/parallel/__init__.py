"""Parallelism toolkit: mesh model, shardings, pipeline, checkpointing.

Importing the package must stay jax-free — the client-side supervisor
pulls :class:`MeshConfig` from here to compute elastic reshapes, and the
lazy CLI forbids jax at dispatch time — so only the pure-arithmetic shape
model is imported eagerly; the jax-backed helpers resolve on first access.
"""

from torchx_tpu.parallel.mesh_config import MeshConfig  # noqa: F401

_LAZY = ("make_mesh", "named_sharding")


def __getattr__(name):  # noqa: ANN001, ANN202
    if name in _LAZY:
        from torchx_tpu.parallel import mesh

        return getattr(mesh, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
