from torchx_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    make_mesh,
    named_sharding,
)
