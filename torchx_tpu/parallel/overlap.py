"""Bucketed gradient-sync overlap (the ``--grad-bucket-mb`` knob).

The trainer's post-backward gradient reduction is one logical all-reduce
over the whole parameter tree. Fused into a single collective it cannot
start until the *last* backward contribution is ready, so none of it
overlaps compute. This module splits the tree into size-capped buckets in
reverse-layer order — the order backward produces gradients — so each
bucket's reduce can issue as soon as its leaves exist, hiding collective
time behind the rest of the backward pass (TorchTitan's async-TP result,
2410.06511, translated to the JAX scheduling model).

Two execution modes, one semantics:

* **gspmd** (the jax 0.4.x-safe default inside the jit train step) —
  per-bucket :func:`jax.lax.optimization_barrier`. The barrier is a
  value-identity, so gradients are **bitwise identical** to the unbucketed
  step; what changes is scheduling: XLA can no longer fuse the per-leaf
  reduces into one giant post-backward collective, and its
  latency-hiding scheduler overlaps each bucket's reduce with the
  still-running backward. Today's single-sync semantics are preserved by
  construction.
* **manual** (shard_map meshes, and the unit-testable ground truth) —
  :func:`bucketed_psum`: one :func:`jax.lax.psum` per bucket over the
  data-parallel axis, chained through an optimization barrier so buckets
  issue in reverse-layer order. psum is leafwise, so any bucketing —
  including one bucket for the whole tree — produces bitwise-identical
  per-leaf sums; the bucket boundary is pure scheduling.

``resolve_bucket_mb`` picks the cap ``remat_auto``-style: deterministic
candidate ladder, one trial record per candidate, first acceptable
choice wins — the trainer logs the trials next to the remat ones.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

#: Candidate bucket caps (MiB) tried by auto selection, small first —
#: smaller buckets start overlapping earlier in the backward pass.
BUCKET_MB_CANDIDATES: Tuple[int, ...] = (4, 8, 16, 32, 64, 128)

#: Auto selection aims near this many buckets: enough boundaries for the
#: scheduler to overlap, few enough that per-collective launch latency
#: stays amortized.
TARGET_BUCKETS = 8

_MIB = 1024 * 1024


def _nbytes(leaf: Any) -> int:
    """Works for concrete arrays and ShapeDtypeStruct-likes alike."""
    size = getattr(leaf, "size", None)
    if size is None:
        size = math.prod(getattr(leaf, "shape", ()) or (1,))
    return int(size) * jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize


@dataclass(frozen=True)
class BucketPlan:
    """Size-capped grouping of gradient-tree leaves, reverse-layer order.

    ``buckets`` holds tuples of *flattened-leaf indices*; iteration order
    is the issue order (last-produced leaves first). A leaf larger than
    the cap gets a bucket of its own — it cannot be split without
    changing the collective's shape.
    """

    bucket_bytes: int
    buckets: Tuple[Tuple[int, ...], ...]
    total_bytes: int

    @property
    def n_buckets(self) -> int:
        """Number of buckets (== number of per-bucket reduces issued)."""
        return len(self.buckets)

    @property
    def largest_bucket_bytes(self) -> int:
        """Byte size of the largest bucket (the overlap-limiting one)."""
        return self._largest

    def describe(self) -> dict:
        """Loggable summary: cap, bucket count, total and largest MiB."""
        return {
            "bucket_mb": self.bucket_bytes // _MIB,
            "n_buckets": self.n_buckets,
            "total_mb": round(self.total_bytes / _MIB, 3),
            "largest_bucket_mb": round(self._largest / _MIB, 3),
        }

    @property
    def _largest(self) -> int:
        if not self.buckets:
            return 0
        return max(sum(self._leaf_bytes[i] for i in b) for b in self.buckets)

    # populated by plan_buckets (object.__setattr__: frozen dataclass)
    _leaf_bytes: Tuple[int, ...] = ()


def plan_buckets(tree: Any, bucket_bytes: int) -> BucketPlan:
    """Greedy size-capped bucketing of ``tree``'s leaves in **reverse**
    flatten order (backward finishes the last layers first, so reverse
    order approximates gradient-ready order under ``lax.scan`` stacking).

    Deterministic: same tree structure + cap -> same plan, so the bucket
    layout never perturbs compilation caches between runs.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    sizes = tuple(_nbytes(leaf) for leaf in leaves)
    buckets: list[Tuple[int, ...]] = []
    cur: list[int] = []
    cur_bytes = 0
    for idx in reversed(range(len(leaves))):
        nb = sizes[idx]
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(idx)
        cur_bytes += nb
        if cur_bytes >= bucket_bytes:  # oversize leaf: own bucket
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(tuple(cur))
    plan = BucketPlan(
        bucket_bytes=int(bucket_bytes),
        buckets=tuple(buckets),
        total_bytes=sum(sizes),
    )
    object.__setattr__(plan, "_leaf_bytes", sizes)
    return plan


@dataclass(frozen=True)
class BucketTrial:
    """One auto-selection candidate, recorded remat_auto-style so the
    trainer can log why a cap was (not) chosen."""

    bucket_mb: int
    n_buckets: int
    largest_bucket_mb: float
    chosen: bool
    reason: str

    def to_dict(self) -> dict:
        """JSON form for the trainer's results / bench trial logs."""
        return {
            "bucket_mb": self.bucket_mb,
            "n_buckets": self.n_buckets,
            "largest_bucket_mb": self.largest_bucket_mb,
            "chosen": self.chosen,
            "reason": self.reason,
        }


def resolve_bucket_mb(
    tree: Any,
    requested: Any = "auto",
    candidates: Sequence[int] = BUCKET_MB_CANDIDATES,
) -> Tuple[int, Tuple[BucketTrial, ...]]:
    """Resolve a ``--grad-bucket-mb`` request against a gradient tree.

    An explicit positive integer passes through (one trial record).
    ``"auto"``/``0`` walks the candidate ladder smallest-first and picks
    the first cap yielding at most :data:`TARGET_BUCKETS` buckets — the
    smallest cap (earliest overlap) that does not shred the tree into
    latency-dominated confetti. Falls back to the largest candidate.
    """
    if requested not in ("auto", 0, "0", None):
        mb = int(requested)
        if mb <= 0:
            raise ValueError(f"--grad-bucket-mb must be positive, got {mb}")
        plan = plan_buckets(tree, mb * _MIB)
        trial = BucketTrial(
            bucket_mb=mb,
            n_buckets=plan.n_buckets,
            largest_bucket_mb=round(plan.largest_bucket_bytes / _MIB, 3),
            chosen=True,
            reason="explicit --grad-bucket-mb",
        )
        return mb, (trial,)

    trials: list[BucketTrial] = []
    chosen: Optional[int] = None
    for mb in candidates:
        plan = plan_buckets(tree, mb * _MIB)
        ok = plan.n_buckets <= TARGET_BUCKETS
        pick = ok and chosen is None
        if pick:
            chosen = mb
        trials.append(
            BucketTrial(
                bucket_mb=mb,
                n_buckets=plan.n_buckets,
                largest_bucket_mb=round(plan.largest_bucket_bytes / _MIB, 3),
                chosen=pick,
                reason=(
                    "first cap with <= %d buckets" % TARGET_BUCKETS
                    if pick
                    else (
                        "acceptable but a smaller cap was already chosen"
                        if ok
                        else "too many buckets (collective launch latency)"
                    )
                ),
            )
        )
    if chosen is None:  # tiny trees: even the largest cap over-fragments
        chosen = candidates[-1]
        trials[-1] = BucketTrial(
            bucket_mb=chosen,
            n_buckets=trials[-1].n_buckets,
            largest_bucket_mb=trials[-1].largest_bucket_mb,
            chosen=True,
            reason="largest candidate (fallback)",
        )
    return chosen, tuple(trials)


def _axis_bound(name: str) -> bool:
    """Is ``name`` a usable collective axis here? Modern JAX exposes the
    enclosing manual region via the abstract mesh
    (:func:`torchx_tpu.parallel.mesh.manual_axes`); the 0.4.x tracer
    never populates that inside the legacy shard_map, but its axis env
    does know every bound axis name."""
    from torchx_tpu.parallel.mesh import manual_axes

    if name in manual_axes():
        return True
    try:
        from jax._src.core import get_axis_env

        return bool(get_axis_env().axis_exists(name))
    except Exception:  # pragma: no cover - core API drift
        return False


def _apply_bucketed(leaves: list, plan: BucketPlan, combine) -> list:
    """Shared walk: run ``combine(tuple_of_values, anchor)`` per bucket in
    plan order, threading an anchor value so bucket i+1 cannot issue
    before bucket i. ``combine`` returns the replacement values."""
    out = list(leaves)
    anchor = None
    for bucket in plan.buckets:
        vals = tuple(out[i] for i in bucket)
        vals = combine(vals, anchor)
        for i, v in zip(bucket, vals):
            out[i] = v
        anchor = vals[0]
    return out


def apply_bucketed_barriers(grads: Any, plan: BucketPlan) -> Any:
    """GSPMD mode: value-identity barriers at bucket boundaries.

    Bitwise-safe (optimization_barrier changes scheduling, never values):
    the partitioner still inserts the same per-leaf reduces, but can no
    longer fuse them across bucket boundaries, and the chained anchor
    fixes their issue order to reverse-layer.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)

    def combine(vals, anchor):
        if anchor is not None:
            vals = jax.lax.optimization_barrier(tuple(vals) + (anchor,))[:-1]
        return jax.lax.optimization_barrier(vals)

    return jax.tree_util.tree_unflatten(
        treedef, _apply_bucketed(leaves, plan, combine)
    )


def bucketed_psum(grads: Any, axis_name: Any, plan: BucketPlan) -> Any:
    """Manual mode (inside shard_map): one psum per bucket, issue-ordered.

    psum is leafwise, so the per-leaf results are bitwise identical to a
    single whole-tree psum regardless of bucket size — the property the
    bucket-boundary tests pin down.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)

    def combine(vals, anchor):
        if anchor is not None:
            vals = jax.lax.optimization_barrier(tuple(vals) + (anchor,))[:-1]
        return jax.lax.psum(vals, axis_name)

    return jax.tree_util.tree_unflatten(
        treedef, _apply_bucketed(leaves, plan, combine)
    )


def bucketed_sync(
    grads: Any,
    *,
    bucket_mb: int,
    mode: str = "auto",
    axis_name: Any = "dp",
    plan: Optional[BucketPlan] = None,
) -> Tuple[Any, Optional[BucketPlan]]:
    """Bucket the gradient tree and apply the mode's per-bucket sync.

    ``bucket_mb <= 0`` is the off switch: grads pass through untouched
    (exactly today's single-sync step). ``mode``:

    * ``"auto"`` — ``"manual"`` inside a shard_map region that has the
      reduce axis bound manually, else ``"gspmd"``. The jit train step on
      jax 0.4.x lands on gspmd: the GSPMD-safe fallback that preserves
      single-sync semantics bit for bit.
    * ``"gspmd"`` — :func:`apply_bucketed_barriers` (no collectives of
      its own; the partitioner owns the reduces).
    * ``"manual"`` — :func:`bucketed_psum` over ``axis_name``.

    Returns ``(grads, plan)``; plan is None when bucketing is off.
    """
    if bucket_mb is None or int(bucket_mb) <= 0:
        return grads, None
    if plan is None:
        plan = plan_buckets(grads, int(bucket_mb) * _MIB)
    if mode == "auto":
        names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        mode = (
            "manual"
            if names and all(_axis_bound(n) for n in names)
            else "gspmd"
        )
    if mode == "manual":
        return bucketed_psum(grads, axis_name, plan), plan
    if mode == "gspmd":
        return apply_bucketed_barriers(grads, plan), plan
    raise ValueError(f"unknown bucketed_sync mode {mode!r}")
