"""AOT memory-fit analysis: does a training config fit the target HBM?

PJRT topology descriptions let the flagship train step — splash attention,
dots remat, chunked CE, AdamW, real fsdp/tp shardings — be compiled for a
TPU slice with no hardware attached; the compiler's buffer assignment
(``compiled.memory_analysis()``) then answers the only question that
matters before renting a pod: *does the north-star config fit per-device
HBM?* The same entry points compile on the CPU backend (CI has no libtpu),
where the xla-attention fallback materializes [b, h, s, s] logits — CPU
numbers are therefore a conservative upper bound of the TPU ones.

Used by ``scripts/aot_memory_fit.py`` (the operator CLI that prints the
fit table in docs/performance.md) and ``tests/test_aot_fit.py`` (CI gate).

Reference analog: none — meta-pytorch/torchx has no model/perf stack; this
validates the BASELINE.json north-star (Llama-3-8B >= 45% MFU on v5p-32).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GIB = 1024**3

# v5p HBM per chip; the fit leaves headroom for runtime scratch + infeed
# buffers the buffer assignment does not cover
V5P_HBM_BYTES = 95 * GIB
DEFAULT_HEADROOM = 0.9


def tpu_topology_mesh(topology: str, mesh_axes: Any) -> Mesh:
    """Mesh over the compile-only devices of a TPU slice description.

    ``topology`` is a PJRT topology string like ``v5p:2x2x4`` (the 16-chip
    v5p-32 slice) or ``v5e:4x4``; requires a TPU-capable PJRT plugin.
    """
    from jax.experimental import topologies

    from torchx_tpu.parallel.mesh import make_mesh

    topo = topologies.get_topology_desc(topology, "tpu")
    return make_mesh(mesh_axes, devices=topo.devices)


def _specs_for_state(state_shapes: Any, param_specs: Any) -> Any:
    """PartitionSpec tree matching a TrainState shape tree.

    Optimizer-state subtrees that mirror the params tree (Adam's mu/nu)
    inherit the param specs wholesale; everything else (step counters,
    empty states) replicates. Matching is by pytree structure, so this
    stays correct for any optax chain whose stateful members mirror params.
    """
    params_treedef = jtu.tree_structure(state_shapes.params)

    def rec(node: Any) -> Any:
        try:
            if jtu.tree_structure(node) == params_treedef:
                return param_specs
        except Exception:
            pass
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # namedtuple
            return type(node)(*(rec(c) for c in node))
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c) for c in node)
        return P()  # scalar / unrecognized leaf: replicated

    return dataclasses.replace(
        state_shapes,
        params=param_specs,
        opt_state=rec(state_shapes.opt_state),
        step=P(),
    )


def abstract_train_state(cfg: Any, mesh: Mesh, optimizer: Any):
    """TrainState of ShapeDtypeStructs carrying the training shardings."""
    from torchx_tpu.examples.train_llama import TrainState
    from torchx_tpu.models import llama

    init_fn, specs_fn = llama.model_fns(cfg)  # dense vs MoE dispatch
    params_shapes = jax.eval_shape(
        lambda: init_fn(cfg, jax.random.PRNGKey(0))
    )
    opt_shapes = jax.eval_shape(optimizer.init, params_shapes)
    state_shapes = TrainState(
        params=params_shapes,
        opt_state=opt_shapes,
        step=jax.ShapeDtypeStruct((), jnp.int32),
    )
    pspecs = specs_fn(cfg, pp=mesh.shape.get("pp", 1) > 1)
    spec_tree = _specs_for_state(state_shapes, pspecs)
    return jax.tree.map(
        lambda s, p: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, p)
        ),
        state_shapes,
        spec_tree,
    )


@dataclasses.dataclass
class FitResult:
    batch: int
    seq: int
    remat_policy: str
    args_bytes: int  # per-device params + opt state + batch
    temp_bytes: int  # per-device activations / workspace
    peak_bytes: int  # per-device worst case (see compile_fit)
    fits: bool
    generated_code_bytes: int = 0

    def row(self) -> str:
        """This result as one markdown fit-table row."""
        return (
            f"| {self.batch} | {self.seq} | {self.remat_policy} "
            f"| {self.args_bytes / GIB:.1f} | {self.temp_bytes / GIB:.1f} "
            f"| {self.peak_bytes / GIB:.1f} | "
            f"{'yes' if self.fits else 'NO'} |"
        )


def compile_fit(
    cfg: Any,
    mesh: Mesh,
    batch: int,
    seq: int,
    hbm_bytes: int = V5P_HBM_BYTES,
    headroom: float = DEFAULT_HEADROOM,
) -> FitResult:
    """AOT-compile one (config, mesh, batch, seq) and read the memory fit."""
    from torchx_tpu.examples.train_llama import make_optimizer, make_train_step
    from torchx_tpu.parallel.mesh import BATCH_SPEC

    cfg = dataclasses.replace(cfg, max_seq=seq)
    optimizer = make_optimizer(warmup=100)
    state_sds = abstract_train_state(cfg, mesh, optimizer)
    batch_sds = {
        "tokens": jax.ShapeDtypeStruct(
            (batch, seq + 1),
            jnp.int32,
            sharding=NamedSharding(mesh, BATCH_SPEC),
        )
    }
    step = make_train_step(cfg, mesh, optimizer)
    compiled = step.lower(state_sds, batch_sds).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        raise RuntimeError("backend returned no memory analysis")
    peak = getattr(ma, "peak_memory_in_bytes", 0)
    # arguments (params/opt state) are resident for the whole step whether
    # or not the peak_memory accounting includes them, so the fit test uses
    # the conservative max(live-buffer peak, args + temps)
    resident = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    worst = max(peak, resident)
    return FitResult(
        batch=batch,
        seq=seq,
        remat_policy=cfg.remat_policy,
        args_bytes=ma.argument_size_in_bytes,
        temp_bytes=ma.temp_size_in_bytes,
        peak_bytes=worst,
        fits=worst <= hbm_bytes * headroom,
        generated_code_bytes=ma.generated_code_size_in_bytes,
    )


def north_star_cfg(attn_impl: str = "splash") -> Any:
    """llama3_8b exactly as the 45%-MFU claim trains it: bf16, dots remat,
    splash attention at the measured 512/512 tiles, chunked logsumexp CE
    with bf16 logits (docs/performance.md round-4 levers)."""
    from torchx_tpu.models import llama

    return llama.llama3_8b(
        remat=True,
        remat_policy="dots",
        attn_impl=attn_impl,
        attn_block_q=512,
        attn_block_kv=512,
        loss_chunk=2048,
    )


def model_state_bytes_per_device(cfg: Any, n_devices: int) -> int:
    """Analytic params + Adam moments bytes per device (all fsdp/tp-sharded
    at scale): 3x the bf16 param bytes spread over the mesh."""
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return 3 * cfg.param_count() * itemsize // n_devices


def probe_fits(requests: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Batch :func:`compile_fit` for the ``tpx tune`` AOT prune stage.

    One jax process serves the whole candidate batch (the tune driver is
    jax-free; spawning one interpreter per candidate would pay the jax
    import tax N times). Each request dict carries ``config`` (builtin
    name), ``mesh_spec``, ``batch``, ``seq`` and optionally
    ``remat_policy``, ``int8_scope``, ``hbm_bytes``, ``headroom``; each
    result mirrors :class:`FitResult` plus the echoed request, or carries
    ``error`` — per-candidate failures never kill the batch.
    """
    from torchx_tpu.examples.train_llama import all_configs
    from torchx_tpu.parallel.mesh import make_mesh
    from torchx_tpu.parallel.mesh_config import MeshConfig, parse_mesh_spec

    configs = all_configs()
    out: list[dict[str, Any]] = []
    for req in requests:
        result: dict[str, Any] = {"request": req}
        try:
            overrides: dict[str, Any] = {}
            if req.get("remat_policy"):
                overrides["remat_policy"] = req["remat_policy"]
            scope = req.get("int8_scope") or "none"
            if scope != "none":
                overrides["int8_matmuls"] = True
                overrides["int8_scope"] = scope
            cfg = configs[req["config"]](**overrides)
            mesh_cfg = (
                parse_mesh_spec(req["mesh_spec"])
                if req.get("mesh_spec")
                else MeshConfig()
            )
            mesh = make_mesh(mesh_cfg)
            r = compile_fit(
                cfg,
                mesh,
                int(req["batch"]),
                int(req["seq"]),
                hbm_bytes=int(req.get("hbm_bytes") or V5P_HBM_BYTES),
                headroom=float(req.get("headroom") or DEFAULT_HEADROOM),
            )
            result.update(
                {
                    "fits": r.fits,
                    "args_bytes": int(r.args_bytes),
                    "temp_bytes": int(r.temp_bytes),
                    "peak_bytes": int(r.peak_bytes),
                    "remat_policy": r.remat_policy,
                }
            )
        except Exception as e:  # noqa: BLE001 - advisory batch probe
            result["error"] = f"{type(e).__name__}: {e}"
        out.append(result)
    return out


def _probe_main() -> int:
    """``python -m torchx_tpu.parallel.aot_fit``: JSON requests on stdin,
    one JSON results line on stdout (the tune driver's subprocess ABI)."""
    import json
    import sys

    requests = json.load(sys.stdin)
    if not isinstance(requests, list):
        raise SystemExit("expected a JSON list of probe requests on stdin")
    print(json.dumps(probe_fits(requests)), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(_probe_main())
