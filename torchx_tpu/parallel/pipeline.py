"""Pipeline parallelism: GPipe-style microbatched execution over a mesh axis.

Completes the parallelism checklist (docs/architecture.md §2.8): the
stacked-layer axis of a model's parameters shards over a ``pp`` mesh axis
(each device owns a contiguous stage of layers), the batch splits into
microbatches, and activations flow stage-to-stage via ``lax.ppermute``
inside ``shard_map`` — the classic bubble schedule: step t runs microbatch
``t - stage`` on each stage, total ``n_micro + n_stages - 1`` steps, bubble
fraction ``(S-1)/(M+S-1)``.

The primitive is generic over the layer body (the same signature
``body(x, layer_params) -> x`` that ``llama._layer`` partials down to), and
differentiable end-to-end (ppermute's transpose is the reverse permute;
the scan saves per-step activations for backward — combine with
``jax.checkpoint`` on the body for long pipelines).

Usage::

    mesh = make_pp_mesh(n_stages)                   # 1-axis ("pp") mesh
    y = pipeline_apply(body, stacked_params, x, mesh, n_microbatches=8)

``stacked_params`` leaves have a leading layer axis divisible by
``n_stages``; ``x`` is [batch, ...] with batch divisible by
``n_microbatches``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

LayerBody = Callable[[jnp.ndarray, Any], jnp.ndarray]


def make_pp_mesh(n_stages: int, devices=None) -> Mesh:  # noqa: ANN001
    """A 1-axis ("pp",) mesh over the first ``n_stages`` devices."""
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()[:n_stages]
    if len(devs) < n_stages:
        raise ValueError(f"need {n_stages} devices for {n_stages} stages")
    return Mesh(np.array(devs[:n_stages]), ("pp",))


def _stage_apply(
    body: LayerBody, local_layers: Any, x: jnp.ndarray, with_aux: bool = False
):
    """Run this stage's local slice of layers (scan over the local stack).

    With ``with_aux`` the body returns ``(x, aux)`` — a scalar or any
    fixed-shape array (e.g. the MoE router-health vector) — and the
    per-layer aux values are summed over the stage's local stack. Aux
    rides the scan's stacked OUTPUTS rather than the carry so its shape
    never needs declaring up front.
    """
    if not with_aux:

        def step(h, layer_slice):  # noqa: ANN001
            return body(h, layer_slice), None

        out, _ = jax.lax.scan(step, x, local_layers)
        return out

    def step_aux(h, layer_slice):  # noqa: ANN001
        h, aux = body(h, layer_slice)
        return h, jnp.asarray(aux, jnp.float32)

    out, aux_stack = jax.lax.scan(step_aux, x, local_layers)
    return out, aux_stack.sum(axis=0)


def _pipeline_shard(
    body: LayerBody,
    n_micro: int,
    with_aux: bool,
    extra_axes: tuple,  # manual axes beyond pp (e.g. ("sp",))
    local_layers: Any,  # leaves [L/S, ...] — this stage's layers
    x: jnp.ndarray,  # [n_micro, mb, ...] microbatched input (replicated)
):
    """Runs inside shard_map over ("pp", *extra_axes)."""
    n_stages = jax.lax.psum(1, "pp")
    stage = jax.lax.axis_index("pp")
    mb_shape = x.shape[1:]
    total_steps = n_micro + n_stages - 1
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def step(carry, t):  # noqa: ANN001
        prev_out, outputs = carry
        # stage 0 feeds microbatch t (clamped; garbage beyond M is masked by
        # the output indexing), later stages receive the previous stage's
        # output shifted forward one hop
        x_t = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        incoming = jax.lax.ppermute(prev_out, "pp", fwd_perm)
        my_in = jnp.where(stage == 0, x_t, incoming)
        aux_t = None
        if with_aux:
            my_out, aux_t = _stage_apply(body, local_layers, my_in, with_aux=True)
            # this stage holds real data only for steps in [stage,
            # stage + n_micro); aux from warmup/drain garbage is masked out
            valid = (t >= stage) & (t - stage < n_micro)
            aux_t = jnp.where(valid, aux_t, jnp.zeros_like(aux_t))
        else:
            my_out = _stage_apply(body, local_layers, my_in)
        # the last stage finished microbatch (t - (S-1)) at step t; before
        # then, keep the existing (zero) slot so warmup garbage is masked
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        current = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        slot = jnp.where(t >= n_stages - 1, my_out, current)
        updated = jax.lax.dynamic_update_index_in_dim(outputs, slot, out_idx, axis=0)
        return (my_out, updated), aux_t

    outputs0 = jnp.zeros((n_micro, *mb_shape), dtype=x.dtype)
    prev0 = jnp.zeros(mb_shape, dtype=x.dtype)
    (_, outputs), aux_stack = jax.lax.scan(
        step, (prev0, outputs0), jnp.arange(total_steps)
    )
    # only the last stage holds real outputs; broadcast them to all stages
    outputs = jnp.where(stage == n_stages - 1, outputs, 0)
    outputs = jax.lax.psum(outputs, "pp")
    if with_aux:
        # sum per-layer aux across stages; each microbatch's aux is a mean
        # over its own tokens, so average over microbatches to match the
        # non-pp semantics (per-layer aux = mean over the full batch)
        aux_total = jax.lax.psum(aux_stack.sum(axis=0), "pp") / n_micro
        if extra_axes:
            # the aux out_spec is P() (replicated), but each extra-axis
            # shard (e.g. an sp sequence shard) computed aux over its OWN
            # tokens — average them so the assembled global value is the
            # full-batch mean rather than one arbitrary shard's
            aux_total = jax.lax.pmean(aux_total, extra_axes)
        return outputs, aux_total
    return outputs


def pipeline_apply(
    body: LayerBody,
    stacked_params: Any,  # leaves [L, ...]
    x: jnp.ndarray,  # [batch, ...]
    mesh: Mesh,
    n_microbatches: int,
    with_aux: bool = False,
    manual_axes: frozenset = frozenset(),
    x_spec: P | None = None,
):
    """Apply L stacked layers to x, pipelined over the mesh's "pp" axis.

    With ``with_aux`` the body returns ``(x, aux)`` per layer — a scalar
    or any fixed-shape array (e.g. the MoE router-health vector) — and
    the call returns ``(out, aux_total)``
    where aux_total sums layers and averages microbatches. For aux linear
    in the microbatch mean this equals the non-pipelined scan exactly; for
    nonlinear aux (MoE balancing) it is the group-wise variant computed per
    microbatch — equivalent balancing pressure, not bitwise loss parity.

    ``manual_axes`` adds mesh axes beyond ``pp`` to the manual region, and
    ``x_spec`` (a spec for the un-microbatched ``[batch, ...]`` x over
    those axes) shards the activations into it. A body that runs its own
    collectives over an axis — ring attention over ``sp`` — must be
    manualized HERE, at the single shard_map: nesting a second shard_map
    inside the stage body would rebind ``pp`` and is rejected by Shardy's
    verifier. The batch entry of ``x_spec`` must be None (microbatching
    reshapes it); dp/fsdp/tp stay automatic inside the stage either way.
    """
    n_stages = mesh.shape["pp"]
    leaves = jax.tree.leaves(stacked_params)
    n_layers = leaves[0].shape[0]
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {n_microbatches} microbatches"
        )
    mb = batch // n_microbatches
    x_micro = x.reshape(n_microbatches, mb, *x.shape[1:])

    if x_spec is not None and len(x_spec) > 0 and x_spec[0] is not None:
        raise ValueError(
            f"x_spec batch entry must be None, got {x_spec}: the batch axis "
            "is reshaped into (microbatch, mb) and cannot be manual-sharded"
        )
    # spec for the microbatched x: (n_micro, mb, *feature axes) — the two
    # leading axes replicated over the manual axes, feature entries from
    # x_spec (e.g. the sequence axis over "sp")
    feature_spec = tuple(x_spec)[1:] if x_spec is not None else ()
    micro_spec = P(None, None, *feature_spec)

    layer_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    # partial manualization: pp (and any caller-requested axes, e.g. sp for
    # in-stage ring attention) go manual; other mesh axes (dp/fsdp/tp)
    # remain automatic so the partitioner keeps sharding the math inside
    # each stage
    from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

    fn = tpx_shard_map(
        functools.partial(
            _pipeline_shard,
            body,
            n_microbatches,
            with_aux,
            tuple(sorted(manual_axes)),
        ),
        mesh=mesh,
        in_specs=(layer_specs, micro_spec),  # layers sharded by stage
        out_specs=(micro_spec, P()) if with_aux else micro_spec,
        axis_names=frozenset({"pp"}) | manual_axes,
        check_vma=False,
    )
    if with_aux:
        out, aux_total = fn(stacked_params, x_micro)
        return out.reshape(batch, *out.shape[2:]), aux_total
    out = fn(stacked_params, x_micro)
    return out.reshape(batch, *out.shape[2:])
