"""Automatic rematerialization policy selection from AOT memory analysis.

The remat policy is a pure memory/recompute trade: ``dots_attn`` saves the
most activations (cheapest backward, biggest footprint), ``dots`` drops
the attention-kernel outputs, ``full`` recomputes everything. Today the
right choice depends on batch, sequence, mesh, and model size — picking it
by hand means either OOMing at scale or paying recompute FLOPs the HBM
could have absorbed.

``remat_policy="auto"`` resolves the choice at launch: each candidate
policy (cheapest recompute first) is AOT-lowered and compiled against
abstract inputs, the compiler's buffer assignment
(``compiled.memory_analysis()``, same accounting as
:mod:`torchx_tpu.parallel.aot_fit`) is checked against the device HBM
budget, and the first policy that fits wins. The trial compiles land in
the persistent XLA compilation cache, so the winner's real compile in the
trainer is a cache hit — the selection's marginal cost is roughly the
compiles of the candidates that did NOT fit.

The trainer (examples/train_llama.py) resolves "auto" before building the
train step and reports the chosen policy in its result dict and the
``step.*`` trace family; :mod:`bench` records it per bench leg.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh

from torchx_tpu.parallel.aot_fit import (
    DEFAULT_HEADROOM,
    FitResult,
    V5P_HBM_BYTES,
    compile_fit,
)

#: candidate policies, cheapest recompute (largest footprint) first — the
#: selection order: stop at the first one whose compiled step fits.
POLICY_ORDER: tuple[str, ...] = ("dots_attn", "dots", "full")


@dataclasses.dataclass
class PolicyTrial:
    """One candidate policy's fit verdict (for logs / bench JSON)."""

    policy: str
    fits: bool
    peak_bytes: int  # 0 when the trial compile failed
    error: Optional[str] = None


def device_hbm_bytes(default: int = V5P_HBM_BYTES) -> int:
    """Per-device HBM budget: the addressable device's ``bytes_limit``
    when the runtime reports one (TPU/GPU), else ``default`` (CPU and
    compile-only backends report nothing useful — there the v5p budget
    keeps auto-selection meaningful in dryruns)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return default
    if stats and stats.get("bytes_limit", 0) > 0:
        return int(stats["bytes_limit"])
    return default


def choose_remat_policy(
    cfg: Any,
    mesh: Mesh,
    batch: int,
    seq: int,
    *,
    hbm_bytes: Optional[int] = None,
    headroom: float = DEFAULT_HEADROOM,
    fit_fn: Optional[Callable[[Any], FitResult]] = None,
) -> tuple[str, list[PolicyTrial]]:
    """Resolve ``remat_policy="auto"`` -> a concrete policy for this run.

    Tries :data:`POLICY_ORDER` in sequence and returns the first policy
    whose AOT-compiled train step fits ``hbm_bytes * headroom`` per
    device, plus the trial records for reporting. If nothing fits (or
    every trial compile fails) the answer is ``"full"`` — maximal
    recompute is the only remaining lever, and the real compile will
    surface the OOM with its own diagnostics.

    ``fit_fn`` overrides the fit oracle (a callable taking the candidate
    config and returning a :class:`~torchx_tpu.parallel.aot_fit.FitResult`)
    — tests inject mocked memory analyses; the default AOT-compiles via
    :func:`~torchx_tpu.parallel.aot_fit.compile_fit`.
    """
    budget = hbm_bytes if hbm_bytes is not None else device_hbm_bytes()
    if fit_fn is None:
        fit_fn = lambda c: compile_fit(  # noqa: E731
            c, mesh, batch, seq, hbm_bytes=budget, headroom=headroom
        )
    trials: list[PolicyTrial] = []
    for policy in POLICY_ORDER:
        candidate = dataclasses.replace(cfg, remat=True, remat_policy=policy)
        try:
            res = fit_fn(candidate)
        except Exception as e:  # noqa: BLE001 - a failed trial is a verdict
            trials.append(
                PolicyTrial(policy=policy, fits=False, peak_bytes=0, error=str(e))
            )
            continue
        trials.append(
            PolicyTrial(policy=policy, fits=res.fits, peak_bytes=res.peak_bytes)
        )
        if res.fits:
            return policy, trials
    return "full", trials
