from torchx_tpu.runtime.tracking.api import (  # noqa: F401
    FsspecResultTracker,
    ResultTracker,
)
