"""In-job result tracking (the older, simpler sibling of the tracker
subsystem).

Reference analog: torchx/runtime/tracking/api.py:20-126 — a minimal
put/get store for per-trial results keyed ``(run_id, key)``, used by hpo
loops that just need "write the objective value where the client can read
it". For anything richer use ``torchx_tpu.tracker.AppRun``.

Usage (in the app)::

    tracker = FsspecResultTracker("/mnt/results")
    tracker[trial_id] = {"loss": 0.12, "mfu": 0.46}

and (in the client)::

    print(FsspecResultTracker("/mnt/results")[trial_id])
"""

from __future__ import annotations

import abc
import json
import os
from typing import Any, Mapping, Optional


class ResultTracker(abc.ABC):
    @abc.abstractmethod
    def put(self, key: str, value: Mapping[str, Any]) -> None:
        ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[Mapping[str, Any]]:
        ...

    def __setitem__(self, key: Any, value: Mapping[str, Any]) -> None:
        self.put(str(key), value)

    def __getitem__(self, key: Any) -> Mapping[str, Any]:
        result = self.get(str(key))
        if result is None:
            raise KeyError(key)
        return result


class FsspecResultTracker(ResultTracker):
    """One JSON file per key under a root dir/URL."""

    def __init__(self, root: str) -> None:
        self._root = str(root).rstrip("/")

    def _path(self, key: str) -> str:
        import urllib.parse

        return f"{self._root}/{urllib.parse.quote(key, safe='')}.json"

    def _open(self, path: str, mode: str):  # noqa: ANN202
        if "://" in self._root:
            import fsspec

            return fsspec.open(path, mode).open()
        if "w" in mode:
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)

    def put(self, key: str, value: Mapping[str, Any]) -> None:
        with self._open(self._path(key), "w") as f:
            json.dump(dict(value), f, default=str)

    def get(self, key: str) -> Optional[Mapping[str, Any]]:
        try:
            with self._open(self._path(key), "r") as f:
                return json.load(f)
        except (OSError, FileNotFoundError):
            return None
