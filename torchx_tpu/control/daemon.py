"""``tpx control`` — the multi-tenant control-plane daemon.

One long-lived localhost process owns a Runner, a
:class:`~torchx_tpu.control.reconciler.Reconciler` (all watch streams),
and the sharded :class:`~torchx_tpu.control.store.JobStateStore`, and
serves the launcher verbs over plain JSON HTTP (the stdlib
ThreadingHTTPServer idiom the serving stack already uses). Every CLI on
the machine then shares ONE describe path and ONE event stream per
backend instead of each running its own poll loop.

API (JSON; Bearer-token auth on every ``/v1`` route):

    GET  /healthz                 -> {"status": "ok", "jobs": N, ...}
    GET  /metricz                 -> tpx_* metrics, Prometheus text
    POST /v1/session  {"tenant"}  -> {"token"}          (root token only)
    POST /v1/submit   {"component", "args", "scheduler", "cfg", ...}
                                  -> {"handle"} | 429 past the tenant cap
    GET  /v1/status?handle=       -> {"state", "terminal", ...} | 404
    GET  /v1/list[?scheduler=]    -> {"apps": [...]}
    POST /v1/cancel   {"handle"}  -> {"ok": true}
    GET  /v1/wait?handle=&timeout= -> bounded long-poll; returns the
                                  status when terminal or when the budget
                                  expires ({"terminal": false})
    GET  /v1/logs?handle=&role=&k= -> JSONL line stream (log attach)
    GET  /v1/queue                -> fleet queue + placements snapshot
                                  ({"enabled": false} without --fleet)
    GET  /v1/metrics/query?name=&reduce=&range=&label.K=V
                                  -> telemetry series + reduced scalars
                                  (no ``name``: {"names": [...]})
    GET  /v1/alerts               -> active SLO alerts + last burn rates
    POST /v1/metrics/targets {"url", "name"?, "remove"?}
                                  -> register/remove a /metricz scrape
    POST /v1/pipelines {"spec"}   -> {"pipeline"}: submit a train→eval→
                                  promote DAG to the pipeline engine
    GET  /v1/pipelines[?pipeline=] -> one pipeline's full record, or all
    POST /v1/pipelines/cancel {"pipeline"} -> the cancelled record
    GET  /v1/cell                 -> federation identity + lifecycle:
                                  {"cell", "state", "draining",
                                  "rehydrated", "rehydration", "inflight"}
    POST /v1/cell/drain           -> begin draining (durable): in-flight
                                  work finishes, new submits bounce 503
    POST /v1/cell/uncordon        -> reopen a drained cell for traffic

Every daemon is one federation *cell* (``--cell``/``$TPX_CELL``,
default ``default``): journal records carry the cell name, ``/healthz``
reports rehydration progress so a router can tell "booting, journal
replaying" from "healthy", and the drain verbs drive the
HEALTHY → DRAINING → DRAINED → UNCORDONED lifecycle the
:mod:`torchx_tpu.federation` router keys off. While draining, submit
verbs (``/v1/submit``, ``/v1/pipelines``) refuse with 503 +
``{"code": "cell_draining"}`` — a deliberate *don't-retry-here* verdict:
the federation router spills the request to the next-best cell instead.

The daemon also hosts the fleet **telemetry plane**: a
:class:`~torchx_tpu.obs.telemetry.Collector` scrapes registered replica
``/metricz`` targets and every obs session's textfiles into a bounded
:class:`~torchx_tpu.obs.telemetry.MetricStore` (plus the daemon's own
registry, source ``control``), ``/metricz`` serves the cross-source
aggregate, and an optional :class:`~torchx_tpu.obs.slo.SloEngine`
(``--slo`` specs) evaluates burn rates each cycle, journals alert
transitions to ``state_dir/slo_alerts.jsonl``, and feeds the fleet
market its SLO signal.

Security model: the daemon binds loopback only. At start it mints a root
token and records ``{"addr", "token", "pid"}`` in a 0600 discovery file
(``$TPX_CONTROL_DIR/control.json``) — same-user CLIs find the daemon
through it (:func:`torchx_tpu.control.client.maybe_client`). The root
token can mint per-tenant session tokens (``/v1/session``); each tenant
is capped at ``tenant_cap`` concurrently *active* (non-terminal) jobs,
submits past the cap get 429 (with a ``Retry-After`` hint and a stable
JSON error body) and the caller's retry policy decides.

With a :class:`~torchx_tpu.fleet.api.FleetScheduler` attached (``tpx
control --fleet``), ``/v1/submit`` stops bouncing: the submit is
dryrun-validated, serialized into a resubmission recipe, and handed to
the fleet queue — the reply is either ``{"handle"}`` (placed now) or
``{"queued": true, "position": N}``. The daemon implements the
scheduler's executor seam (materialize + run + reconciler tracking) and
feeds it every watch event, so a terminal job immediately re-runs the
placement loop.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

from torchx_tpu import settings
from torchx_tpu.control.events import StateEvent
from torchx_tpu.control.reconciler import Reconciler
from torchx_tpu.control.store import JobStateStore
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.specs.api import AppState

logger = logging.getLogger(__name__)

DISCOVERY_FILE = "control.json"


def control_dir() -> str:
    """State root for the control plane: ``$TPX_CONTROL_DIR``, default
    ``~/.torchx_tpu/control``."""
    raw = os.environ.get(settings.ENV_TPX_CONTROL_DIR)
    if raw and raw.strip():
        return raw
    return os.path.join(os.path.expanduser("~"), ".torchx_tpu", "control")


class _DaemonError(Exception):
    """Maps straight to an HTTP error reply.

    ``payload`` keys are merged into the JSON error body (stable,
    machine-readable fields next to the human ``error`` string);
    ``headers`` become response headers (e.g. ``Retry-After``)."""

    def __init__(
        self,
        code: int,
        message: str,
        payload: Optional[dict] = None,
        headers: Optional[dict] = None,
    ) -> None:
        super().__init__(message)
        self.code = code
        self.message = message
        self.payload = dict(payload or {})
        self.headers = dict(headers or {})


class _FleetExecutor:
    """The daemon-side half of the fleet scheduler's executor seam.

    Re-materializes a gang's journaled recipe at its CURRENT replica
    count (shrink/grow resubmits change it), injects the fleet env
    (``$TPX_FLEET_JOB``/``CLASS`` always, ``$TPX_MESH`` on reshapes), and
    submits with ``no_lint=True`` — validation happened at submit-time
    dryrun; a reshape must not bounce off a lint gate. Called with the
    scheduler's lock held, so it never calls back into the scheduler."""

    def __init__(self, daemon: "ControlDaemon") -> None:
        self._daemon = daemon

    def schedule(self, job: Any, mesh_spec: Optional[str]) -> str:
        from torchx_tpu.specs.serialize import appdef_from_dict

        daemon = self._daemon
        recipe = job.recipe
        app = appdef_from_dict(recipe["appdef"])
        scheduler = str(recipe.get("scheduler") or "local")
        if app.roles:
            app.roles[0].num_replicas = int(job.cur_replicas)
        for role in app.roles:
            role.env[settings.ENV_TPX_FLEET_JOB] = job.req.job
            role.env[settings.ENV_TPX_FLEET_CLASS] = job.req.klass
            # every attempt of a gang (first place, preempt-requeue,
            # shrink/grow reshape) joins the job's journaled trace, so
            # `tpx trace --stitch <job>` sees one lifecycle timeline
            if recipe.get("trace_id"):
                role.env[settings.ENV_TPX_TRACE_ID] = str(recipe["trace_id"])
            if mesh_spec:
                role.env[settings.ENV_TPX_MESH] = mesh_spec
            else:
                role.env.pop(settings.ENV_TPX_MESH, None)
        handle = daemon.runner.run(
            app,
            scheduler,
            cfg=dict(recipe.get("cfg") or {}),
            workspace=recipe.get("workspace"),
            no_lint=True,
        )
        sched_name, app_id = daemon._split_handle(handle)
        with daemon._lock:
            daemon._jobs[handle] = job.req.tenant
        daemon.reconciler.ingest(
            StateEvent(
                scheduler=sched_name,
                app_id=app_id,
                state=AppState.SUBMITTED,
                source="fleet",
                cell=daemon.cell,
            )
        )
        daemon.reconciler.track(
            sched_name, daemon.runner._scheduler(sched_name), app_id
        )
        return handle

    def cancel(self, handle: str) -> None:
        try:
            self._daemon.runner.cancel(handle)
        except Exception as e:  # noqa: BLE001 - reshape cancel is best-effort
            logger.debug("fleet cancel of %s failed: %s", handle, e)


class _PipelineExecutor:
    """The pipeline engine's stage submitter.

    Materializes the stage component, stamps every role with the stage
    kind (``tpx/pipeline`` metadata — the TPX603 rule's anchor), and
    submits: through the fleet scheduler with the stage's priority class
    when one is attached (eval=interactive, canary=serve), else directly
    through the Runner with the same journal/track bookkeeping as
    ``/v1/submit``."""

    def __init__(self, daemon: "ControlDaemon") -> None:
        self._daemon = daemon

    def submit(
        self, tenant: str, pipeline: str, stage: Any, args: list[str]
    ) -> dict:
        from torchx_tpu.pipelines.dag import ROLE_METADATA_KEY

        daemon = self._daemon
        cfg = daemon._parse_cfg(stage.scheduler, {"cfg": dict(stage.cfg)})
        info = daemon.runner.dryrun_component(
            stage.component, list(args), stage.scheduler, cfg=cfg
        )
        app = info._app
        for role in app.roles:
            role.metadata[ROLE_METADATA_KEY] = stage.kind
        if daemon.fleet is not None:
            return self._fleet_submit(tenant, stage, app, cfg)
        handle = daemon.runner.run(
            app, stage.scheduler, cfg=cfg, no_lint=True
        )
        sched_name, app_id = daemon._split_handle(handle)
        with daemon._lock:
            daemon._jobs[handle] = tenant
        daemon.reconciler.ingest(
            StateEvent(
                scheduler=sched_name,
                app_id=app_id,
                state=AppState.SUBMITTED,
                source="pipeline",
                cell=daemon.cell,
            )
        )
        daemon.reconciler.track(
            sched_name, daemon.runner._scheduler(sched_name), app_id
        )
        return {"handle": handle}

    def _fleet_submit(
        self, tenant: str, stage: Any, app: Any, cfg: dict
    ) -> dict:
        from torchx_tpu.fleet.model import GangRequest
        from torchx_tpu.specs.serialize import appdef_to_dict

        daemon = self._daemon
        role = app.roles[0] if app.roles else None
        tpu = role.resource.tpu if role is not None else None
        for r in app.roles:
            r.metadata["fleet/class"] = stage.priority
        gang = GangRequest(
            job="",
            tenant=tenant,
            klass=stage.priority,
            replicas=(
                int(stage.replicas)
                if int(stage.replicas) > 1
                else (role.num_replicas if role is not None else 1)
            ),
            chips_per_replica=tpu.chips if tpu is not None else 1,
        )
        recipe = {
            "appdef": appdef_to_dict(app),
            "scheduler": stage.scheduler,
            "cfg": cfg,
            "workspace": None,
        }
        result = daemon.fleet.submit(gang, recipe)
        status = result.get("status")
        if status == "infeasible":
            raise RuntimeError(
                f"gang cannot fit this fleet: {result.get('reason')}"
            )
        if status == "placed":
            return {"handle": result.get("handle", "")}
        return {"queued": True, "fleet_job": result["job"]}

    def resolve(self, fleet_job: str) -> str:
        """Handle of a fleet-queued stage once the market placed it."""
        if self._daemon.fleet is None:
            return ""
        for entry in self._daemon.fleet.queue_snapshot().get("running", []):
            if str(entry.get("job", "")) == fleet_job:
                return str(entry.get("handle", ""))
        return ""

    def cancel(self, handle: str) -> None:
        try:
            self._daemon.runner.cancel(handle)
        except Exception as e:  # noqa: BLE001 - fail-fast cancel is best-effort
            logger.debug("pipeline cancel of %s failed: %s", handle, e)


class ControlDaemon:
    """The daemon's state + HTTP server; see the module docstring.

    Args:
        runner: the :class:`~torchx_tpu.runner.api.Runner` driving the
            backends (default: a fresh ``get_runner("tpx-control")``).
        host/port: bind address — loopback by default; ``port=0`` lets
            the OS pick (read it back from :attr:`addr`).
        state_dir: discovery file + job-state store root (default
            :func:`control_dir`).
        tenant_cap: max concurrently active jobs per tenant (default
            :data:`~torchx_tpu.settings.DEFAULT_CONTROL_TENANT_CAP`).
            Only enforced in daemon-only mode — with ``fleet`` attached,
            submits queue instead of bouncing.
        fleet: an optional :class:`~torchx_tpu.fleet.api.FleetScheduler`;
            the daemon binds itself as its executor, subscribes it to the
            watch stream, and rehydrates its journal.
        slos: SLO spec strings/objects (see
            :func:`torchx_tpu.obs.slo.parse_slo`) the telemetry plane
            evaluates each collect cycle.
        scrape_interval: collector cycle seconds (default
            ``$TPX_TELEMETRY_INTERVAL`` or
            :data:`~torchx_tpu.settings.DEFAULT_TELEMETRY_INTERVAL`).
        telemetry: set False to run without the collector/SLO plane
            (``/metricz`` then serves only the daemon's own registry).
        cell: federation cell name this daemon answers as (default
            ``$TPX_CELL`` or
            :data:`~torchx_tpu.settings.DEFAULT_CELL_NAME`). Stamped
            into every journal record and served on ``/v1/cell``.
    """

    def __init__(
        self,
        runner: Optional[Any] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        state_dir: Optional[str] = None,
        tenant_cap: Optional[int] = None,
        fleet: Optional[Any] = None,
        slos: Optional[list] = None,
        scrape_interval: Optional[float] = None,
        telemetry: bool = True,
        pipeline_pool_provider: Optional[Any] = None,
        clock: Callable[[], float] = time.monotonic,
        cell: Optional[str] = None,
    ) -> None:
        if runner is None:
            from torchx_tpu.runner.api import get_runner

            runner = get_runner("tpx-control")
        self.runner = runner
        self.clock = clock
        self.state_dir = state_dir or control_dir()
        self.cell = (
            cell
            or os.environ.get(settings.ENV_TPX_CELL, "").strip()
            or settings.DEFAULT_CELL_NAME
        )
        # rehydration status, surfaced on /healthz so a federation
        # router (and operators) can tell "booting, journal replaying"
        # from "healthy" — routers treat a not-yet-rehydrated cell as
        # drained. Flipped True as the LAST act of __init__.
        self.rehydrated = False
        self.rehydration = {
            "journal_jobs": 0,
            "fleet_reowned": 0,
            "pipelines_reowned": 0,
        }
        # drain state is durable (state_dir/cell.json): a drained cell
        # that restarts comes back drained — the operator uncordons, not
        # the crash
        self._cell_path = os.path.join(self.state_dir, "cell.json")
        self._draining = False
        try:
            with open(self._cell_path) as f:
                self._draining = bool(json.load(f).get("draining"))
        except (OSError, ValueError):
            pass
        self.tenant_cap = (
            tenant_cap
            if tenant_cap is not None
            else settings.DEFAULT_CONTROL_TENANT_CAP
        )
        self.store = JobStateStore(os.path.join(self.state_dir, "store"))
        self.rehydration["journal_jobs"] = len(self.store)
        self.reconciler = Reconciler(store=self.store, clock=clock)
        runner.attach_reconciler(self.reconciler)
        self.root_token = secrets.token_hex(16)
        self._tokens: dict[str, str] = {self.root_token: "root"}
        # handle -> tenant, for the per-tenant active-job cap. Rehydrated
        # handles (daemon restart) land under their journaled tenant.
        self._jobs: dict[str, str] = {}
        self._lock = threading.Lock()
        self._server = ThreadingHTTPServer((host, port), self._make_handler())
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._closed = False
        self.telemetry_store: Optional[Any] = None
        self.collector: Optional[Any] = None
        self.slo_engine: Optional[Any] = None
        if telemetry:
            from torchx_tpu.obs.slo import SloEngine, SloSpec, parse_slo
            from torchx_tpu.obs.telemetry import Collector, MetricStore

            self.telemetry_store = MetricStore()
            self.collector = Collector(
                self.telemetry_store, interval_s=scrape_interval
            )
            # the daemon's own registry is a first-class source: control
            # verbs, fleet gauges, and gang-wait histograms flow through
            # obs_metrics.REGISTRY in this process
            self.collector.hooks.append(self._ingest_self)
            specs = [
                s if isinstance(s, SloSpec) else parse_slo(str(s))
                for s in (slos or [])
            ]
            self.slo_engine = SloEngine(
                self.telemetry_store,
                specs,
                journal_path=os.path.join(self.state_dir, "slo_alerts.jsonl"),
            )
            self.collector.hooks.append(lambda: self.slo_engine.evaluate())
        self.fleet = fleet
        if fleet is not None:
            if self.slo_engine is not None and hasattr(
                fleet, "set_slo_signal"
            ):
                # market input: the worst long-window burn across
                # fleet-scoped SLOs (gang wait, step time)
                engine = self.slo_engine
                fleet.set_slo_signal(
                    lambda: engine.max_burn(metric_prefix="tpx_")
                )
            fleet.bind(_FleetExecutor(self))
            self.reconciler.subscribe(fleet.on_event)
            fleet.rehydrate()
            # re-own rehydrated running jobs: tenant accounting + watch
            # tracking, so their terminal events free fleet capacity
            for entry in fleet.queue_snapshot().get("running", []):
                handle = str(entry.get("handle") or "")
                if not handle:
                    continue
                with self._lock:
                    self._jobs[handle] = str(entry.get("tenant", ""))
                try:
                    sched_name, app_id = self._split_handle(handle)
                    self.reconciler.track(
                        sched_name, runner._scheduler(sched_name), app_id
                    )
                    self.rehydration["fleet_reowned"] += 1
                except Exception as e:  # noqa: BLE001 - degrade to poll
                    logger.warning(
                        "fleet rehydrate: cannot track %s: %s", handle, e
                    )
        # the pipeline engine rides the same reconciler event stream and
        # the same journal-then-act durability contract as the fleet; it
        # is always on (a daemon without pipelines is just one that never
        # received a /v1/pipelines submit)
        from torchx_tpu.pipelines.engine import PipelineEngine

        pipeline_slo = None
        if self.slo_engine is not None:
            slo_engine = self.slo_engine
            pipeline_slo = lambda: slo_engine.max_burn(  # noqa: E731
                metric_prefix="tpx_"
            )
        self.pipelines = PipelineEngine(
            os.path.join(self.state_dir, "pipelines.jsonl"),
            executor=_PipelineExecutor(self),
            reconciler=self.reconciler,
            slo_signal=pipeline_slo,
            pool_provider=pipeline_pool_provider,
        )
        self.reconciler.subscribe(self.pipelines.on_event)
        for item in self.pipelines.rehydrate():
            handle = str(item.get("handle") or "")
            if not handle:
                continue
            with self._lock:
                self._jobs[handle] = str(item.get("tenant", ""))
            try:
                self.reconciler.track(
                    item["scheduler"],
                    runner._scheduler(item["scheduler"]),
                    item["app_id"],
                )
                self.rehydration["pipelines_reowned"] += 1
            except Exception as e:  # noqa: BLE001 - degrade to poll
                logger.warning(
                    "pipeline rehydrate: cannot track %s: %s", handle, e
                )
        self.rehydrated = True
        obs_metrics.FED_CELL_STATE.set(
            float(obs_metrics.CELL_STATE_VALUES["DRAINING"])
            if self._draining
            else float(obs_metrics.CELL_STATE_VALUES["HEALTHY"]),
            cell=self.cell,
        )

    # -- lifecycle ---------------------------------------------------------

    @property
    def addr(self) -> str:
        """The daemon's base URL, e.g. ``http://127.0.0.1:PORT``."""
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def discovery_path(self) -> str:
        """Where the 0600 addr+token discovery file lives under state_dir."""
        return os.path.join(self.state_dir, DISCOVERY_FILE)

    def _write_discovery(self) -> None:
        """Record addr + root token for same-user CLIs, 0600 (the token
        IS the auth boundary between users on a shared host)."""
        os.makedirs(self.state_dir, exist_ok=True)
        path = self.discovery_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"addr": self.addr, "token": self.root_token, "pid": os.getpid()},
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, 0o600)
        os.replace(tmp, path)

    def start(self) -> "ControlDaemon":
        """Write the discovery file and serve on a background thread."""
        self._write_discovery()
        if self.collector is not None:
            self.collector.start()
        self._serving = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tpx-control", daemon=True
        )
        self._thread.start()
        logger.info("tpx control serving on %s", self.addr)
        return self

    def serve_forever(self) -> None:
        """Foreground mode (what ``tpx control`` runs)."""
        self._write_discovery()
        if self.collector is not None:
            self.collector.start()
        logger.info("tpx control serving on %s", self.addr)
        self._serving = True
        try:
            self._server.serve_forever()
        finally:
            self.close()

    def close(self) -> None:
        """Stop serving, join the serve thread, close the reconciler, and
        remove the discovery file. Idempotent; safe on a never-started
        daemon."""
        if self._closed:
            return
        self._closed = True
        if self.pipelines is not None:
            self.pipelines.close()
        if self.collector is not None:
            self.collector.stop()
        if self._serving:
            # shutdown() blocks on the serve loop acknowledging — never
            # call it on a server whose serve_forever was never entered
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.reconciler.close()
        try:
            os.remove(self.discovery_path())
        except OSError:
            pass

    # -- tenancy -----------------------------------------------------------

    def _authenticate(self, header: Optional[str]) -> str:
        """Bearer token -> tenant name, or 401."""
        if header and header.startswith("Bearer "):
            tenant = self._tokens.get(header[len("Bearer ") :].strip())
            if tenant is not None:
                return tenant
        raise _DaemonError(401, "missing or invalid bearer token")

    def mint_session(self, tenant: str) -> str:
        """Issue a fresh bearer token bound to ``tenant`` (in-memory only;
        tokens die with the daemon)."""
        token = secrets.token_hex(16)
        with self._lock:
            self._tokens[token] = tenant
        return token

    def _active_jobs(self, tenant: str) -> int:
        """Jobs of the tenant whose last journaled state is still live.
        A job with no event yet counts as active (its SUBMITTED seed is
        written on the submit path, so this is a closing race, not a
        steady state)."""
        with self._lock:
            handles = [h for h, t in self._jobs.items() if t == tenant]
        active = 0
        for handle in handles:
            scheduler, app_id = self._split_handle(handle)
            event = self.reconciler.latest(scheduler, app_id) or self.store.latest(
                scheduler, app_id
            )
            if event is None or not (
                event.terminal or event.state == AppState.UNKNOWN
            ):
                active += 1
        obs_metrics.CONTROL_ACTIVE_JOBS.set(float(active), tenant=tenant)
        return active

    @staticmethod
    def _split_handle(handle: str) -> tuple[str, str]:
        from torchx_tpu.specs.api import parse_app_handle

        scheduler, _, app_id = parse_app_handle(handle)
        return scheduler, app_id

    # -- verbs -------------------------------------------------------------

    def _op_session(self, tenant: str, req: dict) -> dict:
        if tenant != "root":
            raise _DaemonError(403, "only the root token mints sessions")
        name = str(req.get("tenant", "")).strip()
        if not name:
            raise _DaemonError(400, "missing tenant name")
        return {"token": self.mint_session(name)}

    def _parse_cfg(self, scheduler: str, req: dict) -> dict:
        # cfg_str (the CLI's raw -cfg string) parses against the
        # backend's typed runopts schema HERE — clients stay
        # schema-blind; an explicit cfg dict overlays the result
        cfg: dict = {}
        cfg_str = str(req.get("cfg_str") or "")
        if cfg_str:
            cfg.update(
                self.runner.scheduler_run_opts(scheduler).cfg_from_str(cfg_str)
            )
        cfg.update(dict(req.get("cfg") or {}))
        return cfg

    def _op_submit(self, tenant: str, req: dict) -> dict:
        component = req.get("component")
        scheduler = req.get("scheduler")
        if not component or not scheduler:
            raise _DaemonError(400, "submit needs component and scheduler")
        self._check_not_draining()
        if self.fleet is not None:
            return self._op_fleet_submit(tenant, req)
        active = self._active_jobs(tenant)
        if active >= self.tenant_cap:
            retry_after = settings.CONTROL_RETRY_AFTER_SECONDS
            raise _DaemonError(
                429,
                f"tenant {tenant!r} has {active} active jobs"
                f" (cap {self.tenant_cap}); retry after one finishes",
                payload={
                    "code": "tenant_cap_exceeded",
                    "tenant": tenant,
                    "active": active,
                    "cap": self.tenant_cap,
                    "retry_after_seconds": retry_after,
                },
                headers={"Retry-After": str(retry_after)},
            )
        try:
            cfg = self._parse_cfg(str(scheduler), req)
            handle = self.runner.run_component(
                str(component),
                [str(a) for a in req.get("args", [])],
                str(scheduler),
                cfg=cfg,
                workspace=req.get("workspace"),
            )
        except _DaemonError:
            raise
        except Exception as e:  # noqa: BLE001 - surfaced to the client
            raise _DaemonError(400, f"{type(e).__name__}: {e}") from e
        sched_name, app_id = self._split_handle(handle)
        with self._lock:
            self._jobs[handle] = tenant
        # seed the journal (the cap's ground truth) and join the watch
        # stream so the terminal event lands without anyone polling
        self.reconciler.ingest(
            StateEvent(
                scheduler=sched_name,
                app_id=app_id,
                state=AppState.SUBMITTED,
                source="daemon",
                cell=self.cell,
            )
        )
        self.reconciler.track(
            sched_name, self.runner._scheduler(sched_name), app_id
        )
        self._active_jobs(tenant)
        return {"handle": handle}

    def _op_fleet_submit(self, tenant: str, req: dict) -> dict:
        """Submit through the fleet scheduler: dryrun-validate, derive the
        gang demand from the materialized AppDef (overridable by explicit
        ``replicas``/``chips`` request fields), journal the resubmission
        recipe, and enqueue. 409 = the fleet can NEVER host the gang."""
        from torchx_tpu.fleet.model import GangRequest
        from torchx_tpu.specs.serialize import appdef_to_dict

        component = str(req.get("component"))
        scheduler = str(req.get("scheduler"))
        try:
            cfg = self._parse_cfg(scheduler, req)
            info = self.runner.dryrun_component(
                component,
                [str(a) for a in req.get("args", [])],
                scheduler,
                cfg=cfg,
                workspace=req.get("workspace"),
            )
        except Exception as e:  # noqa: BLE001 - surfaced to the client
            raise _DaemonError(400, f"{type(e).__name__}: {e}") from e
        app = info._app
        role = app.roles[0] if app.roles else None
        replicas = int(
            req.get("replicas")
            or (role.num_replicas if role is not None else 1)
        )
        chips = req.get("chips")
        if chips is None:
            tpu = role.resource.tpu if role is not None else None
            chips = tpu.chips if tpu is not None else 1
        try:
            gang = GangRequest(
                job="",
                tenant=tenant,
                klass=str(req.get("priority") or "batch"),
                replicas=replicas,
                chips_per_replica=int(chips),
                elastic=bool(req.get("elastic")),
                mesh=str(req.get("mesh") or ""),
                min_replicas=int(req.get("min_replicas") or 1),
            )
        except ValueError as e:
            raise _DaemonError(400, str(e)) from e
        recipe = {
            "appdef": appdef_to_dict(app),
            "scheduler": scheduler,
            "cfg": cfg,
            "workspace": req.get("workspace"),
        }
        result = self.fleet.submit(gang, recipe)
        status = result.get("status")
        if status == "infeasible":
            raise _DaemonError(
                409,
                f"gang cannot fit this fleet: {result.get('reason')}",
                payload={"code": "fleet_infeasible", "fleet_job": result["job"]},
            )
        if status == "placed":
            return {"handle": result.get("handle", ""), "fleet_job": result["job"]}
        return {
            "queued": True,
            "fleet_job": result["job"],
            "position": result.get("position"),
            "class": result.get("class"),
        }

    def _op_queue(self, tenant: str, query: dict) -> dict:
        if self.fleet is None:
            return {"enabled": False}
        return self.fleet.queue_snapshot()

    def _status_payload(self, handle: str, status: Optional[Any]) -> dict:
        if status is None:
            return {"handle": handle, "state": "UNKNOWN", "terminal": True}
        failure_class = getattr(status, "failure_class", None)
        roles = []
        for role in getattr(status, "roles", []) or []:
            roles.append(
                {
                    "role": getattr(role, "role", ""),
                    "replicas": [
                        getattr(r, "id", 0)
                        for r in getattr(role, "replicas", []) or []
                    ],
                }
            )
        return {
            "handle": handle,
            "state": str(getattr(status.state, "name", status.state)),
            "terminal": bool(status.is_terminal()),
            "num_restarts": getattr(status, "num_restarts", 0),
            "msg": getattr(status, "msg", ""),
            "failure_class": (
                str(getattr(failure_class, "name", failure_class))
                if failure_class is not None
                else None
            ),
            "ui_url": getattr(status, "ui_url", None),
            "roles": roles,
        }

    def _op_status(self, tenant: str, query: dict) -> dict:
        handle = self._one(query, "handle")
        status = self.runner.status(handle)
        if status is None:
            raise _DaemonError(404, f"unknown app {handle}")
        return self._status_payload(handle, status)

    def _op_list(self, tenant: str, query: dict) -> dict:
        scheduler = query.get("scheduler", [None])[0]
        if scheduler:
            apps = self.runner.list(scheduler)
            return {
                "apps": [
                    {"app_id": a.app_id, "state": str(a.state.name)} for a in apps
                ]
            }
        # fleet view: everything the journal knows, no backend calls
        out = []
        for (sched, app_id), event in sorted(self.store.snapshot().items()):
            out.append(
                {
                    "scheduler": sched,
                    "app_id": app_id,
                    "state": event.state.name,
                    "time_usec": event.time_usec,
                }
            )
        return {"apps": out}

    def _op_cancel(self, tenant: str, req: dict) -> dict:
        handle = str(req.get("handle", ""))
        if not handle:
            # fleet job id: cancels a queued gang before it ever gets a
            # handle (or the current attempt of a running one)
            job = str(req.get("job", ""))
            if job and self.fleet is not None:
                if not self.fleet.cancel_job(job):
                    raise _DaemonError(404, f"unknown fleet job {job!r}")
                return {"ok": True}
            raise _DaemonError(400, "missing handle")
        try:
            self.runner.cancel(handle)
        except Exception as e:  # noqa: BLE001
            raise _DaemonError(400, f"{type(e).__name__}: {e}") from e
        return {"ok": True}

    def _op_wait(self, tenant: str, query: dict) -> dict:
        """Bounded long-poll: rides the reconciler's wake path, so a
        terminal event answers immediately; budget capped at 60s per
        request (clients re-issue — HTTP stays short-lived)."""
        handle = self._one(query, "handle")
        budget = min(60.0, float(query.get("timeout", ["30"])[0] or 30.0))
        scheduler, app_id = self._split_handle(handle)
        self.reconciler.track(
            scheduler, self.runner._scheduler(scheduler), app_id
        )
        deadline = self.clock() + budget
        while True:
            status = self.runner.status(handle)
            if status is None:
                return {"handle": handle, "state": "UNKNOWN", "terminal": True}
            if status.is_terminal():
                return self._status_payload(handle, status)
            remaining = deadline - self.clock()
            if remaining <= 0:
                payload = self._status_payload(handle, status)
                payload["terminal"] = False
                return payload
            self.reconciler.wait_event(
                scheduler, app_id, timeout=min(remaining, 2.0)
            )

    def _one(self, query: dict, key: str) -> str:
        vals = query.get(key) or []
        if not vals or not vals[0]:
            raise _DaemonError(400, f"missing query parameter {key!r}")
        return str(vals[0])

    # -- federation cell lifecycle -----------------------------------------

    def _inflight(self) -> int:
        """Jobs whose last journaled state is still live, across all
        tenants — the number a draining cell waits on before it counts
        as DRAINED."""
        with self._lock:
            handles = list(self._jobs)
        n = 0
        for handle in handles:
            scheduler, app_id = self._split_handle(handle)
            event = self.reconciler.latest(
                scheduler, app_id
            ) or self.store.latest(scheduler, app_id)
            if event is None or not (
                event.terminal or event.state == AppState.UNKNOWN
            ):
                n += 1
        return n

    def _cell_state(self) -> str:
        """The lifecycle label: DRAINING until in-flight work finishes,
        then DRAINED; HEALTHY when not draining."""
        if not self._draining:
            return "HEALTHY"
        return "DRAINING" if self._inflight() > 0 else "DRAINED"

    def cell_payload(self) -> dict:
        """The ``/v1/cell`` body: identity + lifecycle + rehydration."""
        state = self._cell_state()
        obs_metrics.FED_CELL_STATE.set(
            float(obs_metrics.CELL_STATE_VALUES.get(state, 0)),
            cell=self.cell,
        )
        return {
            "cell": self.cell,
            "state": state,
            "draining": self._draining,
            "inflight": self._inflight(),
            "rehydrated": self.rehydrated,
            "rehydration": dict(self.rehydration),
        }

    def _persist_cell(self) -> None:
        from torchx_tpu.util.jsonl import rewrite_json

        rewrite_json(
            self._cell_path, {"cell": self.cell, "draining": self._draining}
        )

    def _check_not_draining(self) -> None:
        """503 new work away while draining. Deliberately NOT a 429: the
        client must not retry against this daemon — the federation
        router reads ``code: cell_draining`` and spills to another cell."""
        if self._draining:
            raise _DaemonError(
                503,
                f"cell {self.cell!r} is draining; submit elsewhere",
                payload={
                    "code": "cell_draining",
                    "cell": self.cell,
                    "state": self._cell_state(),
                },
                headers={
                    "Retry-After": str(settings.CONTROL_RETRY_AFTER_SECONDS)
                },
            )

    def _op_cell(self, tenant: str, query: dict) -> dict:
        return self.cell_payload()

    def _op_cell_drain(self, tenant: str, req: dict) -> dict:
        """Begin draining: durable flag first (journal-before-act), then
        refuse new submits. In-flight jobs keep running to terminal."""
        self._draining = True
        self._persist_cell()
        logger.info("cell %s draining (%d in flight)", self.cell, self._inflight())
        return self.cell_payload()

    def _op_cell_uncordon(self, tenant: str, req: dict) -> dict:
        """Reopen the cell; reports the transitional UNCORDONED label
        once (subsequent reads say HEALTHY)."""
        was_draining = self._draining
        self._draining = False
        self._persist_cell()
        payload = self.cell_payload()
        if was_draining:
            payload["state"] = "UNCORDONED"
        logger.info("cell %s uncordoned", self.cell)
        return payload

    # -- telemetry plane ---------------------------------------------------

    def _ingest_self(self) -> None:
        """Fold this process's own registry into the store (source
        ``control``) — collector hook AND pre-read refresh, so the
        aggregate never lags the daemon's own counters."""
        if self.telemetry_store is not None:
            self.telemetry_store.ingest_text(
                "control", obs_metrics.REGISTRY.render()
            )

    def _require_telemetry(self) -> Any:
        if self.telemetry_store is None:
            raise _DaemonError(
                501, "telemetry plane disabled on this daemon"
            )
        return self.telemetry_store

    def _op_metrics_query(self, tenant: str, query: dict) -> dict:
        """``/v1/metrics/query``: ``name`` (omit to list), ``reduce``
        (last/sum/avg/max/min/rate/pNN), ``range`` seconds, and
        ``label.K=V`` filters."""
        store = self._require_telemetry()
        self._ingest_self()
        names = query.get("name") or []
        if not names or not names[0]:
            return {"names": store.names()}
        labels = {
            k[len("label.") :]: vals[0]
            for k, vals in query.items()
            if k.startswith("label.") and vals
        }
        raw_range = query.get("range", [None])[0]
        try:
            range_s = float(raw_range) if raw_range else None
        except ValueError as e:
            raise _DaemonError(400, f"bad range: {raw_range!r}") from e
        reduce = query.get("reduce", [None])[0] or None
        try:
            return store.query(
                str(names[0]),
                labels=labels or None,
                reduce=reduce,
                range_s=range_s,
            )
        except ValueError as e:
            raise _DaemonError(400, str(e)) from e

    def _op_alerts(self, tenant: str, query: dict) -> dict:
        if self.slo_engine is None:
            return {"enabled": False, "alerts": [], "burns": {}}
        return {
            "enabled": True,
            "alerts": [a.to_json() for a in self.slo_engine.active()],
            "burns": {
                name: {"short": round(s, 3), "long": round(l, 3)}
                for name, (s, l) in sorted(self.slo_engine.burns().items())
            },
            "slos": [s.name for s in self.slo_engine.specs],
        }

    def _op_metrics_targets(self, tenant: str, req: dict) -> dict:
        """Register (``{"url", "name"?}``) or drop (``{"remove": name}``)
        a replica ``/metricz`` scrape target."""
        self._require_telemetry()
        assert self.collector is not None
        remove = str(req.get("remove") or "")
        if remove:
            if not self.collector.remove_target(remove):
                raise _DaemonError(404, f"unknown scrape target {remove!r}")
            return {"ok": True, "targets": self.collector.targets()}
        url = str(req.get("url") or "")
        if not url.startswith(("http://", "https://")):
            raise _DaemonError(400, f"scrape url must be http(s): {url!r}")
        name = req.get("name")
        source = self.collector.add_target(
            url, name=str(name) if name else None
        )
        return {"source": source, "targets": self.collector.targets()}

    # -- pipelines ---------------------------------------------------------

    def _op_pipeline_submit(self, tenant: str, req: dict) -> dict:
        """``POST /v1/pipelines``: validate the spec, journal, start."""
        from torchx_tpu.pipelines.dag import PipelineSpec

        self._check_not_draining()
        doc = req.get("spec")
        if not isinstance(doc, dict):
            raise _DaemonError(400, "submit needs a 'spec' object")
        try:
            spec = PipelineSpec.from_dict(doc)
        except (ValueError, KeyError, TypeError) as e:
            raise _DaemonError(400, f"bad pipeline spec: {e}") from e
        try:
            pid = self.pipelines.submit(spec, tenant=tenant)
        except Exception as e:  # noqa: BLE001 - surfaced to the client
            raise _DaemonError(400, f"{type(e).__name__}: {e}") from e
        return {"pipeline": pid}

    def _op_pipeline_status(self, tenant: str, query: dict) -> dict:
        """``GET /v1/pipelines[?pipeline=]``: one record or the list."""
        pid = (query.get("pipeline") or [None])[0]
        try:
            return self.pipelines.status(str(pid) if pid else None)
        except KeyError as e:
            raise _DaemonError(404, str(e)) from e

    def _op_pipeline_cancel(self, tenant: str, req: dict) -> dict:
        """``POST /v1/pipelines/cancel``: cancel a pipeline's stages."""
        pid = str(req.get("pipeline", ""))
        if not pid:
            raise _DaemonError(400, "missing pipeline id")
        try:
            return self.pipelines.cancel(pid)
        except KeyError as e:
            raise _DaemonError(404, str(e)) from e

    def render_metricz(self) -> str:
        """The ``/metricz`` body: the cross-source fleet aggregate when
        the telemetry plane is up, else just this process's registry."""
        if self.telemetry_store is None:
            return obs_metrics.REGISTRY.render()
        self._ingest_self()
        return self.telemetry_store.render_prom()

    # -- HTTP plumbing -----------------------------------------------------

    def _make_handler(self) -> Any:
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:  # quiet
                pass

            def _reply(
                self,
                code: int,
                payload: dict,
                op: str = "",
                headers: Optional[dict] = None,
            ) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, str(value))
                self.end_headers()
                self.wfile.write(body)
                if op:
                    obs_metrics.CONTROL_REQUESTS.inc(op=op, code=str(code))

            def _run(self, op: str, fn: Any) -> None:
                start = time.perf_counter()
                headers: Optional[dict] = None
                try:
                    payload = fn()
                    code = 200
                except _DaemonError as e:
                    payload = {"error": e.message, **e.payload}
                    code, headers = e.code, e.headers
                except Exception as e:  # noqa: BLE001 - keep the daemon up
                    logger.warning("control %s failed: %s", op, e)
                    payload, code = {"error": f"{type(e).__name__}: {e}"}, 500
                obs_metrics.CONTROL_REQUEST_SECONDS.observe(
                    time.perf_counter() - start, op=op
                )
                self._reply(code, payload, op=op, headers=headers)

            def _tenant(self) -> str:
                return daemon._authenticate(self.headers.get("Authorization"))

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    doc = json.loads(raw or b"{}")
                except ValueError as e:
                    raise _DaemonError(400, f"bad JSON body: {e}") from e
                if not isinstance(doc, dict):
                    raise _DaemonError(400, "body must be a JSON object")
                return doc

            def do_GET(self) -> None:  # noqa: N802
                url = urlparse(self.path)
                query = parse_qs(url.query)
                if url.path == "/healthz":
                    self._reply(
                        200,
                        {
                            "status": (
                                "ok" if daemon.rehydrated else "rehydrating"
                            ),
                            "jobs": len(daemon.store),
                            "addr": daemon.addr,
                            "tenant_cap": daemon.tenant_cap,
                            "fleet": daemon.fleet is not None,
                            "cell": daemon.cell,
                            "draining": daemon._draining,
                            "rehydrated": daemon.rehydrated,
                            "rehydration": dict(daemon.rehydration),
                        },
                    )
                elif url.path == "/metricz":
                    text = daemon.render_metricz().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                elif url.path == "/v1/metrics/query":
                    self._run(
                        "metrics_query",
                        lambda: daemon._op_metrics_query(
                            self._tenant(), query
                        ),
                    )
                elif url.path == "/v1/alerts":
                    self._run(
                        "alerts",
                        lambda: daemon._op_alerts(self._tenant(), query),
                    )
                elif url.path == "/v1/status":
                    self._run(
                        "status",
                        lambda: daemon._op_status(self._tenant(), query),
                    )
                elif url.path == "/v1/list":
                    self._run(
                        "list", lambda: daemon._op_list(self._tenant(), query)
                    )
                elif url.path == "/v1/wait":
                    self._run(
                        "wait", lambda: daemon._op_wait(self._tenant(), query)
                    )
                elif url.path == "/v1/queue":
                    self._run(
                        "queue",
                        lambda: daemon._op_queue(self._tenant(), query),
                    )
                elif url.path == "/v1/pipelines":
                    self._run(
                        "pipeline_status",
                        lambda: daemon._op_pipeline_status(
                            self._tenant(), query
                        ),
                    )
                elif url.path == "/v1/cell":
                    self._run(
                        "cell", lambda: daemon._op_cell(self._tenant(), query)
                    )
                elif url.path == "/v1/logs":
                    self._logs(query)
                else:
                    self._reply(404, {"error": f"unknown path {url.path}"})

            def do_POST(self) -> None:  # noqa: N802
                url = urlparse(self.path)
                if url.path == "/v1/session":
                    self._run(
                        "session",
                        lambda: daemon._op_session(self._tenant(), self._body()),
                    )
                elif url.path == "/v1/submit":
                    self._run(
                        "submit",
                        lambda: daemon._op_submit(self._tenant(), self._body()),
                    )
                elif url.path == "/v1/cancel":
                    self._run(
                        "cancel",
                        lambda: daemon._op_cancel(self._tenant(), self._body()),
                    )
                elif url.path == "/v1/metrics/targets":
                    self._run(
                        "metrics_targets",
                        lambda: daemon._op_metrics_targets(
                            self._tenant(), self._body()
                        ),
                    )
                elif url.path == "/v1/pipelines":
                    self._run(
                        "pipeline_submit",
                        lambda: daemon._op_pipeline_submit(
                            self._tenant(), self._body()
                        ),
                    )
                elif url.path == "/v1/pipelines/cancel":
                    self._run(
                        "pipeline_cancel",
                        lambda: daemon._op_pipeline_cancel(
                            self._tenant(), self._body()
                        ),
                    )
                elif url.path == "/v1/cell/drain":
                    self._run(
                        "cell_drain",
                        lambda: daemon._op_cell_drain(
                            self._tenant(), self._body()
                        ),
                    )
                elif url.path == "/v1/cell/uncordon":
                    self._run(
                        "cell_uncordon",
                        lambda: daemon._op_cell_uncordon(
                            self._tenant(), self._body()
                        ),
                    )
                else:
                    self._reply(404, {"error": f"unknown path {url.path}"})

            def _logs(self, query: dict) -> None:
                """Log attach: JSONL stream, one {"line": ...} per log
                line, closed by {"done": true}. Auth + argument errors
                surface as clean JSON replies BEFORE streaming starts."""
                try:
                    self._tenant()
                    handle = daemon._one(query, "handle")
                    role = query.get("role", ["app"])[0]
                    k = int(query.get("k", ["0"])[0] or 0)
                    tail = query.get("tail", ["0"])[0] in ("1", "true")
                    lines = daemon.runner.log_lines(
                        handle, role, k=k, should_tail=tail
                    )
                except _DaemonError as e:
                    self._reply(e.code, {"error": e.message}, op="logs")
                    return
                except Exception as e:  # noqa: BLE001
                    self._reply(
                        400, {"error": f"{type(e).__name__}: {e}"}, op="logs"
                    )
                    return
                obs_metrics.CONTROL_REQUESTS.inc(op="logs", code="200")
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    for line in lines:
                        self.wfile.write(
                            json.dumps({"line": line.rstrip("\n")}).encode()
                            + b"\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b'{"done": true}\n')
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client detached mid-stream

        return Handler
