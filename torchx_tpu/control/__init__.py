"""Fleet-scale control plane: watch streams, one reconciler, one daemon.

The pre-control-plane launcher polls per caller: every ``Runner.wait``
loop, every ``tpx status`` script, and every supervisor sleeps-and-polls
``describe`` on its own schedule, so N callers watching M jobs cost
N x M control-plane call streams. This package inverts that into an
event-driven pyramid:

* **Watch streams** (:mod:`~torchx_tpu.control.watch`) — every scheduler
  exposes ``watch(app_ids) -> StateEvent iterator`` through one interface:
  the local backend watches its exit-code/state sidecars by mtime, GKE
  shims ``kubectl get -w``, and everything else gets a coalesced
  poll-adapter fallback. All confirming reads route through the existing
  resilient describe seam and emit ``launcher.watch`` spans.
* **Reconciler** (:mod:`~torchx_tpu.control.reconciler`) — a single event
  loop owns all watch streams, journals transitions into a sharded
  on-disk :class:`~torchx_tpu.control.store.JobStateStore`, refreshes the
  Runner's describe cache through its writer path, and wakes
  ``Runner.wait`` / supervisor waiters via condition variables instead of
  per-caller polling.
* **Daemon** (:mod:`~torchx_tpu.control.daemon`) — ``tpx control``, a
  localhost HTTP daemon exposing submit/status/list/cancel/wait/log over
  JSON with per-session auth tokens and per-tenant concurrency caps. The
  CLI proxies through it transparently when ``$TPX_CONTROL_ADDR`` is set
  (:mod:`~torchx_tpu.control.client`) and falls back to direct-runner
  mode otherwise.

Everything in this package is jax-free and stdlib-only, so the daemon and
any proxying CLI stay off the heavy import path.
"""

from torchx_tpu.control.client import ControlClient, ControlClientError, maybe_client
from torchx_tpu.control.daemon import ControlDaemon
from torchx_tpu.control.events import StateEvent, event_from_describe
from torchx_tpu.control.reconciler import Reconciler
from torchx_tpu.control.store import JobStateStore
from torchx_tpu.control.watch import PollWatcher, Watcher, watch_interval

__all__ = [
    "ControlClient",
    "ControlClientError",
    "ControlDaemon",
    "JobStateStore",
    "PollWatcher",
    "Reconciler",
    "StateEvent",
    "Watcher",
    "event_from_describe",
    "maybe_client",
    "watch_interval",
]
