"""The reconciler: one event loop that owns every watch stream.

Before the control plane, each caller watching a job ran its own poll
loop. The reconciler inverts that fan-out: it holds exactly ONE
:class:`~torchx_tpu.control.watch.Watcher` per scheduler backend (a
daemon thread pumping ``events(follow=True)``), and every observed
transition is:

1. journaled into the sharded :class:`~torchx_tpu.control.store
   .JobStateStore` (crash-safe daemon restarts),
2. folded into the Runner's describe cache through its writer path
   (:meth:`~torchx_tpu.runner.describe_cache.DescribeCache.put` when the
   event carries a confirming describe, ``invalidate`` when it does not —
   never a second cache), and
3. broadcast on a condition variable so ``Runner.wait`` / supervisor
   waiters blocked in :meth:`wait_event` wake *immediately* instead of
   sleeping out their poll interval.

Any number of runners/daemon threads share one reconciler; it is fully
thread-safe and survives watcher death (a dead stream is logged and its
apps fall back to the callers' poll loops — the reconciler degrades, the
wait path never breaks).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional

from torchx_tpu.control.events import StateEvent
from torchx_tpu.control.store import JobStateStore
from torchx_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)


class Reconciler:
    """Single owner of all watch streams; see the module docstring.

    Args:
        store: optional durable journal; events are appended before any
            in-memory state changes (crash ordering: disk first).
        clock: injectable monotonic clock for :meth:`wait_event` deadlines
            (the sim harness runs the reconciler on virtual time).
    """

    def __init__(
        self,
        store: Optional[JobStateStore] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.store = store
        self._clock = clock
        self._cond = threading.Condition()
        # (scheduler, app_id) -> (seq, event); seq is a global monotonic
        # counter so waiters can tell "new since I started waiting"
        self._events: dict[tuple[str, str], tuple[int, StateEvent]] = {}
        self._seq = 0
        self._lock = threading.Lock()
        self._watchers: dict[str, Any] = {}  # backend -> Watcher
        self._threads: dict[str, threading.Thread] = {}
        self._caches: list[Any] = []  # DescribeCache instances to refresh
        self._subscribers: list[Any] = []  # callables fed every ingest
        self._closed = False

    # -- wiring ------------------------------------------------------------

    def bind_cache(self, cache: Any) -> None:
        """Register a Runner's describe cache for watch-driven refresh
        (idempotent; any number of runners can share the reconciler)."""
        with self._lock:
            if cache not in self._caches:
                self._caches.append(cache)

    def subscribe(self, fn: Any) -> None:
        """Register ``fn(event)`` to run after every ingested transition
        (journal -> cache -> broadcast -> subscribers). The fleet
        scheduler hangs its placement loop off this hook. Subscriber
        exceptions are logged, never propagated into the watch pump; a
        subscriber may call back into the reconciler (ingest/track)."""
        with self._lock:
            if fn not in self._subscribers:
                self._subscribers.append(fn)

    def track(self, backend: str, scheduler: Any, app_id: str) -> None:
        """Start watching one app: joins the backend's existing stream or
        opens it (one watcher thread per backend, ever). Never raises —
        a backend whose watch cannot start just stays on poll."""
        try:
            with self._lock:
                if self._closed:
                    return
                watcher = self._watchers.get(backend)
                if watcher is not None:
                    watcher.add(app_id)
                    return
                watcher = scheduler.watch([app_id])
                self._watchers[backend] = watcher
                t = threading.Thread(
                    target=self._pump,
                    args=(backend, watcher),
                    daemon=True,
                    name=f"tpx-reconcile-{backend}",
                )
                self._threads[backend] = t
            obs_metrics.WATCH_STREAMS.set(
                float(len(self._watchers)), scheduler=backend
            )
            t.start()
        except Exception as e:  # noqa: BLE001 - tracking is an optimization
            logger.warning("cannot watch %s on %s: %s", app_id, backend, e)

    def has_stream(self, backend: str) -> bool:
        """True when a watch stream is already open for ``backend``."""
        with self._lock:
            return backend in self._watchers

    # -- the event loop ----------------------------------------------------

    def _pump(self, backend: str, watcher: Any) -> None:
        try:
            for event in watcher.events(follow=True):
                self.ingest(event)
        except Exception as e:  # noqa: BLE001 - stream death degrades to poll
            logger.warning("watch stream for %s died: %s", backend, e)
        finally:
            with self._lock:
                self._watchers.pop(backend, None)
                self._threads.pop(backend, None)
            obs_metrics.WATCH_STREAMS.set(0.0, scheduler=backend)

    def ingest(self, event: StateEvent) -> None:
        """Apply one observed transition: journal -> cache -> wake.

        Public so the daemon's submit path can seed SUBMITTED events and
        tests can inject transitions without a live watcher."""
        if self.store is not None:
            self.store.append(event)
        with self._lock:
            caches = list(self._caches)
        for cache in caches:
            try:
                if event.resp is not None or event.state.name == "UNKNOWN":
                    # confirmed describe (or backend-forgot): writer path
                    cache.put(event.scheduler, event.app_id, event.resp)
                else:
                    # stream-only transition: drop the entry so the next
                    # reader re-fetches through the resilient seam
                    cache.invalidate(event.scheduler, event.app_id)
            except Exception:  # noqa: BLE001 - cache refresh is best-effort
                logger.debug("cache refresh failed", exc_info=True)
        with self._cond:
            self._seq += 1
            self._events[(event.scheduler, event.app_id)] = (self._seq, event)
            self._cond.notify_all()
        with self._lock:
            subscribers = list(self._subscribers)
        for fn in subscribers:
            try:
                fn(event)
            except Exception:  # noqa: BLE001 - never kill the watch pump
                logger.warning(
                    "reconciler subscriber failed for %s/%s",
                    event.scheduler,
                    event.app_id,
                    exc_info=True,
                )

    # -- waiter side -------------------------------------------------------

    def latest(self, scheduler: str, app_id: str) -> Optional[StateEvent]:
        """Most recent transition seen this process for one app."""
        with self._cond:
            entry = self._events.get((scheduler, app_id))
            return entry[1] if entry else None

    def wait_event(
        self, scheduler: str, app_id: str, timeout: float
    ) -> Optional[StateEvent]:
        """Block until a NEW event for the app arrives (or ``timeout``).

        An already-recorded terminal/UNKNOWN event returns immediately —
        the ``Runner.wait`` regression case where the job finished between
        two polls must not cost a full poll-interval sleep. Returns the
        event, or None on timeout (callers fall back to their poll)."""
        key = (scheduler, app_id)
        deadline = self._clock() + max(0.0, timeout)
        with self._cond:
            entry = self._events.get(key)
            start_seq = entry[0] if entry else 0
            if entry is not None and (
                entry[1].terminal or entry[1].state.name == "UNKNOWN"
            ):
                return entry[1]
            while True:
                entry = self._events.get(key)
                if entry is not None and entry[0] > start_seq:
                    return entry[1]
                remaining = deadline - self._clock()
                if remaining <= 0 or self._closed:
                    return None
                self._cond.wait(remaining)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Stop every stream and wake every waiter (they fall back to
        polling)."""
        with self._lock:
            self._closed = True
            watchers = list(self._watchers.values())
            threads = list(self._threads.values())
        for w in watchers:
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass
        for t in threads:
            t.join(timeout=2.0)
        with self._cond:
            self._cond.notify_all()
