"""Sharded on-disk job-state store: the reconciler's durable journal.

A control daemon tracking thousands of concurrent jobs must survive its
own death the way the supervisor does: everything it knows has to be on
disk *before* it matters, and a SIGKILL mid-write may cost at most the
final line. The store follows the
:class:`~torchx_tpu.supervisor.ledger.AttemptLedger` crash-safety idiom,
scaled out to fleet write rates by sharding::

    <root>/
        meta.json          # shard count + format version, fsync'd atomic
        shard-00/events.jsonl
        shard-01/events.jsonl
        ...

Events append to the shard owned by their ``(scheduler, app_id)`` key
(stable CRC32 — NOT ``hash()``, which is seed-randomized per process), as
one complete line per ``write`` on an append-mode fd (line-atomic on
POSIX) followed by flush+fsync. Rehydration replays every shard oldest-
first and keeps the last event per app; a torn final line (writer died
mid-append) is skipped, not fatal. Shard count is pinned by ``meta.json``:
a store reopened with a different ``shards`` argument keeps the on-disk
layout (otherwise rehydration would look in the wrong shard).

Writes are best-effort from the caller's point of view — a full disk
degrades daemon restart fidelity, never a live submit path.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Optional

from torchx_tpu.control.events import StateEvent

META_FILE = "meta.json"
EVENTS_FILE = "events.jsonl"
FORMAT_VERSION = 1
DEFAULT_SHARDS = 8


def shard_for(scheduler: str, app_id: str, shards: int) -> int:
    """Stable shard index for one app key (process-independent)."""
    key = f"{scheduler}/{app_id}".encode()
    return zlib.crc32(key) % max(1, shards)


class JobStateStore:
    """Durable latest-state map over every app the reconciler has seen.

    Thread-safe: the reconciler's event loop appends while daemon HTTP
    threads read ``latest``/``snapshot``. One lock per shard keeps
    concurrent appends to different shards unserialized.
    """

    def __init__(self, root: str, shards: int = DEFAULT_SHARDS) -> None:
        self.root = root
        self.shards = self._pin_shards(shards)
        self._locks = [threading.Lock() for _ in range(self.shards)]
        self._latest: dict[tuple[str, str], StateEvent] = {}
        self._latest_lock = threading.Lock()
        self.rehydrate()

    # -- layout ------------------------------------------------------------

    def _pin_shards(self, shards: int) -> int:
        """Honor an existing store's shard count over the argument, and
        persist the choice for the next process (atomic + fsync'd meta,
        the AttemptLedger ``write_meta`` idiom)."""
        meta_path = os.path.join(self.root, META_FILE)
        try:
            with open(meta_path) as f:
                existing = int(json.load(f).get("shards", 0))
            if existing > 0:
                return existing
        except (OSError, ValueError):
            pass
        shards = max(1, int(shards))
        try:
            os.makedirs(self.root, exist_ok=True)
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"version": FORMAT_VERSION, "shards": shards}, f, sort_keys=True
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, meta_path)
        except OSError:
            pass
        return shards

    def _shard_file(self, shard: int) -> str:
        return os.path.join(self.root, f"shard-{shard:02d}", EVENTS_FILE)

    # -- write side --------------------------------------------------------

    def append(self, event: StateEvent) -> None:
        """Journal one event (line-atomic append + fsync) and fold it into
        the in-memory latest-state map."""
        shard = shard_for(event.scheduler, event.app_id, self.shards)
        path = self._shard_file(shard)
        with self._locks[shard]:
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "a") as f:
                    f.write(json.dumps(event.serialize()) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except (OSError, TypeError, ValueError):
                pass
        with self._latest_lock:
            self._latest[(event.scheduler, event.app_id)] = event

    # -- read side ---------------------------------------------------------

    def rehydrate(self) -> int:
        """Rebuild the latest-state map from every shard on disk (what a
        restarted daemon calls before serving status). Returns the number
        of distinct apps recovered; torn/garbage lines are skipped."""
        latest: dict[tuple[str, str], StateEvent] = {}
        for shard in range(self.shards):
            try:
                f = open(self._shard_file(shard))
            except OSError:
                continue
            with f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        event = StateEvent.deserialize(json.loads(line))
                    except ValueError:
                        continue  # torn final line from a killed writer
                    if event.app_id:
                        latest[(event.scheduler, event.app_id)] = event
        with self._latest_lock:
            self._latest = latest
        return len(latest)

    def latest(self, scheduler: str, app_id: str) -> Optional[StateEvent]:
        """Most recent event recorded for one app, or None."""
        with self._latest_lock:
            return self._latest.get((scheduler, app_id))

    def snapshot(self) -> dict[tuple[str, str], StateEvent]:
        """Copy of the whole latest-state map (daemon ``/v1/list`` fuel)."""
        with self._latest_lock:
            return dict(self._latest)

    def __len__(self) -> int:
        with self._latest_lock:
            return len(self._latest)
