"""The control plane's unit of work: one observed app state transition.

A :class:`StateEvent` is what every watch adapter emits and what the
reconciler consumes. It carries the scheduler's authoritative
:class:`~torchx_tpu.schedulers.api.DescribeAppResponse` when the watcher
confirmed the transition with a describe (the reconciler then refreshes
the describe cache through its writer path); stream-only transitions
(e.g. a kubectl watch line) ship without one and the reconciler
invalidates instead, so the next reader re-fetches.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from torchx_tpu.schedulers.api import DescribeAppResponse
from torchx_tpu.specs.api import AppState, is_terminal
from torchx_tpu.util.times import epoch_usec


@dataclass
class StateEvent:
    """One observed state transition of one app.

    Attributes:
        scheduler: backend name the app runs on.
        app_id: backend app id.
        state: the state the app transitioned TO.
        source: which adapter observed it — ``"sidecar"`` (local mtime
            watch), ``"kubectl"`` (GKE watch shim), or ``"poll"`` (the
            generic adapter).
        time_usec: observation wall-clock stamp.
        resp: the confirming describe response, when the adapter made one
            (terminal transitions always do).
        cell: federation cell the observing control daemon belongs to
            (empty outside a daemon / in single-cell direct mode). Makes
            every journal record cell-addressable, so merged multi-cell
            journals stay attributable.
    """

    scheduler: str
    app_id: str
    state: AppState
    source: str = "poll"
    time_usec: int = field(default_factory=epoch_usec)
    resp: Optional[DescribeAppResponse] = None
    cell: str = ""

    @property
    def terminal(self) -> bool:
        """True when ``state`` is terminal (the watch stream ends here)."""
        return is_terminal(self.state)

    def serialize(self) -> dict:
        """JSONL-safe record (the JobStateStore's line format). The
        ``cell`` key is written only when set, so single-cell journals
        keep their pre-federation byte format."""
        doc = {
            "scheduler": self.scheduler,
            "app_id": self.app_id,
            "state": self.state.name,
            "source": self.source,
            "time_usec": self.time_usec,
        }
        if self.cell:
            doc["cell"] = self.cell
        return doc

    @staticmethod
    def deserialize(doc: dict) -> "StateEvent":
        """Inverse of :meth:`serialize`; unknown state names degrade to
        UNKNOWN (a newer writer's line must not break rehydration)."""
        try:
            state = AppState[doc.get("state", "UNKNOWN")]
        except KeyError:
            state = AppState.UNKNOWN
        return StateEvent(
            scheduler=str(doc.get("scheduler", "")),
            app_id=str(doc.get("app_id", "")),
            state=state,
            source=str(doc.get("source", "poll")),
            time_usec=int(doc.get("time_usec", 0) or 0),
            cell=str(doc.get("cell", "")),
        )


def event_from_describe(
    scheduler: str,
    app_id: str,
    resp: Optional[DescribeAppResponse],
    source: str = "poll",
) -> StateEvent:
    """Build the event for one describe result; ``None`` (backend no
    longer knows the id) maps to UNKNOWN, which is treated as terminal
    for watch purposes — there is nothing left to watch."""
    if resp is None:
        return StateEvent(
            scheduler=scheduler, app_id=app_id, state=AppState.UNKNOWN, source=source
        )
    return StateEvent(
        scheduler=scheduler,
        app_id=app_id,
        state=resp.state,
        source=source,
        resp=resp,
    )
