"""Watch adapters: one event interface over every scheduler backend.

``Scheduler.watch(app_ids)`` returns a :class:`Watcher` whose
``events()`` iterator yields a :class:`~torchx_tpu.control.events
.StateEvent` per observed state *transition*. Three adapters implement
it:

* :class:`LocalSidecarWatcher` — the local backend's processes already
  leave durable traces next to their logs (the ``.tpx_state.json`` state
  file and the ``exitcode`` sidecars the ``/bin/sh`` launch wrapper
  writes), so the watcher mtime-polls those tiny files and only issues a
  *confirming* ``describe`` when something changed. Watching N local jobs
  costs N ``stat`` calls per tick and ~one describe per transition —
  not one describe per caller per tick.
* :class:`KubectlWatcher` — shims ``kubectl get -w -o json`` (one stream
  per namespace, shared by every watched JobSet in it) and parses the
  streamed objects; terminal transitions are confirmed with a describe so
  classification (preemption vs failure) stays authoritative. When
  kubectl is unavailable the affected apps degrade to the poll scan.
* :class:`PollWatcher` — the generic fallback: a coalesced describe scan
  per tick. Still a win over per-caller polling because the reconciler
  owns ONE such stream per backend regardless of how many waiters ride it.

Confirming reads go through each backend's existing ``describe`` path,
which is already routed through the resilient seam (retries, breakers,
fault injection) — a watcher never invents a second control-plane path.
Every emitted event carries a ``launcher.watch`` span and increments
``tpx_watch_events_total``.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from torchx_tpu import settings
from torchx_tpu.control.events import StateEvent, event_from_describe
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.specs.api import AppState, is_terminal

logger = logging.getLogger(__name__)


def watch_interval() -> float:
    """Tick interval for watch scans: ``$TPX_WATCH_INTERVAL`` else the
    default; malformed values fall back, with a floor keeping a bad env
    from busy-spinning the scan loop."""
    raw = os.environ.get(settings.ENV_TPX_WATCH_INTERVAL)
    if raw is None or not raw.strip():
        return settings.DEFAULT_WATCH_INTERVAL
    try:
        return max(0.01, float(raw))
    except ValueError:
        return settings.DEFAULT_WATCH_INTERVAL


def _watch_done(state: AppState) -> bool:
    """True when there is nothing left to watch for an app: a terminal
    state, or UNKNOWN (the backend no longer knows the id)."""
    return is_terminal(state) or state == AppState.UNKNOWN


class Watcher:
    """Base watch stream over a dynamic set of app ids on ONE scheduler.

    Subclasses implement :meth:`_scan` (one cheap pass over the active
    set, returning confirmed transitions). The base class owns the tick
    loop, transition dedup, span/metric emission, dynamic :meth:`add`,
    and :meth:`close` (which wakes a sleeping scan immediately).
    """

    #: event-source tag stamped on everything this adapter emits.
    source = "poll"

    def __init__(
        self,
        scheduler: Any,
        app_ids: Iterable[str] = (),
        interval: Optional[float] = None,
    ) -> None:
        self._sched = scheduler
        self._interval = interval if interval is not None else watch_interval()
        self._lock = threading.Lock()
        # app_id -> last emitted state (None = nothing emitted yet)
        self._active: dict[str, Optional[AppState]] = {}
        self._wake = threading.Event()
        self._closed = False
        for app_id in app_ids:
            self._active[app_id] = None

    @property
    def backend(self) -> str:
        """The scheduler backend this watcher streams events for."""
        return getattr(self._sched, "backend", "unknown")

    def add(self, app_id: str) -> None:
        """Start watching one more app (thread-safe, wakes the scan)."""
        with self._lock:
            if app_id not in self._active:
                self._active[app_id] = None
        self._wake.set()

    def close(self) -> None:
        """Stop the stream; a blocked ``events()`` iterator returns."""
        self._closed = True
        self._wake.set()

    # -- transition bookkeeping -------------------------------------------

    def _watching(self) -> list[tuple[str, Optional[AppState]]]:
        with self._lock:
            return [
                (app_id, last)
                for app_id, last in self._active.items()
                if last is None or not _watch_done(last)
            ]

    def _transition(self, event: StateEvent) -> Optional[StateEvent]:
        """Dedup: returns the event iff it changes the app's last emitted
        state; records the new state either way."""
        with self._lock:
            last = self._active.get(event.app_id)
            if last == event.state:
                return None
            self._active[event.app_id] = event.state
        return event

    # -- the stream --------------------------------------------------------

    def events(self, follow: bool = False) -> Iterator[StateEvent]:
        """Yield state transitions as they are observed.

        With ``follow=False`` the stream ends once every tracked app has
        reached a terminal (or UNKNOWN) state; with ``follow=True`` it
        runs until :meth:`close` — the reconciler's mode, where new apps
        keep arriving via :meth:`add`.
        """
        while not self._closed:
            try:
                transitions = self._scan()
            except Exception as e:  # noqa: BLE001 - a watch stream must not die
                logger.warning(
                    "%s watch scan failed (%s); stream continues", self.backend, e
                )
                transitions = []
            for event in transitions:
                obs_metrics.WATCH_EVENTS.inc(
                    scheduler=self.backend, source=event.source
                )
                obs_trace.heartbeat(
                    "launcher.watch",
                    scheduler=self.backend,
                    app_id=event.app_id,
                    state=event.state.name,
                    source=event.source,
                )
                yield event
            if not follow and not self._watching():
                return
            self._wake.wait(self._interval)
            self._wake.clear()

    # -- subclass hook ------------------------------------------------------

    def _describe(self, app_id: str):
        """One confirming describe through the backend's (resilient)
        describe path; errors are absorbed — the stream keeps watching."""
        try:
            return self._sched.describe(app_id)
        except Exception as e:  # noqa: BLE001 - transient control-plane wobble
            logger.debug("watch describe of %s failed: %s", app_id, e)
            return _DESCRIBE_FAILED

    def _scan(self) -> list[StateEvent]:
        """One pass over the active set -> confirmed transition events."""
        out = []
        for app_id, _last in self._watching():
            resp = self._describe(app_id)
            if resp is _DESCRIBE_FAILED:
                continue
            event = self._transition(
                event_from_describe(self.backend, app_id, resp, source=self.source)
            )
            if event is not None:
                out.append(event)
        return out


#: sentinel distinguishing "describe raised" (keep watching, state
#: unknown-but-probably-fine) from "describe returned None" (the backend
#: genuinely forgot the app -> UNKNOWN, stop watching).
_DESCRIBE_FAILED = object()


class PollWatcher(Watcher):
    """The generic poll-adapter fallback — :class:`Watcher`'s default scan
    as a concrete, importable class (what ``Scheduler.watch`` returns for
    backends without a native event source)."""

    source = "poll"


# =========================================================================
# Local: sidecar mtime watcher
# =========================================================================


class LocalSidecarWatcher(Watcher):
    """Event source for the local scheduler's on-disk traces.

    Per tick, per app: ``stat`` the state file (external cancels and
    owner state writes bump its mtime) and count the per-replica
    ``exitcode`` sidecars (the launch wrapper writes one the instant a
    replica exits, with no describe anywhere in the path). Only when one
    of those cheap signals changes does the watcher issue a confirming
    ``describe`` — which is also what lets the owning scheduler run its
    fail-fast / preemption-drill / elastic-restart bookkeeping.
    """

    source = "sidecar"

    def __init__(
        self,
        scheduler: Any,
        app_ids: Iterable[str] = (),
        interval: Optional[float] = None,
    ) -> None:
        super().__init__(scheduler, app_ids, interval=interval)
        # app_id -> (log_dir, last state-file mtime, last sidecar count)
        self._traces: dict[str, tuple[Optional[str], float, int]] = {}

    def _log_dir(self, app_id: str) -> Optional[str]:
        app = getattr(self._sched, "_apps", {}).get(app_id)
        if app is not None:
            return app.log_dir
        from torchx_tpu.schedulers.local_scheduler import _registry_lookup

        return _registry_lookup(app_id)

    def _sidecar_signal(self, app_id: str, log_dir: str) -> tuple[float, int, int]:
        """(state-file mtime, completed-sidecar count, replica total) for
        one app — the cheap change detector. Missing files read as
        (0, 0, 0)."""
        from torchx_tpu.schedulers.local_scheduler import (
            EXITCODE_FILE,
            STATE_FILE,
        )

        state_path = os.path.join(log_dir, STATE_FILE)
        try:
            mtime = os.stat(state_path).st_mtime
        except OSError:
            return 0.0, 0, 0
        count = total = 0
        try:
            with open(state_path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return mtime, 0, 0
        for role_name, replicas in payload.get("roles", {}).items():
            for r in replicas:
                total += 1
                rc = os.path.join(
                    log_dir, role_name, str(r.get("id", 0)), EXITCODE_FILE
                )
                if os.path.exists(rc):
                    count += 1
        return mtime, count, total

    def _scan(self) -> list[StateEvent]:
        out = []
        for app_id, last in self._watching():
            cached = self._traces.get(app_id)
            log_dir = cached[0] if cached else self._log_dir(app_id)
            if log_dir is None:
                # nothing on disk yet (or a foreign id): describe decides
                resp = self._describe(app_id)
                if resp is _DESCRIBE_FAILED:
                    continue
                event = self._transition(
                    event_from_describe(self.backend, app_id, resp, self.source)
                )
                if event is not None:
                    out.append(event)
                continue
            mtime, sidecars, total = self._sidecar_signal(app_id, log_dir)
            changed = (
                cached is None
                or last is None
                or mtime != cached[1]
                or sidecars != cached[2]
            )
            if not changed:
                continue
            resp = self._describe(app_id)
            if resp is _DESCRIBE_FAILED:
                continue
            if resp is not None and not _watch_done(resp.state) and (
                total and sidecars >= total
            ):
                # reap race: every replica's exit sidecar is already on
                # disk but the owner has not reaped the processes, so
                # describe still says RUNNING. Do NOT record the signal —
                # the next tick re-describes until the state catches up
                # (recording it here would mean nothing ever changes again
                # and the terminal event is lost).
                pass
            else:
                self._traces[app_id] = (log_dir, mtime, sidecars)
            event = self._transition(
                event_from_describe(self.backend, app_id, resp, self.source)
            )
            if event is not None:
                out.append(event)
        return out


# =========================================================================
# GKE: kubectl watch shim
# =========================================================================


def _iter_json_docs(chunks: Iterable[str]) -> Iterator[dict]:
    """Incrementally parse a stream of concatenated JSON documents (what
    ``kubectl get -w -o json`` emits): brace-depth tracking, quote/escape
    aware, garbage between documents skipped."""
    depth = 0
    in_str = False
    escape = False
    buf: list[str] = []
    for chunk in chunks:
        for ch in chunk:
            if depth == 0 and ch != "{":
                continue  # inter-document noise
            buf.append(ch)
            if in_str:
                if escape:
                    escape = False
                elif ch == "\\":
                    escape = True
                elif ch == '"':
                    in_str = False
                continue
            if ch == '"':
                in_str = True
            elif ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    try:
                        yield json.loads("".join(buf))
                    except ValueError:
                        pass
                    buf = []


def jobset_watch_state(doc: dict) -> AppState:
    """Minimal JobSet-object -> AppState mapping for watch-line triage
    (terminal lines are re-confirmed through ``describe``, which owns the
    full classification)."""
    conditions = (doc.get("status") or {}).get("conditions") or []
    for cond in conditions:
        if str(cond.get("status", "")).lower() != "true":
            continue
        ctype = str(cond.get("type", ""))
        if ctype == "Completed":
            return AppState.SUCCEEDED
        if ctype in ("Failed", "FailurePolicyComplete"):
            return AppState.FAILED
        if ctype == "Suspended":
            return AppState.PENDING
    return AppState.RUNNING


class KubectlWatcher(Watcher):
    """``kubectl get jobsets -w`` shim: one streaming subprocess per
    namespace, shared by every watched JobSet in it.

    A reader thread per namespace feeds parsed objects into a queue the
    scan drains; terminal-looking lines trigger one confirming describe.
    If kubectl cannot be spawned the namespace's apps silently degrade to
    the inherited poll scan — same events, poll-interval latency.
    """

    source = "kubectl"

    def __init__(
        self,
        scheduler: Any,
        app_ids: Iterable[str] = (),
        interval: Optional[float] = None,
        spawn: Optional[Callable[[list[str]], Any]] = None,
    ) -> None:
        super().__init__(scheduler, app_ids, interval=interval)
        self._spawn = spawn or self._default_spawn
        self._procs: dict[str, Any] = {}  # namespace -> proc
        self._poll_fallback: set[str] = set()  # namespaces without kubectl
        self._pending: "list[tuple[str, AppState]]" = []
        self._pending_lock = threading.Lock()

    @staticmethod
    def _default_spawn(cmd: list[str]) -> Any:
        return subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )

    @staticmethod
    def _split(app_id: str) -> tuple[str, str]:
        namespace, _, name = app_id.partition(":")
        return (namespace, name) if name else ("default", app_id)

    def _ensure_stream(self, namespace: str) -> None:
        if namespace in self._procs or namespace in self._poll_fallback:
            return
        cmd = [
            "kubectl",
            "get",
            "jobsets.jobset.x-k8s.io",
            "-n",
            namespace,
            "-w",
            "-o",
            "json",
        ]
        try:
            proc = self._spawn(cmd)
        except OSError as e:
            logger.warning(
                "kubectl watch unavailable for namespace %s (%s);"
                " falling back to the poll adapter",
                namespace,
                e,
            )
            self._poll_fallback.add(namespace)
            return
        self._procs[namespace] = proc
        t = threading.Thread(
            target=self._pump,
            args=(namespace, proc),
            daemon=True,
            name=f"tpx-watch-{namespace}",
        )
        t.start()

    def _pump(self, namespace: str, proc: Any) -> None:
        stdout = getattr(proc, "stdout", None)
        if stdout is None:
            self._poll_fallback.add(namespace)
            return
        try:
            for doc in _iter_json_docs(stdout):
                name = ((doc.get("metadata") or {}).get("name")) or ""
                if not name:
                    continue
                app_id = f"{namespace}:{name}"
                with self._pending_lock:
                    self._pending.append((app_id, jobset_watch_state(doc)))
                self._wake.set()
        except Exception as e:  # noqa: BLE001 - stream death -> poll fallback
            logger.warning("kubectl watch stream for %s died: %s", namespace, e)
        finally:
            self._procs.pop(namespace, None)
            self._poll_fallback.add(namespace)
            self._wake.set()

    def _scan(self) -> list[StateEvent]:
        watched = {app_id for app_id, _ in self._watching()}
        for app_id in watched:
            self._ensure_stream(self._split(app_id)[0])
        with self._pending_lock:
            pending, self._pending = self._pending, []
        out = []
        seen: set[str] = set()
        for app_id, state in pending:
            if app_id not in watched or app_id in seen:
                continue
            if _watch_done(state):
                # terminal per the stream: confirm through describe so the
                # event carries the authoritative classification
                seen.add(app_id)
                resp = self._describe(app_id)
                if resp is _DESCRIBE_FAILED:
                    continue
                event = self._transition(
                    event_from_describe(self.backend, app_id, resp, self.source)
                )
            else:
                event = self._transition(
                    StateEvent(
                        scheduler=self.backend,
                        app_id=app_id,
                        state=state,
                        source=self.source,
                    )
                )
            if event is not None:
                out.append(event)
        # namespaces without a live stream degrade to the poll scan
        for app_id, _last in self._watching():
            if self._split(app_id)[0] not in self._poll_fallback:
                continue
            if app_id in seen:
                continue
            resp = self._describe(app_id)
            if resp is _DESCRIBE_FAILED:
                continue
            event = self._transition(
                event_from_describe(self.backend, app_id, resp, source="poll")
            )
            if event is not None:
                out.append(event)
        return out

    def close(self) -> None:
        for proc in list(self._procs.values()):
            try:
                proc.terminate()
            except Exception:  # noqa: BLE001
                pass
        super().close()
