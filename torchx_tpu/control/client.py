"""HTTP client for the ``tpx control`` daemon — the CLI's proxy seam.

When ``$TPX_CONTROL_ADDR`` is set (or a live daemon's discovery file is
found under ``$TPX_CONTROL_DIR``), :func:`maybe_client` returns a
:class:`ControlClient` and the CLI routes submit/status/list/cancel/wait/
log verbs through the daemon instead of driving schedulers directly —
thousands of shells then share one reconciler. When neither is present it
returns None and the CLI falls back to direct-runner mode, byte-for-byte
the pre-daemon behavior.

stdlib-only (urllib), so the proxy path adds nothing to the CLI's
import cost — ``tpx --help`` stays jax-free with the daemon registered.
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Iterator, Optional

from torchx_tpu import settings

DEFAULT_TIMEOUT = 30.0


class ControlClientError(RuntimeError):
    """A daemon request failed; carries the HTTP status (0 = transport)."""

    def __init__(self, code: int, message: str) -> None:
        super().__init__(message)
        self.code = code
        self.message = message


class _Throttled(Exception):
    """Internal: a 429 with its (capped) Retry-After hint attached."""

    def __init__(self, message: str, retry_after: float) -> None:
        super().__init__(message)
        self.message = message
        self.retry_after = retry_after


class ControlClient:
    """Thin JSON-over-HTTP wrapper mirroring the daemon's verb set.

    Throttling (HTTP 429) is absorbed here: the daemon has always sent a
    ``Retry-After`` header plus a ``retry_after_seconds`` body field with
    its 429s, and the client honors them — capped, jittered sleep, then
    retry, up to ``retry_429`` attempts — instead of bouncing the error
    to every caller. A 429'd request was *refused*, never executed, so
    the replay is idempotent by construction. ``sleep``/``rng`` are
    injectable so tests assert the backoff without wall time.
    """

    def __init__(
        self,
        addr: str,
        token: str,
        timeout: float = DEFAULT_TIMEOUT,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        retry_429: int = settings.CONTROL_429_MAX_RETRIES,
    ) -> None:
        self.addr = addr.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.retry_429 = max(0, int(retry_429))
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()

    # -- plumbing ----------------------------------------------------------

    def _retry_after(self, err: urllib.error.HTTPError, body: dict) -> float:
        """The daemon's throttle hint, header first (the HTTP-standard
        spelling), body field second, default third — capped so a bogus
        hint cannot park the caller."""
        raw = err.headers.get("Retry-After") if err.headers else None
        if raw is None:
            raw = body.get("retry_after_seconds")
        try:
            hint = float(raw) if raw is not None else float(
                settings.CONTROL_RETRY_AFTER_SECONDS
            )
        except (TypeError, ValueError):
            hint = float(settings.CONTROL_RETRY_AFTER_SECONDS)
        return max(0.0, min(hint, settings.CONTROL_429_RETRY_CAP_SECONDS))

    def _request(
        self,
        path: str,
        payload: Optional[dict] = None,
        timeout: Optional[float] = None,
    ) -> dict:
        attempt = 0
        while True:
            try:
                return self._request_once(path, payload, timeout)
            except _Throttled as t:
                if attempt >= self.retry_429:
                    raise ControlClientError(429, t.message) from t
                attempt += 1
                # ±10% jitter so N throttled clients don't re-dial in
                # one synchronized wave when the hint expires
                self._sleep(
                    t.retry_after * (1.0 + self._rng.uniform(-0.1, 0.1))
                )

    def _request_once(
        self,
        path: str,
        payload: Optional[dict] = None,
        timeout: Optional[float] = None,
    ) -> dict:
        req = urllib.request.Request(
            self.addr + path,
            data=None if payload is None else json.dumps(payload).encode(),
            headers={
                "Authorization": f"Bearer {self.token}",
                "Content-Type": "application/json",
            },
            method="GET" if payload is None else "POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout or self.timeout
            ) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
                message = body.get("error", str(e))
            except ValueError:
                body, message = {}, str(e)
            if e.code == 429:
                raise _Throttled(message, self._retry_after(e, body)) from e
            raise ControlClientError(e.code, message) from e
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ControlClientError(0, f"control daemon unreachable: {e}") from e

    # -- verbs -------------------------------------------------------------

    def healthz(self) -> dict:
        """Liveness probe: the daemon's version, uptime, and stream count."""
        return self._request("/healthz")

    def mint_session(self, tenant: str) -> str:
        """Mint a per-tenant session token (root-token callers only)."""
        return str(self._request("/v1/session", {"tenant": tenant})["token"])

    def submit_job(
        self,
        component: str,
        args: list[str],
        scheduler: str,
        cfg: Optional[dict] = None,
        cfg_str: str = "",
        workspace: Optional[str] = None,
        priority: Optional[str] = None,
        elastic: bool = False,
        mesh: str = "",
        replicas: Optional[int] = None,
        chips: Optional[int] = None,
        min_replicas: Optional[int] = None,
    ) -> dict:
        """Submit through the daemon, returning the full reply.

        In daemon-only mode the reply is ``{"handle"}``; with the fleet
        scheduler enabled it may instead be ``{"queued": true,
        "fleet_job", "position"}``. The fleet fields (``priority``,
        ``elastic``, ``mesh``, ``replicas``/``chips`` overrides,
        ``min_replicas``) are ignored by a daemon without a fleet."""
        payload: dict = {
            "component": component,
            "args": list(args),
            "scheduler": scheduler,
            "cfg": dict(cfg or {}),
            "cfg_str": cfg_str,
            "workspace": workspace,
            "elastic": bool(elastic),
            "mesh": mesh,
        }
        if priority is not None:
            payload["priority"] = priority
        if replicas is not None:
            payload["replicas"] = int(replicas)
        if chips is not None:
            payload["chips"] = int(chips)
        if min_replicas is not None:
            payload["min_replicas"] = int(min_replicas)
        return self._request("/v1/submit", payload)

    def submit(
        self,
        component: str,
        args: list[str],
        scheduler: str,
        cfg: Optional[dict] = None,
        cfg_str: str = "",
        workspace: Optional[str] = None,
    ) -> str:
        """Submit through the daemon. ``cfg_str`` ships the CLI's raw
        ``-cfg k=v,...`` string so the daemon parses it against the
        backend's typed runopts schema (the client stays schema-blind).

        Callers of this verb need a handle NOW; a fleet-queued reply
        (no handle yet) surfaces as a 202-coded
        :class:`ControlClientError` naming the fleet job id."""
        reply = self.submit_job(
            component,
            args,
            scheduler,
            cfg=cfg,
            cfg_str=cfg_str,
            workspace=workspace,
        )
        handle = reply.get("handle")
        if not handle:
            raise ControlClientError(
                202,
                f"queued as {reply.get('fleet_job')} at position"
                f" {reply.get('position')}; watch with `tpx queue`",
            )
        return str(handle)

    def queue(self) -> dict:
        """The fleet scheduler's queue + placement snapshot
        (``{"enabled": false}`` when the daemon has no fleet)."""
        return self._request("/v1/queue")

    def metrics_query(
        self,
        name: Optional[str] = None,
        labels: Optional[dict] = None,
        reduce: Optional[str] = None,
        range_s: Optional[float] = None,
    ) -> dict:
        """Query the daemon's telemetry plane (``/v1/metrics/query``).

        No ``name`` lists the known metric names; with one, returns the
        raw windowed series plus the reducer's per-label-set scalars
        (``reduce`` = last/sum/avg/max/min/rate/pNN)."""
        from urllib.parse import quote

        parts = []
        if name:
            parts.append(f"name={quote(name, safe='')}")
        if reduce:
            parts.append(f"reduce={quote(reduce, safe='')}")
        if range_s is not None:
            parts.append(f"range={range_s:g}")
        for k, v in (labels or {}).items():
            parts.append(f"label.{quote(k, safe='')}={quote(str(v), safe='')}")
        return self._request(
            "/v1/metrics/query" + ("?" + "&".join(parts) if parts else "")
        )

    def alerts(self) -> dict:
        """Active SLO alerts + last burn rates (``/v1/alerts``)."""
        return self._request("/v1/alerts")

    def add_scrape_target(self, url: str, name: Optional[str] = None) -> dict:
        """Register a replica ``/metricz`` URL with the daemon's
        collector; returns ``{"source", "targets"}``."""
        payload: dict = {"url": url}
        if name:
            payload["name"] = name
        return self._request("/v1/metrics/targets", payload)

    def remove_scrape_target(self, name: str) -> dict:
        """Drop a scrape target by source name."""
        return self._request("/v1/metrics/targets", {"remove": name})

    def pipeline_submit(self, spec: dict) -> dict:
        """Submit a train→eval→promote DAG (``POST /v1/pipelines``).

        ``spec`` is a :class:`~torchx_tpu.pipelines.dag.PipelineSpec`
        dict (``{"name", "stages": [...]}``); returns
        ``{"pipeline": "pl_N"}``."""
        return self._request("/v1/pipelines", {"spec": spec})

    def pipeline_status(self, pipeline: Optional[str] = None) -> dict:
        """One pipeline's stage-by-stage record, or the full list +
        current incumbent when ``pipeline`` is None."""
        path = "/v1/pipelines"
        if pipeline:
            from urllib.parse import quote

            path += f"?pipeline={quote(pipeline, safe='')}"
        return self._request(path)

    def pipeline_cancel(self, pipeline: str) -> dict:
        """Cancel a running pipeline: in-flight stages are cancelled on
        their backends and the pipeline journals CANCELLED."""
        return self._request("/v1/pipelines/cancel", {"pipeline": pipeline})

    def cell_status(self) -> dict:
        """The daemon's federation-cell identity + lifecycle
        (``GET /v1/cell``): ``{"cell", "state", "draining",
        "rehydrated", "rehydration"}``."""
        return self._request("/v1/cell")

    def cell_drain(self) -> dict:
        """Begin draining this cell: in-flight work keeps running, new
        submissions are refused with 503 so a federation router spills
        them to the next-best cell."""
        return self._request("/v1/cell/drain", {})

    def cell_uncordon(self) -> dict:
        """Reopen a drained/draining cell for new traffic."""
        return self._request("/v1/cell/uncordon", {})

    def status(self, handle: str) -> dict:
        """One job's recorded state: answered from the daemon's
        reconciler journal + shared describe cache, not a fresh backend
        describe per call."""
        from urllib.parse import quote

        return self._request(f"/v1/status?handle={quote(handle, safe='')}")

    def list(self, scheduler: Optional[str] = None) -> list[dict]:
        """All jobs the daemon tracks, optionally filtered by backend."""
        path = "/v1/list"
        if scheduler:
            from urllib.parse import quote

            path += f"?scheduler={quote(scheduler, safe='')}"
        return list(self._request(path).get("apps", []))

    def cancel(self, handle: str) -> None:
        """Cancel the job on its backend (and release the tenant's slot)."""
        self._request("/v1/cancel", {"handle": handle})

    #: consecutive transport failures :meth:`wait` rides out before the
    #: error surfaces (a daemon restart drops every in-flight long-poll;
    #: the journal-rehydrated successor answers the re-issued one).
    WAIT_RECONNECT_ATTEMPTS = 10

    def wait(self, handle: str, timeout: Optional[float] = None) -> dict:
        """Block until terminal: chained bounded long-polls against
        ``/v1/wait`` (each HTTP request stays short; the daemon's
        reconciler wakes it the moment the terminal event lands).

        A transport failure mid-chain — the daemon restarting under the
        wait is the common case — is retried with capped jittered
        backoff instead of erroring: the successor daemon rehydrates its
        journal, so the re-issued poll resolves against the recorded
        (possibly already-terminal) state. Only
        :data:`WAIT_RECONNECT_ATTEMPTS` *consecutive* failures surface.
        """
        deadline = None if timeout is None else self._clock() + timeout
        from urllib.parse import quote

        transport_failures = 0
        while True:
            budget = 30.0
            if deadline is not None:
                budget = min(budget, max(0.1, deadline - self._clock()))
            try:
                payload = self._request(
                    f"/v1/wait?handle={quote(handle, safe='')}"
                    f"&timeout={budget:g}",
                    timeout=budget + 15.0,
                )
            except ControlClientError as e:
                if e.code != 0:
                    raise  # a real HTTP verdict (401/404/...) is final
                transport_failures += 1
                if transport_failures >= self.WAIT_RECONNECT_ATTEMPTS:
                    raise
                if deadline is not None and self._clock() >= deadline:
                    raise TimeoutError(
                        f"app {handle} unreachable at deadline: {e.message}"
                    ) from e
                delay = min(0.25 * (2.0 ** (transport_failures - 1)), 5.0)
                self._sleep(delay * (1.0 + self._rng.uniform(-0.1, 0.1)))
                continue
            transport_failures = 0
            if payload.get("terminal"):
                return payload
            if deadline is not None and self._clock() >= deadline:
                raise TimeoutError(
                    f"app {handle} still {payload.get('state')} after {timeout}s"
                )

    def log_lines(
        self,
        handle: str,
        role_name: str = "app",
        k: int = 0,
        tail: bool = False,
    ) -> Iterator[str]:
        """Stream one replica's log lines through the daemon (JSONL);
        ``tail=True`` follows the stream until the app finishes."""
        from urllib.parse import quote

        req = urllib.request.Request(
            f"{self.addr}/v1/logs?handle={quote(handle, safe='')}"
            f"&role={quote(role_name, safe='')}&k={int(k)}"
            f"&tail={'1' if tail else '0'}",
            headers={"Authorization": f"Bearer {self.token}"},
        )
        try:
            resp = urllib.request.urlopen(
                req, timeout=None if tail else self.timeout
            )
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read() or b"{}").get("error", str(e))
            except ValueError:
                message = str(e)
            raise ControlClientError(e.code, message) from e
        except (urllib.error.URLError, OSError) as e:
            raise ControlClientError(0, f"control daemon unreachable: {e}") from e
        with resp:
            for raw in resp:
                try:
                    doc = json.loads(raw)
                except ValueError:
                    continue
                if doc.get("done"):
                    return
                if "line" in doc:
                    yield str(doc["line"])


def _discovery() -> Optional[tuple[str, str]]:
    """(addr, token) from the daemon's discovery file, if one exists."""
    from torchx_tpu.control.daemon import DISCOVERY_FILE, control_dir

    path = os.path.join(control_dir(), DISCOVERY_FILE)
    try:
        with open(path) as f:
            doc = json.load(f)
        addr, token = str(doc.get("addr", "")), str(doc.get("token", ""))
        if addr and token:
            return addr, token
    except (OSError, ValueError):
        pass
    return None


def maybe_client(require_env: bool = True) -> Optional[ControlClient]:
    """The CLI's proxy decision, in one place.

    ``$TPX_CONTROL_ADDR`` set -> a client for that address (token from
    ``$TPX_CONTROL_TOKEN``, else the discovery file). Unset -> None
    (direct-runner mode) unless ``require_env=False``, which also accepts
    a discovery file alone (how ``tpx control status`` finds its daemon).
    """
    addr = os.environ.get(settings.ENV_TPX_CONTROL_ADDR, "").strip()
    token = os.environ.get(settings.ENV_TPX_CONTROL_TOKEN, "").strip()
    if addr:
        if not token:
            found = _discovery()
            if found is not None and found[0].rstrip("/") == addr.rstrip("/"):
                token = found[1]
        if not token:
            raise ControlClientError(
                401,
                f"{settings.ENV_TPX_CONTROL_ADDR} is set but no token: set"
                f" {settings.ENV_TPX_CONTROL_TOKEN} or run the daemon with a"
                " readable discovery file",
            )
        return ControlClient(addr, token)
    if not require_env:
        found = _discovery()
        if found is not None:
            return ControlClient(found[0], found[1])
    return None
