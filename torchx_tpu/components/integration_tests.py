"""Component integration-test harness: run real components on a real
scheduler and assert they succeed.

Reference analog: torchx/components/integration_tests/integ_tests.py:27-60
+ component_provider.py — a ``ComponentProvider`` owns one component
invocation (setup/appdef/teardown); ``IntegComponentTest`` runs a batch of
providers against a scheduler + image and fails on the first unsuccessful
app. Driven by ``scripts/component_integration_tests.py`` in CI (local by
default; point it at gke/slurm for cluster e2e).
"""

from __future__ import annotations

import abc
import logging
from dataclasses import dataclass, field
from typing import Mapping, Optional, Type

from torchx_tpu.runner.api import get_runner
from torchx_tpu.specs.api import AppDef, AppState, CfgVal

logger = logging.getLogger(__name__)


class ComponentProvider(abc.ABC):
    """One component invocation to validate end-to-end."""

    def __init__(self, scheduler: str, image: str) -> None:
        self._scheduler = scheduler
        self._image = image

    def setUp(self) -> None:  # noqa: N802 (reference naming)
        pass

    def tearDown(self) -> None:  # noqa: N802
        pass

    @abc.abstractmethod
    def get_app_def(self) -> AppDef:
        ...


class EchoProvider(ComponentProvider):
    def get_app_def(self) -> AppDef:
        from torchx_tpu.components.utils import echo

        return echo(msg="integ-echo", image=self._image)


class BoothProvider(ComponentProvider):
    def get_app_def(self) -> AppDef:
        from torchx_tpu.components.utils import booth

        return booth(x1=1.0, x2=3.0, image=self._image)


class SpmdMeshProvider(ComponentProvider):
    """The flagship: 2-process SPMD mesh formation (CPU-simulated)."""

    def get_app_def(self) -> AppDef:
        import os

        import torchx_tpu
        from torchx_tpu.components.dist import spmd

        script = os.path.join(
            os.path.dirname(torchx_tpu.__file__), "examples", "compute_mesh_size.py"
        )
        return spmd(script=script, j="2x2", image=self._image)


DEFAULT_PROVIDERS: list[Type[ComponentProvider]] = [
    EchoProvider,
    BoothProvider,
    SpmdMeshProvider,
]


@dataclass
class IntegResult:
    provider: str
    handle: Optional[str]
    state: Optional[AppState]
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.state == AppState.SUCCEEDED


@dataclass
class IntegComponentTest:
    scheduler: str = "local"
    image: str = ""
    cfg: Mapping[str, CfgVal] = field(default_factory=dict)
    wait_interval: float = 1.0

    def run_components(
        self, providers: Optional[list[Type[ComponentProvider]]] = None
    ) -> list[IntegResult]:
        results: list[IntegResult] = []
        with get_runner("integ-tests") as runner:
            for provider_cls in providers or DEFAULT_PROVIDERS:
                name = provider_cls.__name__
                provider = provider_cls(self.scheduler, self.image)
                try:
                    provider.setUp()
                    app = provider.get_app_def()
                    handle = runner.run(app, self.scheduler, dict(self.cfg))
                    status = runner.wait(handle, wait_interval=self.wait_interval)
                    results.append(
                        IntegResult(
                            provider=name,
                            handle=handle,
                            state=status.state if status else None,
                        )
                    )
                    logger.info(
                        "%s -> %s (%s)", name, status.state if status else "?", handle
                    )
                except Exception as e:  # noqa: BLE001 - collect, report at end
                    results.append(
                        IntegResult(provider=name, handle=None, state=None, error=str(e))
                    )
                finally:
                    provider.tearDown()
        return results

    def assert_all_succeeded(
        self, providers: Optional[list[Type[ComponentProvider]]] = None
    ) -> None:
        results = self.run_components(providers)
        failures = [r for r in results if not r.ok]
        if failures:
            lines = [
                f"  {r.provider}: state={r.state} error={r.error} handle={r.handle}"
                for r in failures
            ]
            raise AssertionError(
                f"{len(failures)}/{len(results)} component integration tests"
                " failed:\n" + "\n".join(lines)
            )
