"""Distributed components — the heart of the launcher.

Reference analog: torchx/components/dist.py (dist.ddp at :162-308). Where
``dist.ddp`` gang-launches ``nodes x procs`` torchrun agents that rendezvous
over a c10d TCPStore, the TPU flagship :func:`spmd` gang-launches **one JAX
process per TPU-VM host** and boots ``jax.distributed`` with the
coordinator address derived from the launcher's rendezvous macro
(``macros.coordinator_env`` ≙ the reference's ``rank0_env`` trick at
dist.py:234-243).

Topology model:

* ``--tpu v5p-32`` (or ``-h tpu_v5p_16``) selects a slice; the gang size is
  the slice's host count — the user never counts processes by hand.
* ``-j N`` with a TPU resource means **N slices** (multi-slice DCN
  training); megascale env wiring is injected by the schedulers.
* without a TPU resource, ``-j {replicas}x{nproc}`` runs ``replicas``
  processes with ``nproc`` simulated CPU devices each — the local test mode
  (reference analog of ``-j {nnodes}x{nproc_per_node}``).
* ``-j min:max`` lower bound sets ``min_replicas`` for elastic gangs
  (reference dist.py:294-296).
"""

from __future__ import annotations

import re
import shlex
from typing import Optional

import torchx_tpu.specs as specs
from torchx_tpu import settings
from torchx_tpu.specs.api import macros
from torchx_tpu.version import TORCHX_TPU_IMAGE

# Debug env preset (reference analog: _TORCH_DEBUG_FLAGS, dist.py:70-83).
_TPU_DEBUG_FLAGS: dict[str, str] = {
    "TPU_STDERR_LOG_LEVEL": "0",
    "TPU_MIN_LOG_LEVEL": "0",
    "JAX_TRACEBACK_FILTERING": "off",
    "JAX_LOG_COMPILES": "1",
}

_J_RE = re.compile(
    r"^(?:(?P<min>\d+):)?(?P<replicas>\d+)(?:x(?P<nproc>\d+))?$"
)


def parse_j(j: str) -> tuple[Optional[int], int, Optional[int]]:
    """``[min:]replicas[xnproc]`` -> (min_replicas, replicas, nproc).

    >>> parse_j("2x4")
    (None, 2, 4)
    >>> parse_j("1:4")
    (1, 4, None)
    """
    m = _J_RE.match(j.strip())
    if not m:
        raise ValueError(
            f"invalid -j format {j!r}; expected [min_replicas:]replicas[xnproc]"
        )
    return (
        int(m.group("min")) if m.group("min") else None,
        int(m.group("replicas")),
        int(m.group("nproc")) if m.group("nproc") else None,
    )


def spmd(
    *script_args: str,
    script: Optional[str] = None,
    m: Optional[str] = None,
    image: str = TORCHX_TPU_IMAGE,
    name: str = "/",
    tpu: Optional[str] = None,
    h: Optional[str] = None,
    j: str = "1",
    env: Optional[dict[str, str]] = None,
    cpu: int = 2,
    memMB: int = 4096,
    max_retries: int = 0,
    mounts: Optional[list[str]] = None,
    debug: bool = False,
    coordinator_port: int = settings.TPX_COORDINATOR_PORT,
) -> specs.AppDef:
    """Launch a JAX SPMD application on a TPU slice (or simulated CPU mesh).

    One process per TPU-VM host; ``jax.distributed`` is initialized on every
    host with the coordinator address wired by the launcher, then the user
    script/module runs in-process. This is the TPU analog of ``dist.ddp``.

    Args:
        script_args: arguments to the main module or script
        script: script to run (either script or m must be set)
        m: python module to run as __main__
        image: container image (or local dir for the local scheduler)
        name: job name override in the form ``{name}/{role}``
        tpu: TPU accelerator type, e.g. ``v5p-32`` / ``v5litepod-8``
        h: named resource (e.g. ``tpu_v5p_16`` or ``cpu_small``); wins over tpu
        j: ``[min:]replicas[xnproc]`` — replicas = slices when a TPU resource
            is set, else processes; nproc = simulated devices per process
            (CPU mode only)
        env: extra environment variables
        cpu: cpu per replica (CPU mode only)
        memMB: RAM MB per replica (CPU mode only)
        max_retries: scheduler retries for the whole gang
        mounts: docker-style mount specs
        debug: enable verbose TPU/JAX debug env preset
        coordinator_port: jax.distributed coordinator port
    """
    if (script is None) == (m is None):
        raise ValueError("exactly one of --script and -m must be set")

    min_replicas, replicas, nproc = parse_j(j)

    if tpu or h:
        resource = specs.resource(h=h) if h else specs.named_resources[str(tpu)]
    else:
        resource = specs.resource(cpu=cpu, memMB=memMB)

    role_env: dict[str, str] = {}
    if resource.tpu is None and nproc:
        # local/CI mode: each process simulates `nproc` devices on CPU
        role_env[settings.ENV_JAX_PLATFORMS] = "cpu"
        role_env[settings.ENV_XLA_FLAGS] = (
            f"--xla_force_host_platform_device_count={nproc}"
        )
    if debug:
        role_env.update(_TPU_DEBUG_FLAGS)
    if env:
        role_env.update(env)

    app_name, role_name = _parse_name(name, default_role="spmd")
    if not app_name:
        app_name = _infer_app_name(script, m)

    if script:
        prog = ["--script", script]
    else:
        prog = ["-m", str(m)]

    cmd = [
        "-u",
        "-m",
        "torchx_tpu.apps.spmd_main",
        "--port",
        str(coordinator_port),
        *prog,
        "--",
        *script_args,
    ]

    return specs.AppDef(
        name=app_name,
        roles=[
            specs.Role(
                name=role_name,
                image=image,
                min_replicas=min_replicas,
                entrypoint="python",
                args=cmd,
                env=role_env,
                num_replicas=replicas,
                max_retries=max_retries,
                retry_policy=specs.RetryPolicy.APPLICATION,
                resource=resource,
                port_map={"coordinator": coordinator_port},
                mounts=specs.parse_mounts(mounts) if mounts else [],
            )
        ],
    )


def _parse_name(name: str, default_role: str) -> tuple[str, str]:
    """``{app}/{role}`` with either side optional (reference
    StructuredNameArgument, components/structured_arg.py)."""
    if "/" in name:
        app, _, role = name.partition("/")
        return app, role or default_role
    return name, default_role


def _infer_app_name(script: Optional[str], m: Optional[str]) -> str:
    if script:
        stem = script.rsplit("/", 1)[-1]
        return stem.removesuffix(".py") or "spmd"
    assert m is not None
    return m.rsplit(".", 1)[-1]


def ddp(
    *script_args: str,
    script: Optional[str] = None,
    m: Optional[str] = None,
    image: str = TORCHX_TPU_IMAGE,
    name: str = "/",
    h: Optional[str] = None,
    j: str = "1x2",
    env: Optional[dict[str, str]] = None,
    cpu: int = 2,
    memMB: int = 4096,
    max_retries: int = 0,
    rdzv_port: int = 29500,
    debug: bool = False,
) -> specs.AppDef:
    """Launch a torch DistributedDataParallel app via torchrun (compat
    component for torch workloads on CPU/GPU node pools; TPU jobs should
    use :func:`spmd`).

    Builds the same c10d rendezvous wiring as the reference's dist.ddp
    (torchx/components/dist.py:224-287): single node uses a dynamic
    localhost endpoint, multi-node defers the coordinator hostname to the
    scheduler-injected env var at runtime.

    Args:
        script_args: arguments to the main module or script
        script: script to run (either script or m must be set)
        m: python module to run as __main__
        image: container image
        name: job name override in the form ``{name}/{role}``
        h: named resource
        j: ``[min_nnodes:]nnodes x nproc_per_node``
        env: extra env variables
        cpu: cpu per replica
        memMB: RAM MB per replica
        max_retries: scheduler retries
        rdzv_port: c10d rendezvous port on the rank0 host
        debug: verbose torch debug env
    """
    if (script is None) == (m is None):
        raise ValueError("exactly one of --script and -m must be set")
    min_nnodes, nnodes, nproc = parse_j(j)
    nproc = nproc or 1
    app_name, role_name = _parse_name(name, default_role="ddp")
    if not app_name:
        app_name = _infer_app_name(script, m)

    single_node = nnodes == 1 and min_nnodes is None
    nnodes_arg = f"{min_nnodes}:{nnodes}" if min_nnodes else str(nnodes)

    role_env = dict(env or {})
    if debug:
        role_env.update(
            {
                "TORCH_DISTRIBUTED_DEBUG": "DETAIL",
                "TORCH_SHOW_CPP_STACKTRACES": "1",
            }
        )

    # multi-node: the coordinator hostname is only known at runtime (the env
    # var *name* comes from the macro; the shell expands the value on each
    # replica — reference dist.py:234-243). `$$` survives macro substitution
    # as a literal `$` for the runtime shell.
    # "$${" + "${coordinator_env}" + ":=localhost}" --macro-substitutes-to->
    # "${TPX_COORDINATOR_HOST:=localhost}:PORT" for the runtime shell.
    rdzv_endpoint = (
        "localhost:0"
        if single_node
        else f"$${{{macros.coordinator_env}:=localhost}}:{rdzv_port}"
    )
    torchrun_args = [
        "-m",
        "torch.distributed.run",
        "--rdzv_backend",
        "c10d",
        "--rdzv_endpoint",
        rdzv_endpoint,
        "--rdzv_id",
        macros.app_id,
        "--nnodes",
        nnodes_arg,
        "--nproc_per_node",
        str(nproc),
        "--tee",
        "3",
        "--role",
        role_name,
    ]
    if script:
        torchrun_args += [script, *script_args]
    else:
        torchrun_args += ["-m", str(m), *script_args]

    if single_node:
        entrypoint = "python"
        args = ["-u", *torchrun_args]
    else:
        entrypoint = "sh"
        shell_cmd = " ".join(
            a if a.startswith("$") else shlex.quote(a)
            for a in ["python", "-u", *torchrun_args]
        )
        args = ["-c", shell_cmd]

    return specs.AppDef(
        name=app_name,
        roles=[
            specs.Role(
                name=role_name,
                image=image,
                min_replicas=min_nnodes,
                entrypoint=entrypoint,
                args=args,
                env=role_env,
                num_replicas=nnodes,
                max_retries=max_retries,
                resource=specs.resource(cpu=cpu, memMB=memMB, h=h),
                port_map={"c10d": rdzv_port},
            )
        ],
    )
