"""Utility components (reference analog: torchx/components/utils.py).

These are deliberately trivial AppDef factories used for smoke tests,
examples, and as scaffolding in pipelines (sh glue steps, file touch
barriers, data copies).
"""

from __future__ import annotations

import shlex
from typing import Optional

import torchx_tpu.specs as specs
from torchx_tpu.version import TORCHX_TPU_IMAGE


def echo(
    msg: str = "hello world", image: str = TORCHX_TPU_IMAGE, num_replicas: int = 1
) -> specs.AppDef:
    """Echos a message to stdout (for testing).

    Args:
        msg: message to echo
        image: image to use
        num_replicas: number of replicas to run
    """
    return specs.AppDef(
        name="echo",
        roles=[
            specs.Role(
                name="echo",
                image=image,
                entrypoint="echo",
                args=[msg],
                num_replicas=num_replicas,
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )


def touch(file: str, image: str = TORCHX_TPU_IMAGE) -> specs.AppDef:
    """Touches a file (for testing and as a pipeline barrier).

    Args:
        file: file to create
        image: image to use
    """
    return specs.AppDef(
        name="touch",
        roles=[
            specs.Role(
                name="touch",
                image=image,
                entrypoint="touch",
                args=[file],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )


def sh(
    *args: str,
    image: str = TORCHX_TPU_IMAGE,
    num_replicas: int = 1,
    cpu: int = 1,
    memMB: int = 1024,
    h: Optional[str] = None,
    env: Optional[dict[str, str]] = None,
    max_retries: int = 0,
    mounts: Optional[list[str]] = None,
) -> specs.AppDef:
    """Runs the provided command via sh.

    Args:
        args: bash arguments (will be quoted)
        image: image to use
        num_replicas: number of replicas to run
        cpu: cpu count per replica
        memMB: RAM per replica in MB
        h: named resource (overrides cpu/memMB)
        env: environment variables
        max_retries: number of retries allowed
        mounts: mounts to add, docker-style string form
    """
    escaped = " ".join(shlex.quote(a) for a in args)
    return specs.AppDef(
        name="sh",
        roles=[
            specs.Role(
                name="sh",
                image=image,
                entrypoint="sh",
                args=["-c", escaped],
                num_replicas=num_replicas,
                env=env or {},
                max_retries=max_retries,
                resource=specs.resource(cpu=cpu, memMB=memMB, h=h),
                mounts=specs.parse_mounts(mounts) if mounts else [],
            )
        ],
    )


def python(
    *args: str,
    m: Optional[str] = None,
    c: Optional[str] = None,
    script: Optional[str] = None,
    image: str = TORCHX_TPU_IMAGE,
    name: str = "python",
    cpu: int = 1,
    memMB: int = 1024,
    h: Optional[str] = None,
    num_replicas: int = 1,
    env: Optional[dict[str, str]] = None,
) -> specs.AppDef:
    """Runs python with the specified module, command or script on the local
    image.

    Args:
        args: arguments passed to the program
        m: run a module as __main__
        c: program passed as string
        script: python script to run
        image: image to use
        name: name of the job
        cpu: cpu count per replica
        memMB: RAM per replica in MB
        h: named resource (overrides cpu/memMB)
        num_replicas: number of replicas
        env: environment variables
    """
    chosen = [x for x in (m, c, script) if x is not None]
    if len(chosen) != 1:
        raise ValueError("exactly one of --m, --c, --script must be set")
    if m is not None:
        prog_args = ["-m", m, *args]
    elif c is not None:
        prog_args = ["-c", c, *args]
    else:
        prog_args = [str(script), *args]
    return specs.AppDef(
        name=name,
        roles=[
            specs.Role(
                name=name,
                image=image,
                entrypoint="python",
                args=["-u", *prog_args],
                num_replicas=num_replicas,
                env=env or {},
                resource=specs.resource(cpu=cpu, memMB=memMB, h=h),
            )
        ],
    )


def copy(src: str, dst: str, image: str = TORCHX_TPU_IMAGE) -> specs.AppDef:
    """Copies the provided file or directory (fsspec URLs supported).

    Args:
        src: source path or url
        dst: destination path or url
        image: image to use
    """
    return specs.AppDef(
        name="copy",
        roles=[
            specs.Role(
                name="copy",
                image=image,
                entrypoint="python",
                args=["-m", "torchx_tpu.apps.copy_main", "--src", src, "--dst", dst],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )


def booth(
    x1: float,
    x2: float,
    image: str = TORCHX_TPU_IMAGE,
) -> specs.AppDef:
    """Evaluates the booth function at (x1, x2) and tracks the result
    (test objective for tracker/hpo integration).

    Args:
        x1: x1 coordinate
        x2: x2 coordinate
        image: image to use
    """
    return specs.AppDef(
        name="booth",
        roles=[
            specs.Role(
                name="booth",
                image=image,
                entrypoint="python",
                args=["-m", "torchx_tpu.apps.booth_main", "--x1", str(x1), "--x2", str(x2)],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )
