"""Built-in components: plain functions returning AppDef.

Discovered by specs.finder; names are relative to this package
(``dist.spmd``, ``utils.echo``). Reference analog: torchx/components/.
"""
