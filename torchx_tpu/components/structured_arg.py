"""Structured CLI argument parsers shared by components.

Reference analog: torchx/components/structured_arg.py (236 LoC) —
``StructuredNameArgument`` ({experiment}/{run} name parsing) and
``StructuredJArgument`` (-j with per-host device inference from the named
resource).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from torchx_tpu.specs import named_resources


@dataclasses.dataclass
class StructuredNameArgument:
    """``{app_name}/{role_name}`` with either side optional."""

    app_name: str
    role_name: str

    @classmethod
    def parse_from(
        cls, name: str, default_app: str = "app", default_role: str = "role"
    ) -> "StructuredNameArgument":
        """Parse ``app[/role]`` (either part optional) into names."""
        if "/" in name:
            app, _, role = name.partition("/")
            return cls(app_name=app or default_app, role_name=role or default_role)
        return cls(app_name=name or default_app, role_name=default_role)


@dataclasses.dataclass
class StructuredJArgument:
    """``[min_replicas:]replicas[xnproc]`` where nproc (devices per process)
    is inferred from the named resource's TPU slice when omitted.

    >>> StructuredJArgument.parse_from("2x4").replicas
    2
    >>> StructuredJArgument.parse_from("2", h="v5litepod-8").nproc
    8
    """

    replicas: int
    nproc: int
    min_replicas: Optional[int] = None

    @classmethod
    def parse_from(cls, j: str, h: Optional[str] = None) -> "StructuredJArgument":
        """Parse a ``-j`` string, inferring nproc from the named
        resource ``h`` when the ``x nproc`` part is omitted."""
        from torchx_tpu.components.dist import parse_j

        min_replicas, replicas, nproc = parse_j(j)
        if nproc is None:
            if h is not None and h in named_resources:
                res = named_resources[h]
                nproc = res.tpu.chips_per_host if res.tpu else 1
            else:
                nproc = 1
        return cls(replicas=replicas, nproc=nproc, min_replicas=min_replicas)

    def __str__(self) -> str:
        prefix = f"{self.min_replicas}:" if self.min_replicas else ""
        return f"{prefix}{self.replicas}x{self.nproc}"
