"""Serving components (reference analog: torchx/components/serve.py:19-77;
``generate_server`` goes beyond the reference — an actual TPU inference
server, not just a registration client)."""

from __future__ import annotations

from typing import Optional

import torchx_tpu.specs as specs
from torchx_tpu.version import TORCHX_TPU_IMAGE


def model_server(
    model_path: str,
    management_api: str,
    model_name: str = "model",
    image: str = TORCHX_TPU_IMAGE,
    timeout: float = 60.0,
) -> specs.AppDef:
    """Register a model archive with a running model server's management
    API (a one-shot registration client, not the server itself).

    Args:
        model_path: url/path of the model artifact to register
        management_api: base URL of the server management API
        model_name: name to register the model under
        image: image to use
        timeout: registration request timeout seconds
    """
    return specs.AppDef(
        name="model-server-register",
        roles=[
            specs.Role(
                name="register",
                image=image,
                entrypoint="python",
                args=[
                    "-m",
                    "torchx_tpu.apps.serve_main",
                    "--model_path",
                    model_path,
                    "--management_api",
                    management_api,
                    "--model_name",
                    model_name,
                    "--timeout",
                    str(timeout),
                ],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )


def generate_server(
    config: str,
    port: int = 8000,
    ckpt_dir: Optional[str] = None,
    int8: bool = False,
    image: str = TORCHX_TPU_IMAGE,
    tpu: Optional[str] = None,
    cpu: int = 4,
    memMB: int = 16384,
    batch_window_ms: float = 3.0,
    max_batch: int = 16,
    engine: str = "continuous",
    block_size: int = 16,
    num_blocks: Optional[int] = None,
    num_replicas: int = 1,
    port_stride: int = 0,
) -> specs.AppDef:
    """Serve KV-cache generation for a model family over HTTP
    (POST /v1/generate, GET /healthz, GET /metricz) — the TPU-native
    serving half the reference delegates to TorchServe. The default
    ``continuous`` engine runs continuous batching over a paged KV cache
    (:mod:`torchx_tpu.serve.engine`); ``coalesce`` selects the legacy
    batch-to-completion batcher thread.

    Args:
        config: model config name (e.g. ``llama3_1b``)
        port: HTTP port to listen on
        ckpt_dir: orbax checkpoint directory to restore weights from
        int8: serve int8 weight-only quantized (2x MXU, half weight HBM)
        image: container image
        tpu: TPU accelerator type (e.g. ``v5litepod-8``); CPU when unset
        cpu: cpu count for CPU serving
        memMB: memory for CPU serving
        batch_window_ms: coalesce-engine batching window
        max_batch: decode slots (continuous) / max coalesced batch
        engine: ``continuous`` (paged KV) or ``coalesce`` (legacy)
        block_size: paged KV-cache block size (continuous engine)
        num_blocks: paged KV pool size in blocks (default: from max_batch)
        num_replicas: server replicas (a serve pool resizes this)
        port_stride: replica i listens on ``port + stride * i`` so a pool's
            co-located replicas get distinct ports
    """
    args = [
        "-m",
        "torchx_tpu.apps.generate_server",
        "--config",
        config,
        "--port",
        str(port),
        "--batch-window-ms",
        str(batch_window_ms),
        "--max-batch",
        str(max_batch),
        "--engine",
        engine,
        "--block-size",
        str(block_size),
    ]
    if num_blocks is not None:
        args += ["--num-blocks", str(num_blocks)]
    if port_stride:
        args += ["--port-stride", str(port_stride)]
    if ckpt_dir:
        args += ["--ckpt-dir", ckpt_dir]
    if int8:
        args += ["--int8"]
    resource = specs.resource(cpu=cpu, memMB=memMB, tpu=tpu)
    return specs.AppDef(
        name=f"generate-{config}",
        roles=[
            specs.Role(
                name="server",
                image=image,
                entrypoint="python",
                args=args,
                num_replicas=num_replicas,
                port_map={"http": port},
                resource=resource,
            )
        ],
    )
