"""Serving components (reference analog: torchx/components/serve.py:19-77;
``generate_server`` goes beyond the reference — an actual TPU inference
server, not just a registration client)."""

from __future__ import annotations

from typing import Optional

import torchx_tpu.specs as specs
from torchx_tpu.version import TORCHX_TPU_IMAGE


def model_server(
    model_path: str,
    management_api: str,
    model_name: str = "model",
    image: str = TORCHX_TPU_IMAGE,
    timeout: float = 60.0,
) -> specs.AppDef:
    """Register a model archive with a running model server's management
    API (a one-shot registration client, not the server itself).

    Args:
        model_path: url/path of the model artifact to register
        management_api: base URL of the server management API
        model_name: name to register the model under
        image: image to use
        timeout: registration request timeout seconds
    """
    return specs.AppDef(
        name="model-server-register",
        roles=[
            specs.Role(
                name="register",
                image=image,
                entrypoint="python",
                args=[
                    "-m",
                    "torchx_tpu.apps.serve_main",
                    "--model_path",
                    model_path,
                    "--management_api",
                    management_api,
                    "--model_name",
                    model_name,
                    "--timeout",
                    str(timeout),
                ],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )


def generate_server(
    config: str,
    port: int = 8000,
    ckpt_dir: Optional[str] = None,
    int8: bool = False,
    image: str = TORCHX_TPU_IMAGE,
    tpu: Optional[str] = None,
    cpu: int = 4,
    memMB: int = 16384,
    batch_window_ms: float = 3.0,
    max_batch: int = 16,
    engine: str = "continuous",
    block_size: int = 16,
    num_blocks: Optional[int] = None,
    num_replicas: int = 1,
    port_stride: int = 0,
    prefix_cache: bool = True,
    prefix_cache_reserve: float = 0.0,
) -> specs.AppDef:
    """Serve KV-cache generation for a model family over HTTP
    (POST /v1/generate, GET /healthz, GET /metricz) — the TPU-native
    serving half the reference delegates to TorchServe. The default
    ``continuous`` engine runs continuous batching over a paged KV cache
    (:mod:`torchx_tpu.serve.engine`); ``coalesce`` selects the legacy
    batch-to-completion batcher thread.

    Args:
        config: model config name (e.g. ``llama3_1b``)
        port: HTTP port to listen on
        ckpt_dir: orbax checkpoint directory to restore weights from
        int8: serve int8 weight-only quantized (2x MXU, half weight HBM)
        image: container image
        tpu: TPU accelerator type (e.g. ``v5litepod-8``); CPU when unset
        cpu: cpu count for CPU serving
        memMB: memory for CPU serving
        batch_window_ms: coalesce-engine batching window
        max_batch: decode slots (continuous) / max coalesced batch
        engine: ``continuous`` (paged KV) or ``coalesce`` (legacy)
        block_size: paged KV-cache block size (continuous engine)
        num_blocks: paged KV pool size in blocks (default: from max_batch)
        num_replicas: server replicas (a serve pool resizes this)
        port_stride: replica i listens on ``port + stride * i`` so a pool's
            co-located replicas get distinct ports
        prefix_cache: radix prefix cache over the paged pool (continuous)
        prefix_cache_reserve: cap cached prefix blocks at this fraction of
            the KV pool (0 = share the whole pool)
    """
    args = [
        "-m",
        "torchx_tpu.apps.generate_server",
        "--config",
        config,
        "--port",
        str(port),
        "--batch-window-ms",
        str(batch_window_ms),
        "--max-batch",
        str(max_batch),
        "--engine",
        engine,
        "--block-size",
        str(block_size),
    ]
    if num_blocks is not None:
        args += ["--num-blocks", str(num_blocks)]
    if port_stride:
        args += ["--port-stride", str(port_stride)]
    if ckpt_dir:
        args += ["--ckpt-dir", ckpt_dir]
    if int8:
        args += ["--int8"]
    if not prefix_cache:
        args += ["--no-prefix-cache"]
    if prefix_cache_reserve > 0:
        args += ["--prefix-cache-reserve", str(prefix_cache_reserve)]
    resource = specs.resource(cpu=cpu, memMB=memMB, tpu=tpu)
    return specs.AppDef(
        name=f"generate-{config}",
        roles=[
            specs.Role(
                name="server",
                image=image,
                entrypoint="python",
                args=args,
                num_replicas=num_replicas,
                port_map={"http": port},
                resource=resource,
            )
        ],
    )


def generate_server_disagg(
    config: str,
    prefill_port: int = 8000,
    decode_port: int = 8100,
    ckpt_dir: Optional[str] = None,
    int8: bool = False,
    image: str = TORCHX_TPU_IMAGE,
    tpu: Optional[str] = None,
    cpu: int = 4,
    memMB: int = 16384,
    max_batch: int = 16,
    block_size: int = 16,
    num_blocks: Optional[int] = None,
    prefill_replicas: int = 1,
    decode_replicas: int = 1,
    port_stride: int = 1,
    kv_transfer: Optional[str] = None,
    prefix_cache_reserve: float = 0.0,
) -> specs.AppDef:
    """Disaggregated generation serving: ONE app, two gangs.

    The ``prefill`` role takes client traffic, runs the cache-aware
    chunked prefill (radix prefix cache over the paged pool), and
    streams each prompt's computed KV blocks to the ``decode`` role over
    the declared transfer path; decode replicas accept handoffs on
    ``/v1/kv`` and batch pure decode steps. Both roles carry the
    transfer spec in role metadata (``tpx/kv_transfer``) so submit-time
    analysis (TPX213) can verify the pair is actually wired — a
    prefill/decode split without a transfer path is an assembly error,
    caught before any chip is provisioned.

    Args:
        config: model config name (e.g. ``llama3_1b``)
        prefill_port: prefill gang's base HTTP port
        decode_port: decode gang's base HTTP port
        ckpt_dir: orbax checkpoint directory to restore weights from
        int8: serve int8 weight-only quantized
        image: container image
        tpu: TPU accelerator type; CPU when unset
        cpu: cpu count for CPU serving
        memMB: memory for CPU serving
        max_batch: decode slots per replica
        block_size: paged KV-cache block size
        num_blocks: paged KV pool size in blocks (default: from max_batch)
        prefill_replicas: prefill gang size (its pool resizes this)
        decode_replicas: decode gang size (its pool resizes this)
        port_stride: replica i listens on ``port + stride * i``
        kv_transfer: transfer spec; defaults to ``http:`` over the decode
            gang's port range at the current ``decode_replicas``
        prefix_cache_reserve: cap cached prefix blocks at this fraction
            of the prefill pool (0 = share the whole pool)
    """
    if kv_transfer is None:
        kv_transfer = "http:" + ",".join(
            f"http://127.0.0.1:{decode_port + port_stride * i}"
            for i in range(decode_replicas)
        )
    # import via the jax-free module so component loading stays light
    from torchx_tpu.serve.kv_transfer import ROLE_METADATA_KEY, TransferConfig

    spec = TransferConfig.from_spec(kv_transfer).to_spec()  # validate early

    def _role_args(role: str, port: int) -> list[str]:
        args = [
            "-m",
            "torchx_tpu.apps.generate_server",
            "--config",
            config,
            "--port",
            str(port),
            "--max-batch",
            str(max_batch),
            "--engine",
            "continuous",
            "--block-size",
            str(block_size),
            "--serve-role",
            role,
            "--kv-transfer",
            spec,
        ]
        if role == "prefill" and prefix_cache_reserve > 0:
            args += ["--prefix-cache-reserve", str(prefix_cache_reserve)]
        if num_blocks is not None:
            args += ["--num-blocks", str(num_blocks)]
        if port_stride:
            args += ["--port-stride", str(port_stride)]
        if ckpt_dir:
            args += ["--ckpt-dir", ckpt_dir]
        if int8:
            args += ["--int8"]
        return args

    resource = specs.resource(cpu=cpu, memMB=memMB, tpu=tpu)
    return specs.AppDef(
        name=f"generate-{config}-disagg",
        roles=[
            specs.Role(
                name="prefill",
                image=image,
                entrypoint="python",
                args=_role_args("prefill", prefill_port),
                num_replicas=prefill_replicas,
                port_map={"http": prefill_port},
                resource=resource,
                metadata={ROLE_METADATA_KEY: spec},
            ),
            specs.Role(
                name="decode",
                image=image,
                entrypoint="python",
                args=_role_args("decode", decode_port),
                num_replicas=decode_replicas,
                port_map={"http": decode_port},
                resource=resource,
                metadata={ROLE_METADATA_KEY: spec},
            ),
        ],
    )
