"""Serving components (reference analog: torchx/components/serve.py:19-77)."""

from __future__ import annotations

import torchx_tpu.specs as specs
from torchx_tpu.version import TORCHX_TPU_IMAGE


def model_server(
    model_path: str,
    management_api: str,
    model_name: str = "model",
    image: str = TORCHX_TPU_IMAGE,
    timeout: float = 60.0,
) -> specs.AppDef:
    """Register a model archive with a running model server's management
    API (a one-shot registration client, not the server itself).

    Args:
        model_path: url/path of the model artifact to register
        management_api: base URL of the server management API
        model_name: name to register the model under
        image: image to use
        timeout: registration request timeout seconds
    """
    return specs.AppDef(
        name="model-server-register",
        roles=[
            specs.Role(
                name="register",
                image=image,
                entrypoint="python",
                args=[
                    "-m",
                    "torchx_tpu.apps.serve_main",
                    "--model_path",
                    model_path,
                    "--management_api",
                    management_api,
                    "--model_name",
                    model_name,
                    "--timeout",
                    str(timeout),
                ],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )
