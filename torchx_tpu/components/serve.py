"""Serving components (reference analog: torchx/components/serve.py:19-77;
``generate_server`` goes beyond the reference — an actual TPU inference
server, not just a registration client)."""

from __future__ import annotations

from typing import Optional

import torchx_tpu.specs as specs
from torchx_tpu.version import TORCHX_TPU_IMAGE


def model_server(
    model_path: str,
    management_api: str,
    model_name: str = "model",
    image: str = TORCHX_TPU_IMAGE,
    timeout: float = 60.0,
) -> specs.AppDef:
    """Register a model archive with a running model server's management
    API (a one-shot registration client, not the server itself).

    Args:
        model_path: url/path of the model artifact to register
        management_api: base URL of the server management API
        model_name: name to register the model under
        image: image to use
        timeout: registration request timeout seconds
    """
    return specs.AppDef(
        name="model-server-register",
        roles=[
            specs.Role(
                name="register",
                image=image,
                entrypoint="python",
                args=[
                    "-m",
                    "torchx_tpu.apps.serve_main",
                    "--model_path",
                    model_path,
                    "--management_api",
                    management_api,
                    "--model_name",
                    model_name,
                    "--timeout",
                    str(timeout),
                ],
                resource=specs.Resource(cpu=1, memMB=1024),
            )
        ],
    )


def generate_server(
    config: str,
    port: int = 8000,
    ckpt_dir: Optional[str] = None,
    int8: bool = False,
    image: str = TORCHX_TPU_IMAGE,
    tpu: Optional[str] = None,
    cpu: int = 4,
    memMB: int = 16384,
    batch_window_ms: float = 3.0,
    max_batch: int = 16,
) -> specs.AppDef:
    """Serve KV-cache generation for a model family over HTTP
    (POST /v1/generate, GET /healthz) — the TPU-native serving half the
    reference delegates to TorchServe. Concurrent requests coalesce into
    shared device batches (JetStream-style batcher thread).

    Args:
        config: model config name (e.g. ``llama3_1b``)
        port: HTTP port to listen on
        ckpt_dir: orbax checkpoint directory to restore weights from
        int8: serve int8 weight-only quantized (2x MXU, half weight HBM)
        image: container image
        tpu: TPU accelerator type (e.g. ``v5litepod-8``); CPU when unset
        cpu: cpu count for CPU serving
        memMB: memory for CPU serving
        batch_window_ms: how long the batcher waits to coalesce requests
        max_batch: max sequences per coalesced device batch
    """
    args = [
        "-m",
        "torchx_tpu.apps.generate_server",
        "--config",
        config,
        "--port",
        str(port),
        "--batch-window-ms",
        str(batch_window_ms),
        "--max-batch",
        str(max_batch),
    ]
    if ckpt_dir:
        args += ["--ckpt-dir", ckpt_dir]
    if int8:
        args += ["--int8"]
    resource = specs.resource(cpu=cpu, memMB=memMB, tpu=tpu)
    return specs.AppDef(
        name=f"generate-{config}",
        roles=[
            specs.Role(
                name="server",
                image=image,
                entrypoint="python",
                args=args,
                port_map={"http": port},
                resource=resource,
            )
        ],
    )
