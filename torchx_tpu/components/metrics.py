"""Metrics/observability components.

Reference analog: torchx/components/metrics.py:31-86 (tensorboard wrapped
in process_monitor).
"""

from __future__ import annotations

from typing import Optional

import torchx_tpu.specs as specs
from torchx_tpu.version import TORCHX_TPU_IMAGE


def tensorboard(
    logdir: str,
    image: str = TORCHX_TPU_IMAGE,
    timeout: float = 86400.0,
    port: int = 6006,
    start_on_file: Optional[str] = None,
    exit_on_file: Optional[str] = None,
) -> specs.AppDef:
    """Run a TensorBoard server next to a training job, supervised by
    process_monitor so it starts when training produces logs and exits when
    training finishes.

    Args:
        logdir: log directory (local or fsspec URL) to serve
        image: image to use
        timeout: maximum seconds to keep the server up
        port: port to serve on
        start_on_file: wait for this marker file before starting
        exit_on_file: exit when this marker file appears
    """
    monitor_args = ["-m", "torchx_tpu.apps.process_monitor", "--timeout", str(timeout)]
    if start_on_file:
        monitor_args += ["--start_on_file", start_on_file]
    if exit_on_file:
        monitor_args += ["--exit_on_file", exit_on_file]
    monitor_args += [
        "--",
        "tensorboard",
        "--bind_all",
        "--port",
        str(port),
        "--logdir",
        logdir,
    ]
    return specs.AppDef(
        name="tensorboard",
        roles=[
            specs.Role(
                name="tensorboard",
                image=image,
                entrypoint="python",
                args=monitor_args,
                port_map={"http": port},
                resource=specs.Resource(cpu=2, memMB=4096),
            )
        ],
    )
