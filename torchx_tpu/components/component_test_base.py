"""Base class for component unit tests.

Reference analog: torchx/components/component_test_base.py:33-121 —
``validate`` runs the AST linter + a ``--help`` argparse round-trip on a
component fn; ``run_component`` materializes and runs it on a scheduler.
Third-party component authors subclass this to test their components the
same way the builtins are tested.
"""

from __future__ import annotations

import unittest
from types import ModuleType
from typing import Callable, Optional

from torchx_tpu.specs.api import AppDef
from torchx_tpu.specs.builders import build_parser, materialize_appdef
from torchx_tpu.specs.file_linter import validate


class ComponentTestCase(unittest.TestCase):
    def validate(self, module: ModuleType, function_name: str) -> None:
        """Assert the component fn passes the AST linter and its argparse
        parser builds (the --help contract)."""
        path = module.__file__
        assert path is not None
        errors = validate(path, function_name)
        self.assertEqual(
            [], [f"{e.line}: {e.description}" for e in errors], f"{function_name}"
        )
        fn = getattr(module, function_name)
        parser, _ = build_parser(fn)
        self.assertTrue(parser.format_help())

    def run_component(
        self,
        component: Callable[..., AppDef],
        args: Optional[list[str]] = None,
        scheduler: str = "local",
        cfg: Optional[dict] = None,
    ) -> str:
        """Materialize + submit the component; returns the app handle."""
        from torchx_tpu.runner.api import get_runner

        app = materialize_appdef(component, args or [])
        with get_runner("component-test") as runner:
            return runner.run(app, scheduler, cfg or {})
