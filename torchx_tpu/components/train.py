"""Training component pointers (reference analog: torchx/components/train.py).

There is deliberately no generic ``train`` component: training apps are too
varied for one template. Use :py:func:`torchx_tpu.components.dist.spmd` to
launch any JAX SPMD trainer (see ``torchx_tpu/examples/train_llama.py`` for
the flagship example), or write a custom component
(``tpx run ./my_component.py:my_trainer``).
"""
