"""Interpretability component pointers (reference analog:
torchx/components/interpret.py — a docs-only stub pointing at examples).

There is no generic ``interpret`` component: model-analysis apps are
ordinary python apps. Launch them with :func:`torchx_tpu.components.utils.python`
or :func:`torchx_tpu.components.dist.spmd` (sharded analysis over a mesh),
e.g.::

    tpx run -s local utils.python -m my_project.analyze_attention -- --ckpt ...
"""
