"""Gang-aware priority queue + the fleet's durable decision journal.

Ordering is three-keyed: priority class first (``serve`` beats
``preemptible``), then *fair share within the class* — the tenant with
the fewest chips currently placed goes first, so one chatty tenant
cannot starve its classmates — then submission order. Admission is
gang-aware by construction: a gang sits in this queue until the placer
can fit **all** of its slices; there is no partial-placement state.

The journal is the ``JobStateStore`` idiom reduced to one file: one JSON
line per decision (submit / place / reshape / requeue / terminal /
infeasible), appended on an append-mode fd and fsync'd before the
scheduler acts on it, so a daemon restart replays the exact queue and
placement state (torn trailing lines from a crash are skipped, never
fatal).
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Any, Iterator, Mapping, Optional

from torchx_tpu.fleet.model import GangRequest
from torchx_tpu.util.times import epoch_usec

logger = logging.getLogger(__name__)

JOURNAL_FILE = "journal.jsonl"


@dataclass
class QueuedGang:
    """One queue entry: the demand plus its arrival bookkeeping.

    ``seq`` is the FIFO tiebreaker and survives a checkpoint-preempt
    requeue (a preempted gang goes back *ahead* of everything submitted
    after it in its class)."""

    req: GangRequest
    seq: int
    enqueued_at: float


class FleetQueue:
    """The pending-gang set with class/fair-share/FIFO ordering."""

    def __init__(self) -> None:
        self._items: dict[str, QueuedGang] = {}  # job id -> entry
        self._seq = 0

    def next_seq(self) -> int:
        """Allocate the next FIFO sequence number."""
        self._seq += 1
        return self._seq

    def bump_seq(self, floor: int) -> None:
        """Raise the sequence counter to at least ``floor`` (rehydration:
        replayed entries keep their original order; new submits go after)."""
        self._seq = max(self._seq, floor)

    def push(
        self, req: GangRequest, now: float, seq: Optional[int] = None
    ) -> QueuedGang:
        """Enqueue a gang (or re-enqueue a preempted one with its old
        ``seq``); returns the entry."""
        entry = QueuedGang(
            req=req,
            seq=self.next_seq() if seq is None else seq,
            enqueued_at=now,
        )
        self._items[req.job] = entry
        return entry

    def remove(self, job: str) -> Optional[QueuedGang]:
        """Drop a gang from the queue (placed / cancelled / infeasible)."""
        return self._items.pop(job, None)

    def get(self, job: str) -> Optional[QueuedGang]:
        """The queue entry for one job, or None."""
        return self._items.get(job)

    def __len__(self) -> int:
        return len(self._items)

    def ordered(
        self, placed_chips: Optional[Mapping[str, int]] = None
    ) -> list[QueuedGang]:
        """Scheduling order: (class rank, tenant's placed chips, seq).

        ``placed_chips`` maps tenant -> chips currently running; the
        tenant with the least gets served first within a class (classic
        fair share). Missing tenants count as zero."""
        placed = placed_chips or {}

        def key(entry: QueuedGang) -> tuple:
            return (
                entry.req.priority,
                int(placed.get(entry.req.tenant, 0)),
                entry.seq,
            )

        return sorted(self._items.values(), key=key)

    def position(
        self, job: str, placed_chips: Optional[Mapping[str, int]] = None
    ) -> Optional[int]:
        """1-based queue position under the current ordering, or None."""
        for i, entry in enumerate(self.ordered(placed_chips)):
            if entry.req.job == job:
                return i + 1
        return None


def over_quota(
    req: GangRequest,
    placed_chips: Mapping[str, int],
    quotas: Mapping[str, int],
) -> bool:
    """Would placing this gang push its tenant past its chip quota?

    Quotas are expressed in chips; a tenant with no quota entry is
    unlimited. Admission (enqueue) is never quota-gated — only placement
    is, so a gang waits out its tenant's burst instead of bouncing."""
    quota = quotas.get(req.tenant)
    if quota is None:
        return False
    return int(placed_chips.get(req.tenant, 0)) + req.chips > int(quota)


class FleetJournal:
    """Fsync'd JSONL decision log (see module docstring).

    Like the supervisor's attempt ledger, constructing it creates
    nothing; the first :meth:`append` creates the directory. Unlike the
    ledger, appends here are NOT best-effort — a scheduler that cannot
    journal must not act, so ``append`` raises on I/O failure."""

    def __init__(self, path: str) -> None:
        self.path = path

    def append(self, kind: str, **fields: Any) -> None:
        """Durably record one decision before it takes effect."""
        entry = {"kind": kind, "time_usec": epoch_usec(), **fields}
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # one complete line per write on an append-mode fd (atomic on
        # POSIX), fsynced: the decision is on disk before the scheduler
        # submits/cancels anything it could not reconstruct
        with open(self.path, "a") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def entries(self) -> Iterator[dict]:
        """Replay every journaled decision; a torn trailing line (crash
        mid-append) is skipped, not fatal."""
        try:
            f = open(self.path)
        except OSError:
            return
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    logger.warning(
                        "fleet journal %s: skipping torn line", self.path
                    )
                    continue
                if isinstance(doc, dict):
                    yield doc
