"""The fleet scheduler: queue -> placer -> market, driven by watch events.

:class:`FleetScheduler` sits between the control daemon's submit path and
the reconciler. Submits become :class:`~torchx_tpu.fleet.model
.GangRequest` demands; the daemon's old 429 becomes a queue position.
Every decision — enqueue, place, shrink, grow, requeue, refusal — is
fsync-journaled *before* it is executed, so a daemon restart rehydrates
the exact queue and placement state.

The scheduler is event-driven: it subscribes to the reconciler's watch
stream, and any terminal transition of a fleet-placed job releases its
slices and re-runs the placement loop (grow-backs + queued gangs). The
elastic shrink/grow path is the PR 7 mesh-reshape machinery driven from
the *scheduler* side: the victim is cancelled and resubmitted with a
refit ``$TPX_MESH`` (``shrink_data_axes`` arithmetic), each attempt
recorded in a per-job :class:`~torchx_tpu.supervisor.ledger
.AttemptLedger` exactly like a supervised resubmission, and the recorded
debt is repaid — the gang grows back to its launch mesh — as soon as
capacity frees.

Execution is behind the small :class:`FleetExecutor` seam so the daemon
(real runner), tests, and the bench's virtual-time simulator share one
scheduler implementation.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional

from torchx_tpu.fleet.market import Preempt, Shrink, Victim, plan_market
from torchx_tpu.fleet.model import (
    PRIORITY_CLASSES,
    FleetModel,
    GangRequest,
)
from torchx_tpu.fleet.placer import plan_placement
from torchx_tpu.fleet.queue import FleetJournal, FleetQueue, over_quota
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.specs.api import Role, parse_app_handle
from torchx_tpu.supervisor.ledger import AttemptLedger

logger = logging.getLogger(__name__)

#: FleetJob lifecycle states.
QUEUED, RUNNING, DONE, INFEASIBLE = "queued", "running", "done", "infeasible"


@dataclass
class FleetJob:
    """One gang's full scheduler-side record.

    ``recipe`` is the resubmission material (serialized AppDef +
    scheduler + cfg) — journaled with the submit so a restarted daemon
    can still place a queued gang, and re-materialized on every
    shrink/grow resubmit. ``debt`` is the launch replica count owed to a
    shrunk gang (0 = whole)."""

    req: GangRequest
    recipe: dict
    seq: int
    enqueued_at: float
    state: str = QUEUED
    handle: str = ""
    units: list[str] = field(default_factory=list)
    cur_replicas: int = 0
    debt: int = 0
    reason: str = ""
    _role_cache: Optional[Role] = field(default=None, repr=False)

    @property
    def shrunk(self) -> bool:
        """Running below launch size with a grow-back owed."""
        return self.state == RUNNING and self.debt > 0

    def role(self) -> Optional[Role]:
        """The gang's first role, materialized from the recipe (None for
        synthetic demand with no AppDef — the oracle then skips it)."""
        if self._role_cache is None and self.recipe.get("appdef"):
            from torchx_tpu.specs.serialize import appdef_from_dict

            app = appdef_from_dict(self.recipe["appdef"])
            if app.roles:
                self._role_cache = app.roles[0]
        return self._role_cache


class FleetExecutor:
    """What the scheduler needs from the world to act on a decision.

    The daemon implements this over its Runner (materialize + submit +
    reconciler tracking); tests and the bench substitute fakes. Both
    methods are called with the scheduler's lock held — implementations
    must not call back into the scheduler."""

    def schedule(self, job: FleetJob, mesh_spec: Optional[str]) -> str:
        """Materialize ``job.recipe`` at ``job.cur_replicas`` replicas
        (injecting ``$TPX_MESH`` when ``mesh_spec`` is set) and submit;
        returns the app handle."""
        raise NotImplementedError

    def cancel(self, handle: str) -> None:
        """Best-effort cancel of a previously returned handle."""
        raise NotImplementedError


def parse_quotas(specs: Optional[list[str]]) -> dict[str, int]:
    """CLI quota flags (``tenant=chips`` strings) -> quota map."""
    quotas: dict[str, int] = {}
    for item in specs or []:
        tenant, _, chips = str(item).partition("=")
        if not tenant or not chips:
            raise ValueError(f"bad quota {item!r}; expected tenant=chips")
        quotas[tenant.strip()] = int(chips)
    return quotas


class FleetScheduler:
    """Priority classes + quotas + topology-aware placement + the market.

    Args:
        model: the modeled fleet to place onto.
        state_dir: journal + attempt-ledger root (the daemon passes its
            own state dir; everything lands under ``<state_dir>/fleet``).
        quotas: per-tenant chip quotas (absent tenant = unlimited).
        clock: injectable monotonic clock (tests/bench drive virtual time).
    """

    def __init__(
        self,
        model: FleetModel,
        state_dir: str,
        quotas: Optional[dict[str, int]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.model = model
        self.quotas = dict(quotas or {})
        self.clock = clock
        root = os.path.join(state_dir, "fleet")
        self.journal = FleetJournal(os.path.join(root, "journal.jsonl"))
        self._ledger_root = os.path.join(root, "attempts")
        self.queue = FleetQueue()
        self._jobs: dict[str, FleetJob] = {}
        self._by_handle: dict[tuple[str, str], str] = {}
        self._executor: Optional[FleetExecutor] = None
        self._lock = threading.RLock()
        self._counter = 0
        # jobs whose executor submit failed during the CURRENT loop; they
        # stay queued but are not retried until the next loop trigger
        self._loop_failed: set[str] = set()
        self.reshapes = 0  # shrinks executed (kills avoided)
        self.grows = 0
        self.kills = 0  # checkpoint-preempts (non-elastic victims)
        # telemetry-plane burn-rate probe (set_slo_signal); None = no
        # telemetry, market behaves as before
        self._slo_signal: Optional[Callable[[], Optional[float]]] = None

    # -- wiring ------------------------------------------------------------

    def bind(self, executor: FleetExecutor) -> None:
        """Attach the execution seam (must happen before submits)."""
        self._executor = executor

    def ledger(self, job: str) -> AttemptLedger:
        """The per-job attempt ledger (``submitted`` entries carry the
        ``$TPX_MESH`` of every reshape, PR 7 style)."""
        return AttemptLedger(job, root=self._ledger_root)

    def set_slo_signal(
        self, fn: Callable[[], Optional[float]]
    ) -> None:
        """Attach the telemetry plane's burn-rate probe (the daemon wires
        its :class:`~torchx_tpu.obs.slo.SloEngine` here). While the worst
        long-window burn stays below 1.0 — error budget not actually
        burning — the market executes elastic shrinks only and defers
        checkpoint-preempt kills; at or past 1.0 the full market runs."""
        self._slo_signal = fn

    @contextlib.contextmanager
    def _job_span(self, job: FleetJob, name: str, **attrs: Any):
        """Emit one lifecycle span inside the gang's own journaled trace,
        tagged ``fleet_job`` so ``tpx trace --stitch <job>`` resolves it
        by name."""
        tid = str(job.recipe.get("trace_id") or "") or None
        with obs_trace.trace_context(tid):
            with obs_trace.span(name, fleet_job=job.req.job, **attrs) as sp:
                yield sp

    # -- submit ------------------------------------------------------------

    def submit(self, req: GangRequest, recipe: Optional[dict] = None) -> dict:
        """Admit one gang: journal, enqueue, and run the placement loop.

        Returns ``{"job", "status", ...}`` where status is ``placed``
        (with ``handle``), ``queued`` (with ``position``), or
        ``infeasible`` (with ``reason``) — the daemon maps these onto
        its HTTP replies. A request with an empty ``job`` gets a fleet id
        assigned."""
        with self._lock:
            if not req.job:
                self._counter += 1
                req = replace(req, job=f"fj-{self._counter:04d}")
            now = self.clock()
            seq = self.queue.next_seq()
            job = FleetJob(
                req=req, recipe=dict(recipe or {}), seq=seq, enqueued_at=now
            )
            # One trace per gang lifecycle. Stamping the id into the
            # journaled recipe makes it survive daemon restarts AND lets
            # the executor export $TPX_TRACE_ID into the gang's env, so
            # replica spans land in the same stitched timeline.
            job.recipe.setdefault("trace_id", obs_trace.new_trace_id())
            self._jobs[req.job] = job
            with self._job_span(
                job, "fleet.submit", klass=req.klass, replicas=req.replicas
            ):
                self.journal.append(
                    "submit",
                    job=req.job,
                    seq=seq,
                    tenant=req.tenant,
                    klass=req.klass,
                    replicas=req.replicas,
                    chips_per_replica=req.chips_per_replica,
                    elastic=req.elastic,
                    mesh=req.mesh,
                    min_replicas=req.min_replicas,
                    recipe=job.recipe,
                )
                self.queue.push(req, now, seq=seq)
            self._schedule_loop()
            return self._submit_reply(job)

    def _submit_reply(self, job: FleetJob) -> dict:
        reply: dict[str, Any] = {"job": job.req.job, "class": job.req.klass}
        if job.state == RUNNING:
            reply.update(status="placed", handle=job.handle)
        elif job.state == INFEASIBLE:
            reply.update(status="infeasible", reason=job.reason)
        else:
            reply.update(
                status="queued",
                position=self.queue.position(
                    job.req.job, self._placed_chips()
                ),
            )
        return reply

    def cancel_job(self, job_id: str) -> bool:
        """Cancel by fleet job id: dequeue a queued gang or cancel a
        running one's current attempt (its terminal event then frees the
        slices). Returns False for unknown ids."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return False
            if job.state == QUEUED:
                self.queue.remove(job_id)
                job.state = DONE
                job.reason = "cancelled"
                self.journal.append("terminal", job=job_id, state="CANCELLED")
                self._update_gauges()
                return True
            if job.state == RUNNING and self._executor is not None:
                self._executor.cancel(job.handle)
                return True
            return False

    # -- the event side ----------------------------------------------------

    def on_event(self, event: Any) -> None:
        """Reconciler subscription: a terminal transition of the current
        attempt of a fleet job frees its slices and re-runs the loop.
        Stale handles (attempts the market already replaced) are ignored
        — the reshape path cancels on purpose."""
        terminal = bool(
            getattr(event, "terminal", False)
            or getattr(event.state, "name", "") == "UNKNOWN"
        )
        if not terminal:
            return
        key = (event.scheduler, event.app_id)
        with self._lock:
            job_id = self._by_handle.pop(key, None)
            if job_id is None:
                return
            job = self._jobs.get(job_id)
            if job is None or job.state != RUNNING:
                return
            job.state = DONE
            job.reason = getattr(event.state, "name", str(event.state))
            self.model.release_job(job_id)
            job.units = []
            with self._job_span(job, "fleet.terminal", state=job.reason):
                self.journal.append("terminal", job=job_id, state=job.reason)
            self._schedule_loop()

    def running_handles(self) -> list[str]:
        """Current attempt handles of every running fleet job (the daemon
        re-tracks these with the reconciler after a restart)."""
        with self._lock:
            return [
                j.handle
                for j in self._jobs.values()
                if j.state == RUNNING and j.handle
            ]

    # -- the placement loop ------------------------------------------------

    def _placed_chips(self) -> dict[str, int]:
        """Chips currently held per tenant (quota + fair-share input)."""
        placed: dict[str, int] = {}
        for job in self._jobs.values():
            if job.state == RUNNING:
                placed[job.req.tenant] = placed.get(job.req.tenant, 0) + (
                    job.cur_replicas * job.req.chips_per_replica
                )
        return placed

    def _schedule_loop(self) -> None:
        """Drain the queue in priority order, running the market for
        blocked gangs, then repay shrink debts — repeat until a full pass
        makes no progress. Called with the lock held."""
        self._loop_failed = set()
        with obs_trace.span("fleet.schedule", queued=len(self.queue)):
            progress = True
            while progress:
                progress = self._pass_queue() or self._pass_growback()
        self._update_gauges()

    def _pass_queue(self) -> bool:
        placed_chips = self._placed_chips()
        # shared per-pass market context: nothing mutates between FAILED
        # market attempts inside one pass, so the running-victim snapshot
        # is computed once and fruitless (priority, replicas, chips) keys
        # are never re-planned — the difference between O(queue * fleet)
        # and O(distinct shapes * fleet) per pass at 1000-slice sim scale
        ctx: dict[str, Any] = {
            "memo": set(),
            "victims": None,
            "free": {},
            "blocked": set(),
        }
        for entry in self.queue.ordered(placed_chips):
            job = self._jobs[entry.req.job]
            if job.req.job in self._loop_failed:
                continue
            if over_quota(job.req, placed_chips, self.quotas):
                continue
            role = job.role()
            # role-less demand makes plan_placement a pure function of
            # (shape, free units), and free units don't change between
            # blocked outcomes within a pass — skip re-planning a shape
            # that already came back blocked (never memoize placed or
            # infeasible: those return out of the pass immediately)
            pkey = (
                (entry.req.replicas, entry.req.chips_per_replica, entry.req.mesh)
                if role is None
                else None
            )
            if pkey is not None and pkey in ctx["blocked"]:
                if self._run_market(job, ctx):
                    return True
                continue
            decision = plan_placement(job.req, self.model, role=role)
            if decision.infeasible:
                self.queue.remove(job.req.job)
                job.state = INFEASIBLE
                job.reason = decision.infeasible
                self.journal.append(
                    "infeasible", job=job.req.job, reason=job.reason
                )
                logger.warning(
                    "fleet: gang %s infeasible: %s",
                    job.req.job,
                    job.reason,
                )
                return True
            if decision.placed:
                self._place(job, decision.units)
                return True
            if pkey is not None:
                ctx["blocked"].add(pkey)
            if self._run_market(job, ctx):
                return True
        return False

    def _run_market(
        self, job: FleetJob, ctx: Optional[dict] = None
    ) -> bool:
        """Try to free capacity for one blocked gang via the market."""
        need = job.req.chips_per_replica
        if ctx is None:
            ctx = {"memo": set(), "victims": None, "free": {}}
        key = (job.req.priority, job.req.replicas, need)
        if key in ctx["memo"]:
            return False
        if ctx["victims"] is None:
            snapshot = []
            for other in self._jobs.values():
                if other.state != RUNNING:
                    continue
                units = self.model.units_of(other.req.job)
                snapshot.append(
                    (
                        other,
                        bool(units),
                        min((u.chips for u in units), default=0),
                    )
                )
            ctx["victims"] = snapshot
        victims = [
            Victim(
                job=other.req.job,
                priority=other.req.priority,
                elastic=other.req.elastic and other.req.mesh != "",
                replicas=other.cur_replicas,
                min_replicas=other.req.min_replicas,
                seq=other.seq,
                suitable=has_units and min_chips >= need,
            )
            for other, has_units, min_chips in ctx["victims"]
            if other.req.job != job.req.job
        ]
        if need not in ctx["free"]:
            ctx["free"][need] = sum(
                1 for u in self.model.free_units() if u.chips >= need
            )
        free_suitable = ctx["free"][need]
        actions = plan_market(
            job.req.replicas - free_suitable, job.req.priority, victims
        )
        if actions and self._gentle_market():
            # SLO budgets are healthy: defer the expensive checkpoint
            # kills and take only the elastic shrinks this pass.
            actions = [a for a in actions if isinstance(a, Shrink)]
        if not actions:
            ctx["memo"].add(key)
            return False
        with obs_trace.span(
            "fleet.preempt",
            demand=job.req.job,
            actions=len(actions),
        ):
            for action in actions:
                victim = self._jobs[action.job]
                if isinstance(action, Shrink):
                    self._reshape(
                        victim,
                        action.to_replicas,
                        kind="shrink",
                        beneficiary=job.req.job,
                    )
                elif isinstance(action, Preempt):
                    self._checkpoint_preempt(victim, beneficiary=job.req.job)
        decision = plan_placement(job.req, self.model, role=job.role())
        if decision.placed:
            self._place(job, decision.units)
        return True

    def _gentle_market(self) -> bool:
        """True when the telemetry plane reports every SLO burning below
        1.0 — budgets intact, so preemption kills can wait. No signal
        (or a failing probe) means no gating: full market."""
        if self._slo_signal is None:
            return False
        try:
            burn = self._slo_signal()
        except Exception:  # noqa: BLE001 - telemetry must not wedge placement
            logger.debug("fleet: slo signal probe failed", exc_info=True)
            return False
        return burn is not None and burn < 1.0

    def _pass_growback(self) -> bool:
        """Repay shrink debts, highest class / oldest first, when free
        capacity covers the missing replicas (and quota allows)."""
        placed_chips = self._placed_chips()
        shrunk = sorted(
            (j for j in self._jobs.values() if j.shrunk),
            key=lambda j: (j.req.priority, j.seq),
        )
        for job in shrunk:
            if job.req.job in self._loop_failed:
                continue
            missing = job.req.replicas - job.cur_replicas
            need = job.req.chips_per_replica
            grow_req = replace(job.req, replicas=missing)
            if over_quota(grow_req, placed_chips, self.quotas):
                continue
            extra = [
                u for u in self.model.free_units() if u.chips >= need
            ][:missing]
            if len(extra) < missing:
                continue
            self._grow(job, extra)
            return True
        return False

    # -- decision execution ------------------------------------------------

    def _place(self, job: FleetJob, units: list) -> None:
        """Journal + execute one placement (initial submit, mesh=None:
        the app launches on its own default mesh)."""
        uids = [u.uid for u in units]
        job.cur_replicas = job.req.replicas
        job.debt = 0
        with self._job_span(
            job, "fleet.place", replicas=job.cur_replicas, units=len(uids)
        ):
            self.journal.append(
                "place",
                job=job.req.job,
                units=uids,
                replicas=job.cur_replicas,
            )
            self.queue.remove(job.req.job)
            self.model.assign(uids, job.req.job)
            job.units = uids
            if not self._try_schedule(job, mesh_spec=None):
                return
            job.state = RUNNING
        waited = max(0.0, self.clock() - job.enqueued_at)
        obs_metrics.FLEET_GANG_WAIT_SECONDS.observe(
            waited, klass=job.req.klass
        )
        obs_metrics.FLEET_PLACEMENTS.inc(klass=job.req.klass)

    def _reshape(
        self, job: FleetJob, to_replicas: int, kind: str, beneficiary: str
    ) -> None:
        """Shrink (or regrow) a running elastic gang via cancel +
        ``$TPX_MESH`` resubmit through its attempt ledger."""
        spec = self._mesh_spec_for(job, to_replicas)
        keep = job.units[:to_replicas]
        freed = job.units[to_replicas:]
        with self._job_span(
            job,
            "fleet.reshape",
            direction=kind,
            replicas=to_replicas,
            beneficiary=beneficiary,
        ):
            self.journal.append(
                "reshape",
                job=job.req.job,
                direction=kind,
                replicas=to_replicas,
                mesh=spec,
                units=keep,
                beneficiary=beneficiary,
            )
            old_handle = job.handle
            self._unmap_handle(old_handle)
            if self._executor is not None and old_handle:
                self._executor.cancel(old_handle)
            self.model.release(freed)
            job.units = keep
            job.cur_replicas = to_replicas
            job.debt = (
                job.req.replicas if to_replicas < job.req.replicas else 0
            )
            self._try_schedule(job, mesh_spec=spec)
        if kind == "shrink":
            self.reshapes += 1
            obs_metrics.FLEET_PREEMPTIONS.inc(kind="shrink")
            logger.info(
                "fleet: shrank %s to %d replica(s) (mesh %s) for %s",
                job.req.job,
                to_replicas,
                spec,
                beneficiary,
            )

    def _grow(self, job: FleetJob, extra_units: list) -> None:
        """Repay a shrink debt: reclaim slices and resubmit at the launch
        mesh (the gang resumes from its last verified checkpoint)."""
        uids = [u.uid for u in extra_units]
        self.model.assign(uids, job.req.job)
        job.units = job.units + uids
        self._reshape(
            job, job.req.replicas, kind="grow", beneficiary=job.req.job
        )
        self.grows += 1
        obs_metrics.FLEET_GROWBACKS.inc()
        logger.info(
            "fleet: grew %s back to %d replicas", job.req.job, job.req.replicas
        )

    def _checkpoint_preempt(self, job: FleetJob, beneficiary: str) -> None:
        """Non-elastic victim: cancel and requeue at its original class
        position (priority-ordered requeue)."""
        with self._job_span(job, "fleet.requeue", beneficiary=beneficiary):
            self.journal.append(
                "requeue", job=job.req.job, beneficiary=beneficiary
            )
            old_handle = job.handle
            self._unmap_handle(old_handle)
            if self._executor is not None and old_handle:
                self._executor.cancel(old_handle)
            self.model.release_job(job.req.job)
            job.units = []
            job.handle = ""
            job.cur_replicas = 0
            job.debt = 0
            job.state = QUEUED
            job.enqueued_at = self.clock()
            self.queue.push(job.req, job.enqueued_at, seq=job.seq)
        self.kills += 1
        obs_metrics.FLEET_PREEMPTIONS.inc(kind="requeue")
        logger.info(
            "fleet: checkpoint-preempted %s for %s", job.req.job, beneficiary
        )

    def _try_schedule(self, job: FleetJob, mesh_spec: Optional[str]) -> bool:
        """Run the executor for one attempt; on failure the gang goes
        back to the queue instead of leaking slices."""
        if self._executor is None:
            raise RuntimeError("FleetScheduler has no executor bound")
        try:
            handle = self._executor.schedule(job, mesh_spec)
        except Exception as e:  # noqa: BLE001 - requeue, don't wedge the loop
            logger.warning(
                "fleet: scheduling %s failed (%s); requeued", job.req.job, e
            )
            self._loop_failed.add(job.req.job)
            self.model.release_job(job.req.job)
            job.units = []
            job.handle = ""
            job.state = QUEUED
            self.queue.push(job.req, self.clock(), seq=job.seq)
            return False
        job.handle = handle
        job.state = RUNNING
        scheduler, _, app_id = parse_app_handle(handle)
        self._by_handle[(scheduler, app_id)] = job.req.job
        self.ledger(job.req.job).append(
            "submitted",
            app_id,
            handle=handle,
            mesh=mesh_spec,
            replicas=job.cur_replicas,
        )
        self.journal.append(
            "attempt", job=job.req.job, handle=handle, mesh=mesh_spec
        )
        return True

    def _unmap_handle(self, handle: str) -> None:
        if not handle:
            return
        try:
            scheduler, _, app_id = parse_app_handle(handle)
        except ValueError:
            return
        self._by_handle.pop((scheduler, app_id), None)

    def _mesh_spec_for(self, job: FleetJob, replicas: int) -> str:
        """Refit the launch mesh onto ``replicas`` slices: full explicit
        spec at launch size, ``shrink_data_axes`` below it (dp/fsdp give;
        model axes never change)."""
        from torchx_tpu.parallel.mesh_config import (
            MeshConfig,
            mesh_sizes_spec,
            parse_mesh_spec,
            shrink_data_axes,
        )

        cpr = job.req.chips_per_replica
        cfg = (
            parse_mesh_spec(job.req.mesh) if job.req.mesh else MeshConfig()
        )
        launch = cfg.resolve(job.req.replicas * cpr)
        if replicas >= job.req.replicas:
            return mesh_sizes_spec(launch)
        return mesh_sizes_spec(shrink_data_axes(launch, replicas * cpr))

    # -- introspection -----------------------------------------------------

    def job(self, job_id: str) -> Optional[FleetJob]:
        """One job's record by fleet id (None when unknown)."""
        with self._lock:
            return self._jobs.get(job_id)

    def queue_snapshot(self) -> dict:
        """The ``/v1/queue`` payload: ordered queue, running set, fleet
        inventory, and the market's running totals."""
        with self._lock:
            placed_chips = self._placed_chips()
            now = self.clock()
            queued = []
            for i, entry in enumerate(self.queue.ordered(placed_chips)):
                job = self._jobs[entry.req.job]
                queued.append(
                    {
                        "position": i + 1,
                        "job": entry.req.job,
                        "tenant": entry.req.tenant,
                        "class": entry.req.klass,
                        "replicas": entry.req.replicas,
                        "chips": entry.req.chips,
                        "waited_seconds": round(
                            max(0.0, now - entry.enqueued_at), 3
                        ),
                        "quota_blocked": over_quota(
                            entry.req, placed_chips, self.quotas
                        ),
                    }
                )
            running = []
            for job in self._jobs.values():
                if job.state != RUNNING:
                    continue
                running.append(
                    {
                        "job": job.req.job,
                        "tenant": job.req.tenant,
                        "class": job.req.klass,
                        "handle": job.handle,
                        "replicas": job.cur_replicas,
                        "launch_replicas": job.req.replicas,
                        "shrunk": job.shrunk,
                        "units": list(job.units),
                    }
                )
            return {
                "enabled": True,
                "queue": queued,
                "running": running,
                "fleet": self.model.snapshot(),
                "market": {
                    "reshapes": self.reshapes,
                    "growbacks": self.grows,
                    "kills": self.kills,
                },
            }

    def _update_gauges(self) -> None:
        depth: dict[str, int] = {k: 0 for k in PRIORITY_CLASSES}
        for entry in self.queue.ordered():
            depth[entry.req.klass] += 1
        for klass, n in depth.items():
            obs_metrics.FLEET_QUEUE_DEPTH.set(float(n), klass=klass)
        obs_metrics.FLEET_CHIPS.set(
            float(self.model.total_chips), state="total"
        )
        obs_metrics.FLEET_CHIPS.set(float(self.model.free_chips), state="free")
        for tenant, chips in self._placed_chips().items():
            obs_metrics.FLEET_TENANT_CHIPS.set(float(chips), tenant=tenant)

    # -- rehydration -------------------------------------------------------

    def rehydrate(self) -> int:
        """Replay the journal after a daemon restart: queued gangs go
        back in (original order), running placements re-own their slices
        and handles. Returns the number of live jobs restored."""
        with self._lock:
            by_job: dict[str, FleetJob] = {}
            max_seq = 0
            for e in self.journal.entries():
                kind, job_id = e.get("kind"), str(e.get("job", ""))
                if kind == "submit":
                    try:
                        req = GangRequest(
                            job=job_id,
                            tenant=str(e.get("tenant", "")),
                            klass=str(e.get("klass", "batch")),
                            replicas=int(e.get("replicas", 1)),
                            chips_per_replica=int(
                                e.get("chips_per_replica", 1)
                            ),
                            elastic=bool(e.get("elastic", False)),
                            mesh=str(e.get("mesh", "")),
                            min_replicas=int(e.get("min_replicas", 1)),
                        )
                    except ValueError:
                        continue
                    seq = int(e.get("seq", 0))
                    max_seq = max(max_seq, seq)
                    by_job[job_id] = FleetJob(
                        req=req,
                        recipe=dict(e.get("recipe") or {}),
                        seq=seq,
                        enqueued_at=self.clock(),
                    )
                    if job_id.startswith("fj-"):
                        try:
                            self._counter = max(
                                self._counter, int(job_id[3:])
                            )
                        except ValueError:
                            pass
                    continue
                job = by_job.get(job_id)
                if job is None:
                    continue
                if kind == "place":
                    job.state = RUNNING
                    job.units = list(e.get("units") or [])
                    job.cur_replicas = int(e.get("replicas", 1))
                    job.debt = 0
                elif kind == "reshape":
                    job.units = list(e.get("units") or [])
                    job.cur_replicas = int(e.get("replicas", 1))
                    job.debt = (
                        job.req.replicas
                        if job.cur_replicas < job.req.replicas
                        else 0
                    )
                elif kind == "attempt":
                    job.handle = str(e.get("handle", ""))
                elif kind == "requeue":
                    job.state = QUEUED
                    job.units = []
                    job.handle = ""
                    job.cur_replicas = 0
                    job.debt = 0
                elif kind in ("terminal", "infeasible"):
                    job.state = DONE
            restored = 0
            self.queue.bump_seq(max_seq)
            for job in by_job.values():
                if job.state == QUEUED:
                    self._jobs[job.req.job] = job
                    self.queue.push(job.req, job.enqueued_at, seq=job.seq)
                    restored += 1
                elif job.state == RUNNING and job.units:
                    try:
                        self.model.assign(job.units, job.req.job)
                    except (KeyError, ValueError):
                        logger.warning(
                            "fleet rehydrate: dropping %s (slices moved)",
                            job.req.job,
                        )
                        continue
                    self._jobs[job.req.job] = job
                    if job.handle:
                        try:
                            sched, _, app_id = parse_app_handle(job.handle)
                            self._by_handle[(sched, app_id)] = job.req.job
                        except ValueError:
                            pass
                    restored += 1
            if restored:
                logger.info(
                    "fleet: rehydrated %d job(s) from %s",
                    restored,
                    self.journal.path,
                )
            self._update_gauges()
            return restored
