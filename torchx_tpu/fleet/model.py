"""The fleet data model: slice pools, gang demands, priority classes.

The scheduler does not talk to cloud inventory APIs — it schedules onto a
*modeled* fleet: named pools of identical :class:`~torchx_tpu.specs.api
.TpuSlice` shapes (``FleetModel``), each slice being one all-or-nothing
unit of placement (the ICI mesh only exists within a slice, so a gang
replica either gets a whole slice or nothing). Per-generation chip and
HBM facts come straight from ``specs/api.py``; the placer turns the HBM
number into a deep-preflight placement oracle.

Demand is a :class:`GangRequest`: ``replicas`` slices of
``chips_per_replica`` chips for one tenant in one priority class. The
class ladder is fixed::

    serve > interactive > batch > preemptible

Everything here is jax-free, stdlib + specs only — the daemon imports it
on its fast path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

from torchx_tpu.specs.api import TpuSlice

#: The priority ladder, highest first. Lower index = scheduled earlier =
#: may take capacity from any class with a higher index (the market).
PRIORITY_CLASSES = ("serve", "interactive", "batch", "preemptible")

#: Classes the preemption market may take capacity from (anything below
#: the top class can be a victim of a strictly higher class).
DEFAULT_CLASS = "batch"


def priority_index(klass: str) -> int:
    """Class name -> rank (0 = highest). Unknown names raise."""
    try:
        return PRIORITY_CLASSES.index(klass)
    except ValueError:
        raise ValueError(
            f"unknown priority class {klass!r};"
            f" known: {', '.join(PRIORITY_CLASSES)}"
        ) from None


@dataclass(frozen=True)
class SliceUnit:
    """One placeable TPU slice inside a pool: the atom of the fleet.

    Attributes:
        uid: stable id, ``"<pool>/<index>"``.
        pool: owning pool name.
        index: position within the pool (contiguity preference sorts on it).
        shape: the pool's :class:`~torchx_tpu.specs.api.TpuSlice`.
    """

    uid: str
    pool: str
    index: int
    shape: TpuSlice

    @property
    def chips(self) -> int:
        """Chips in this slice (the unit of quota accounting)."""
        return self.shape.chips

    @property
    def hbm_bytes_per_chip(self) -> int:
        """Per-chip HBM of the slice's generation — the oracle's budget."""
        return self.shape.hbm_bytes_per_chip


@dataclass(frozen=True)
class SlicePool:
    """``count`` identical slices under one name (one ICI/DCN locality
    domain: replicas placed in one pool are considered DCN-adjacent,
    replicas within one slice share ICI)."""

    name: str
    shape: TpuSlice
    count: int

    def __post_init__(self) -> None:
        if self.count <= 0:
            raise ValueError(f"pool {self.name!r}: count must be positive")


@dataclass(frozen=True)
class GangRequest:
    """One gang's demand: what the queue orders and the placer fits.

    Attributes:
        job: the fleet-assigned job id (stable across shrink/grow).
        tenant: quota + fair-share accounting key.
        klass: priority class name (one of :data:`PRIORITY_CLASSES`).
        replicas: gang size in slices — all-or-nothing (gang admission).
        chips_per_replica: chips each replica needs from its slice.
        elastic: True when the gang tolerates a mesh-reshape shrink (the
            market shrinks it instead of killing it).
        mesh: launch mesh spec (``"fsdp=-1"`` style) the reshape arithmetic
            resolves and refits; empty = axis defaults.
        min_replicas: the floor a shrink may not cross (>= 1).
    """

    job: str
    tenant: str
    klass: str = DEFAULT_CLASS
    replicas: int = 1
    chips_per_replica: int = 1
    elastic: bool = False
    mesh: str = ""
    min_replicas: int = 1

    def __post_init__(self) -> None:
        priority_index(self.klass)  # validate
        if self.replicas <= 0 or self.chips_per_replica <= 0:
            raise ValueError(
                f"gang {self.job!r}: replicas and chips_per_replica must be"
                " positive"
            )
        if not 1 <= self.min_replicas <= self.replicas:
            raise ValueError(
                f"gang {self.job!r}: min_replicas must be in"
                f" [1, {self.replicas}]"
            )

    @property
    def priority(self) -> int:
        """Class rank (0 = highest)."""
        return priority_index(self.klass)

    @property
    def chips(self) -> int:
        """Total chip demand at launch size."""
        return self.replicas * self.chips_per_replica


class FleetModel:
    """The modeled fleet: pools of slices plus the assignment map.

    The model is pure bookkeeping — ``assign``/``release`` never talk to a
    backend. The scheduler layers admission, placement, and the market on
    top of it and keeps it consistent with what was actually submitted.
    """

    def __init__(self, pools: Iterable[SlicePool]) -> None:
        self.pools = list(pools)
        if not self.pools:
            raise ValueError("a fleet needs at least one pool")
        seen: set[str] = set()
        self._units: list[SliceUnit] = []
        for pool in self.pools:
            if pool.name in seen:
                raise ValueError(f"duplicate pool name {pool.name!r}")
            seen.add(pool.name)
            for i in range(pool.count):
                self._units.append(
                    SliceUnit(
                        uid=f"{pool.name}/{i}",
                        pool=pool.name,
                        index=i,
                        shape=pool.shape,
                    )
                )
        self._by_uid = {u.uid: u for u in self._units}
        self._owner: dict[str, str] = {}  # uid -> job id
        # reverse index + stable position, so units_of is O(holdings) —
        # the market calls it per candidate victim per pass, which at
        # 1000-slice sim scale made the O(fleet) scan the bottleneck
        self._held: dict[str, set[str]] = {}  # job id -> uids
        self._pos = {u.uid: i for i, u in enumerate(self._units)}

    # -- construction ------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: str) -> "FleetModel":
        """Parse ``"name:gen-CHIPSxCOUNT,..."`` — e.g.
        ``"default:v5e-4x8,big:v5p-8x2"`` is 8 four-chip v5e slices under
        ``default`` plus 2 eight-chip v5p slices under ``big``."""
        pools = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            name, _, rest = part.partition(":")
            if not rest:
                name, rest = "default", part
            gen, _, dims = rest.partition("-")
            chips_s, _, count_s = dims.partition("x")
            try:
                chips, count = int(chips_s), int(count_s or "1")
            except ValueError:
                raise ValueError(
                    f"bad fleet pool spec {part!r};"
                    " expected name:gen-CHIPSxCOUNT"
                ) from None
            pools.append(
                SlicePool(
                    name=name.strip(),
                    shape=TpuSlice(accelerator=gen.strip(), chips=chips),
                    count=count,
                )
            )
        return cls(pools)

    # -- inventory ---------------------------------------------------------

    def units(self) -> list[SliceUnit]:
        """Every slice in the fleet, pool order then index order."""
        return list(self._units)

    def unit(self, uid: str) -> SliceUnit:
        """Look one slice up by uid (KeyError on unknown)."""
        return self._by_uid[uid]

    def free_units(self) -> list[SliceUnit]:
        """Slices with no owner, in stable pool/index order."""
        return [u for u in self._units if u.uid not in self._owner]

    def owner_of(self, uid: str) -> Optional[str]:
        """Owning job id of a slice, or None when free."""
        return self._owner.get(uid)

    def units_of(self, job: str) -> list[SliceUnit]:
        """The slices a job currently holds, pool/index order."""
        held = self._held.get(job)
        if not held:
            return []
        return [
            self._by_uid[uid] for uid in sorted(held, key=self._pos.__getitem__)
        ]

    @property
    def total_chips(self) -> int:
        """Sum of chips over every slice in the model."""
        return sum(u.chips for u in self._units)

    @property
    def free_chips(self) -> int:
        """Sum of chips over currently unowned slices."""
        return sum(u.chips for u in self.free_units())

    # -- assignment --------------------------------------------------------

    def assign(self, uids: Iterable[str], job: str) -> None:
        """Mark slices owned by ``job``; assigning an owned slice raises
        (the scheduler must never double-book a slice)."""
        uids = list(uids)
        for uid in uids:
            if uid not in self._by_uid:
                raise KeyError(f"unknown slice {uid!r}")
            owner = self._owner.get(uid)
            if owner is not None and owner != job:
                raise ValueError(
                    f"slice {uid!r} already owned by {owner!r}"
                )
        for uid in uids:
            self._owner[uid] = job
            self._held.setdefault(job, set()).add(uid)

    def release(self, uids: Iterable[str]) -> None:
        """Free specific slices (no-op for already-free uids)."""
        for uid in uids:
            owner = self._owner.pop(uid, None)
            if owner is not None:
                held = self._held.get(owner)
                if held is not None:
                    held.discard(uid)
                    if not held:
                        del self._held[owner]

    def release_job(self, job: str) -> list[str]:
        """Free every slice a job holds; returns the freed uids."""
        freed = [u.uid for u in self.units_of(job)]
        self.release(freed)
        return freed

    def snapshot(self) -> dict:
        """JSON-shaped inventory view for ``/v1/queue`` / ``tpx queue``."""
        return {
            "pools": [
                {
                    "name": p.name,
                    "accelerator": p.shape.accelerator,
                    "chips_per_slice": p.shape.chips,
                    "slices": p.count,
                }
                for p in self.pools
            ],
            "chips_total": self.total_chips,
            "chips_free": self.free_chips,
        }
