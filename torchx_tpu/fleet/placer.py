"""Topology-aware gang placement over the modeled fleet.

Placement is all-or-nothing (gang admission): a decision either names one
free slice per replica or nothing. Preference order:

1. **ICI/DCN locality** — all replicas from ONE pool when any single pool
   can host the whole gang (replicas in a pool are DCN-adjacent; a pool
   models one locality domain), lowest slice indices first (contiguity).
2. **Exact chip fit** — a 4-chip replica lands on a 4-chip slice before a
   16-chip slice; fragmenting big slices is a last resort.

Before any pool is considered, the PR 10 cost model acts as the
**placement oracle**: for plan-shaped roles,
:func:`~torchx_tpu.analyze.explain.deep_preflight` re-runs the static
HBM fit against *that pool's generation* (``hbm_bytes_per_chip`` from
``specs/api.py``). A pool whose HBM verdict is an ERROR (TPX701 et al.)
is refused; a gang every pool refuses is **infeasible** — it is reported
and dropped instead of waiting forever for capacity that can never fit
it.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from torchx_tpu.fleet.model import FleetModel, GangRequest, SliceUnit
from torchx_tpu.specs.api import Role

logger = logging.getLogger(__name__)


@dataclass
class PlacementDecision:
    """The placer's answer for one gang.

    Attributes:
        units: one free slice per replica when the gang fits NOW
            (empty = not placeable at current free capacity).
        infeasible: non-empty when no pool in the fleet can EVER host the
            gang (oracle refusal or shape mismatch) — the gang should be
            rejected, not queued.
        refusals: per-pool oracle refusal messages (diagnostic detail).
    """

    units: list[SliceUnit] = field(default_factory=list)
    infeasible: str = ""
    refusals: dict[str, str] = field(default_factory=dict)

    @property
    def placed(self) -> bool:
        """True when :attr:`units` covers the whole gang."""
        return bool(self.units)


def hbm_refusal(
    role: Role, gang: GangRequest, hbm_bytes: int, generation: str = ""
) -> Optional[str]:
    """The placement oracle for one (role, pool-generation) pair.

    Re-runs deep preflight with the pool's per-chip HBM as the budget and
    the gang's total chips as the device count. Any ERROR-severity
    verdict (TPX701 static HBM overflow, TPX703 unresolvable plan) is a
    refusal; roles that are not plan-shaped pass (nothing to verify —
    the TPX705 skip is info, not an error).

    ``generation`` (the pool's accelerator, e.g. ``v5e``) applies the
    persisted ``tpx tune`` calibration for that generation, so the same
    measured activation-memory corrections that sharpen the explain
    report also sharpen which pools the fleet refuses."""
    from torchx_tpu.analyze.diagnostics import Severity
    from torchx_tpu.analyze.explain import deep_preflight

    calibration = None
    if generation:
        from torchx_tpu.tune.calibrate import CalibrationTable

        calibration = CalibrationTable.load_default().scales_for(generation)
    _plan, diags = deep_preflight(
        role,
        devices=gang.replicas * gang.chips_per_replica,
        hbm_bytes=hbm_bytes,
        calibration=calibration,
    )
    errors = [d for d in diags if d.severity == Severity.ERROR]
    if not errors:
        return None
    worst = errors[0]
    return f"{worst.code}: {worst.message}"


def plan_placement(
    gang: GangRequest,
    model: FleetModel,
    role: Optional[Role] = None,
) -> PlacementDecision:
    """Fit one gang onto the fleet's free slices (see module docstring).

    ``role`` enables the HBM oracle; None (synthetic/bench demand, or
    jobs with no resolvable plan) skips it."""
    decision = PlacementDecision()
    # pools whose slice shape can host one replica at all
    capable = [
        p for p in model.pools if p.shape.chips >= gang.chips_per_replica
    ]
    if not capable:
        decision.infeasible = (
            f"no pool has {gang.chips_per_replica}-chip slices"
            f" (largest: {max(p.shape.chips for p in model.pools)})"
        )
        return decision
    # the oracle prunes pools whose generation cannot hold the plan
    allowed = []
    for pool in capable:
        if role is not None:
            refusal = hbm_refusal(
                role,
                gang,
                pool.shape.hbm_bytes_per_chip,
                generation=pool.shape.accelerator,
            )
            if refusal is not None:
                decision.refusals[pool.name] = refusal
                continue
        allowed.append(pool)
    if not allowed:
        worst = next(iter(decision.refusals.values()))
        decision.infeasible = (
            f"every capable pool refused by the placement oracle ({worst})"
        )
        return decision

    allowed_names = {p.name for p in allowed}
    free = [
        u
        for u in model.free_units()
        if u.pool in allowed_names and u.chips >= gang.chips_per_replica
    ]
    by_pool: dict[str, list[SliceUnit]] = {}
    for u in free:
        by_pool.setdefault(u.pool, []).append(u)

    # 1) a single pool that can host the whole gang: ICI/DCN-contiguous.
    #    Tightest fit first (least chip waste), then name for stability.
    whole = [
        (units[0].chips - gang.chips_per_replica, pool, units)
        for pool, units in by_pool.items()
        if len(units) >= gang.replicas
    ]
    if whole:
        _waste, _pool, units = min(whole, key=lambda t: (t[0], t[1]))
        decision.units = sorted(units, key=lambda u: u.index)[: gang.replicas]
        return decision

    # 2) spill across pools: exact fits first, then smallest waste, then
    #    stable pool/index order — still no partial placement.
    if len(free) >= gang.replicas:
        ranked = sorted(
            free,
            key=lambda u: (u.chips - gang.chips_per_replica, u.pool, u.index),
        )
        decision.units = ranked[: gang.replicas]
    return decision
