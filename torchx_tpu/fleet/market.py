"""The elastic preemption market: who pays when a high class can't place.

When a gang of class *c* cannot place and plain capacity won't appear,
the market takes capacity from gangs of **strictly lower** classes,
cheapest sacrifice first:

* An **elastic** victim (training gang running with a reshapeable mesh)
  is *shrunk*, not killed: the scheduler resubmits it on fewer slices
  through the PR 7 mesh-reshape path (``$TPX_MESH`` through the attempt
  ledger), records the **debt** (its launch size), and grows it back when
  capacity frees. A shrink costs one checkpoint-resume, not the job.
* A **non-elastic** victim falls back to checkpoint-preempt: cancelled
  and requeued at its original position in its class (priority-ordered
  requeue), to re-place when capacity returns.

Victim order: lowest class first (``preemptible`` before ``batch``),
youngest first within a class — the cheapest progress is sacrificed
first. The market is all-or-nothing like placement itself: if the
combined plan cannot free enough suitable slices, NOTHING is executed
(no speculative shrinking that still leaves the demand queued).

This module is the pure decision layer — it inspects victims and returns
a plan; :mod:`torchx_tpu.fleet.api` executes plans through the daemon's
runner.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class Victim:
    """The market's view of one running gang (built by the scheduler).

    ``suitable`` is True when this gang's slices can host the demanding
    gang's replicas (chip count fits); freeing unsuitable slices helps
    nobody, so such gangs are never victimized for this demand."""

    job: str
    priority: int
    elastic: bool
    replicas: int
    min_replicas: int
    seq: int
    suitable: bool


@dataclass(frozen=True)
class Shrink:
    """Market action: reshape ``job`` down to ``to_replicas`` slices,
    freeing ``freed`` of them, and record the grow-back debt."""

    job: str
    to_replicas: int
    freed: int


@dataclass(frozen=True)
class Preempt:
    """Market action: checkpoint-preempt ``job`` (cancel + requeue at its
    original class position), freeing all ``freed`` of its slices."""

    job: str
    freed: int


MarketAction = Union[Shrink, Preempt]


def plan_market(
    needed_units: int,
    gang_priority: int,
    victims: list[Victim],
) -> list[MarketAction]:
    """Assemble the cheapest all-or-nothing plan freeing ``needed_units``
    suitable slices for a gang of class rank ``gang_priority``.

    Returns the action list, or ``[]`` when no combination of eligible
    victims frees enough (the demand stays queued untouched)."""
    if needed_units <= 0:
        return []
    eligible = [
        v
        for v in victims
        if v.suitable and v.priority > gang_priority
    ]
    # lowest class first, youngest first: cheapest progress pays first
    eligible.sort(key=lambda v: (-v.priority, -v.seq))
    plan: list[MarketAction] = []
    freed = 0
    for v in eligible:
        if freed >= needed_units:
            break
        if v.elastic:
            headroom = v.replicas - v.min_replicas
            if headroom <= 0:
                continue
            take = min(headroom, needed_units - freed)
            plan.append(
                Shrink(job=v.job, to_replicas=v.replicas - take, freed=take)
            )
            freed += take
        else:
            plan.append(Preempt(job=v.job, freed=v.replicas))
            freed += v.replicas
    if freed < needed_units:
        return []
    return plan
