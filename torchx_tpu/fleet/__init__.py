"""Fleet scheduler: priority classes, gang placement, preemption market.

This package turns the control daemon from a pass-through submitter into
a fleet scheduler. Demand arrives as gangs (N replicas of
:class:`~torchx_tpu.specs.api.TpuSlice`-shaped slices); the scheduler
orders them by priority class and per-tenant fair share, places them
all-or-nothing onto a modeled fleet with ICI/DCN locality preference,
uses the PR 10 deep-preflight cost model as an HBM placement oracle, and
— when a high class cannot place — runs an **elastic preemption
market**: shrink the cheapest elastic victim via the PR 7 mesh-reshape
path instead of killing it, record the debt, grow it back when capacity
frees. Non-elastic victims are checkpoint-preempted and requeued at
their original class position.

Layering: jax-free (enforced by ``scripts/lint_internal.py``). The
decision layers (:mod:`~torchx_tpu.fleet.model`,
:mod:`~torchx_tpu.fleet.queue`, :mod:`~torchx_tpu.fleet.placer`,
:mod:`~torchx_tpu.fleet.market`) are pure; only
:class:`~torchx_tpu.fleet.api.FleetScheduler` touches the world, and
only through the :class:`~torchx_tpu.fleet.api.FleetExecutor` seam the
daemon implements.
"""

from torchx_tpu.fleet.api import (
    FleetExecutor,
    FleetJob,
    FleetScheduler,
    parse_quotas,
)
from torchx_tpu.fleet.market import (
    MarketAction,
    Preempt,
    Shrink,
    Victim,
    plan_market,
)
from torchx_tpu.fleet.model import (
    DEFAULT_CLASS,
    PRIORITY_CLASSES,
    FleetModel,
    GangRequest,
    SlicePool,
    SliceUnit,
    priority_index,
)
from torchx_tpu.fleet.placer import (
    PlacementDecision,
    hbm_refusal,
    plan_placement,
)
from torchx_tpu.fleet.queue import (
    FleetJournal,
    FleetQueue,
    QueuedGang,
    over_quota,
)

__all__ = [
    "PRIORITY_CLASSES",
    "DEFAULT_CLASS",
    "priority_index",
    "SliceUnit",
    "SlicePool",
    "GangRequest",
    "FleetModel",
    "FleetQueue",
    "QueuedGang",
    "FleetJournal",
    "over_quota",
    "PlacementDecision",
    "plan_placement",
    "hbm_refusal",
    "Victim",
    "Shrink",
    "Preempt",
    "MarketAction",
    "plan_market",
    "FleetScheduler",
    "FleetExecutor",
    "FleetJob",
    "parse_quotas",
]
