"""Diagnostic data model for the preflight analyzer.

One report format for everything the launcher can statically check:
component source (``specs/file_linter.py``), AppDef structure, TPU topology
math, env/macro hygiene, scheduler capability fit and supervisor/retry
coherence all emit :class:`Diagnostic` records that aggregate into a
:class:`LintReport`. The report renders as human text (``tpx lint``) or
stable JSON (``tpx lint --json``), and error severity is what the
``Runner.dryrun`` gate refuses on (:class:`LintError`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional


class Severity(str, Enum):
    """How bad a diagnostic is.

    ERROR: the submission is doomed or the launcher's own wiring would be
        corrupted — the Runner gate refuses to submit.
    WARNING: likely a mistake, but the job can run; never gates.
    INFO: advisory context (e.g. capability profile missing); never gates.
    """

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    @property
    def rank(self) -> int:
        """Sort key: errors first, info last."""
        return {"error": 0, "warning": 1, "info": 2}[self.value]


@dataclass(frozen=True)
class Diagnostic:
    """One coded finding about an AppDef, component or scheduler pairing.

    Attributes:
        code: stable ``TPXnnn`` identifier (see docs/api/analyze.md for the
            full table). The hundreds digit is the family: 0xx spec
            structure, 1xx TPU topology/resources, 2xx env/macros, 3xx
            scheduler capability, 4xx supervisor/retry coherence.
        severity: :class:`Severity`; only errors gate submission.
        message: what is wrong, concretely.
        role: role name the finding is about, or None for app-level.
        field: dotted field path within the role/app (e.g.
            ``resource.tpu.topology``, ``env.TPX_REPLICA_ID``), or None.
        hint: how to fix it (one sentence; may be empty).
    """

    code: str
    severity: Severity
    message: str
    role: Optional[str] = None
    field: Optional[str] = None
    hint: str = ""

    @property
    def location(self) -> str:
        """``role.field`` / ``role`` / ``field`` / ``app`` — for rendering."""
        if self.role and self.field:
            return f"{self.role}.{self.field}"
        return self.role or self.field or "app"

    def to_dict(self) -> dict[str, Any]:
        """JSON-stable dict form (keys always present, fixed order)."""
        return {
            "code": self.code,
            "severity": self.severity.value,
            "role": self.role,
            "field": self.field,
            "message": self.message,
            "hint": self.hint,
        }


@dataclass
class LintReport:
    """All diagnostics from one analyzer run over one target.

    Attributes:
        target: what was analyzed (app name, component name, or file path).
        scheduler: scheduler the analysis was specialized for, or None.
        diagnostics: findings, kept in deterministic sorted order
            (severity, code, role, field).
    """

    target: str = ""
    scheduler: Optional[str] = None
    diagnostics: list[Diagnostic] = field(default_factory=list)

    def extend(self, diags: "list[Diagnostic] | LintReport") -> None:
        """Append diagnostics (from a list or another report) and re-sort."""
        if isinstance(diags, LintReport):
            diags = diags.diagnostics
        self.diagnostics.extend(diags)
        self.sort()

    def sort(self) -> None:
        """Deterministic order: severity rank, then code, then location."""
        self.diagnostics.sort(
            key=lambda d: (d.severity.rank, d.code, d.role or "", d.field or "")
        )

    @property
    def errors(self) -> list[Diagnostic]:
        """Error-severity findings — the ones the Runner gate refuses on."""
        return [d for d in self.diagnostics if d.severity is Severity.ERROR]

    @property
    def warnings(self) -> list[Diagnostic]:
        """Warning-severity findings."""
        return [d for d in self.diagnostics if d.severity is Severity.WARNING]

    @property
    def has_errors(self) -> bool:
        """True when at least one error-severity diagnostic is present."""
        return any(d.severity is Severity.ERROR for d in self.diagnostics)

    def codes(self) -> list[str]:
        """Distinct diagnostic codes, sorted."""
        return sorted({d.code for d in self.diagnostics})

    def summary(self) -> dict[str, int]:
        """Counts by severity, all three keys always present."""
        out = {"error": 0, "warning": 0, "info": 0}
        for d in self.diagnostics:
            out[d.severity.value] += 1
        return out

    def to_dict(self) -> dict[str, Any]:
        """Stable JSON form (consumed by ``tpx lint --json`` and CI)."""
        self.sort()
        return {
            "version": 1,
            "target": self.target,
            "scheduler": self.scheduler,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "summary": self.summary(),
        }

    def render(self) -> str:
        """Human-readable multi-line report (what ``tpx lint`` prints)."""
        self.sort()
        s = self.summary()
        sched = f" [scheduler: {self.scheduler}]" if self.scheduler else ""
        head = (
            f"{self.target or 'app'}: {s['error']} error(s),"
            f" {s['warning']} warning(s), {s['info']} info{sched}"
        )
        lines = [head]
        for d in self.diagnostics:
            lines.append(f"  {d.severity.value:<7} {d.code} [{d.location}] {d.message}")
            if d.hint:
                lines.append(f"          fix: {d.hint}")
        if not self.diagnostics:
            lines.append("  clean: no findings")
        return "\n".join(lines)


class LintError(Exception):
    """Raised by the ``Runner.dryrun`` gate when error-severity diagnostics
    exist. Carries the full :class:`LintReport`; the message embeds the
    rendered report so the refusal is actionable without re-running
    ``tpx lint``. Bypass with ``no_lint=True`` / ``--no-lint`` /
    ``TPX_NO_LINT=1``."""

    def __init__(self, report: LintReport) -> None:
        self.report = report
        n = len(report.errors)
        super().__init__(
            f"preflight lint found {n} error(s); fix them or bypass with"
            f" --no-lint / TPX_NO_LINT=1\n{report.render()}"
        )
