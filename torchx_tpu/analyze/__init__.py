"""Preflight static analysis: coded diagnostics before a job reaches a queue.

Slice capacity is the scarce resource — a malformed AppDef that dies minutes
later on a cluster is the most expensive way to find a typo. This subsystem
statically evaluates an :class:`~torchx_tpu.specs.api.AppDef` (plus the
target scheduler and run opts) against a pluggable rule registry and emits
coded diagnostics, each with a severity, a role/field location, a message
and a fix hint.

Wired in three places:

* ``Runner.dryrun`` / ``Runner.run`` refuse to submit on error-severity
  diagnostics (raising :class:`LintError`); bypass with ``no_lint=True``,
  ``--no-lint`` or ``TPX_NO_LINT=1``.
* ``tpx lint <component|appdef.json> [--scheduler S] [--json]`` runs the
  same analysis standalone and exits non-zero on errors.
* component source checks (``specs/file_linter.py``) report through the
  same :class:`Diagnostic` model, so components and AppDefs share one
  report format.

Every run emits a ``launcher.lint`` span and diagnostic-count metrics
(``tpx_lint_runs_total``, ``tpx_lint_diagnostics_total``) through the obs
pipeline.

Diagnostic codes
----------------

| code | severity | meaning | fix hint |
|---|---|---|---|
| TPX001 | error | component source has a syntax error, or the function was not found | point at ``path/to/file.py:fn`` or a name from ``tpx builtins`` |
| TPX002 | error | component parameter is missing a type annotation | annotate every parameter |
| TPX003 | error | component parameter type is not CLI-renderable | use str/int/float/bool, Optional/list/dict of those |
| TPX004 | error | component takes ``**kwargs`` | enumerate parameters explicitly |
| TPX005 | error | component return annotation is not ``-> AppDef`` | components must return an AppDef |
| TPX006 | warning | component has no docstring | add a google-style docstring (it becomes the CLI help) |
| TPX007 | info | component could not be materialized with the given args; AppDef-level rules skipped | pass component arguments after the name |
| TPX010 | error | AppDef has no roles | add at least one Role |
| TPX011 | error | role has no entrypoint | set Role.entrypoint |
| TPX012 | error | ``num_replicas <= 0`` | set num_replicas >= 1 |
| TPX013 | error | ``min_replicas`` outside ``(0, num_replicas]`` | lower min_replicas or raise num_replicas |
| TPX014 | error | duplicate role names in one AppDef | make role names unique |
| TPX015 | warning | role has no image | container backends need an image |
| TPX101 | error | no such TPU slice: chip count impossible for the generation (multi-host slices are built from fixed-size host VMs; v5e/v6e pods cap at 256 chips) | use a valid chip count for the generation |
| TPX102 | error | topology dimensionality does not match the generation (v5e/v6e are 2D meshes, v4/v5p are 3D tori) | use a shape like ``4x8`` (v5e) or ``2x2x4`` (v4) |
| TPX103 | error | TPU-looking key in ``resource.devices`` | TPU chips are allocated via ``resource.tpu``, never devices |
| TPX110 | warning | ``--mesh`` pairs expert parallelism (``ep``) with ``fsdp``/``sp`` sharding: embedding/expert gathers reshard dim-sharded → batch/seq-sharded, which GSPMD partitions by involuntary full rematerialization unless gather outputs carry explicit sharding constraints (heuristic fallback — when the role resolves into a full parallelism plan, TPX700 propagation supersedes this) | pin gather outputs with ``with_sharding_constraint``, or use ``torchx_tpu.examples.train_llama`` which already does |
| TPX111 | error | unknown mesh axis name in a ``--mesh`` role arg | use the trainer mesh axes ``pp/dp/fsdp/ep/tp/sp`` |
| TPX112 | warning | ``--kernels pallas`` will silently fall back to the reference XLA ops: the role has no TPU resource, or the config/seq shapes cannot tile the fused kernels (flash attention needs head_dim 64/128/256 and a 128-divisible sequence; the fused norm needs a lane-aligned dim) | run on TPU with tileable shapes, or drop the flag (``--kernels interpret`` is the parity-testing path) |
| TPX201 | error | role env overrides a launcher-injected identity/rendezvous var (``TPX_REPLICA_ID``, ``MEGASCALE_*``, ...) | remove it — every scheduler injects it |
| TPX202 | warning | env var uses a reserved prefix (``TPX_``/``TPU_``/``MEGASCALE_``) but is not a documented knob | rename it |
| TPX203 | info | ``JAX_*`` env var set (JAX runtime config) | make sure it is intentional |
| TPX204 | warning | ``${...}`` placeholder is not a launcher macro | use ``$${...}`` for runtime shell expansion, or fix the macro name |
| TPX210 | error | two named ports map to the same number | give each port a distinct number |
| TPX211 | error | port outside 1-65535 | pick a valid TCP port |
| TPX212 | warning | serve-shaped role binds ``--port`` with no matching ``port_map`` entry | map the port so routers/serve pools can reach it |
| TPX213 | error | disaggregated serving role (``--serve-role prefill``/``decode``) declares no KV transfer path | add ``--kv-transfer`` or ``tpx/kv_transfer`` role metadata (``generate_server_disagg`` wires both) |
| TPX214 | warning | role declares SLO specs (``--slo`` / ``tpx/slo`` metadata) but the backend has no ``/metricz`` scrape path | target a scrape-reachable backend or drop the replica-scrape SLOs |
| TPX215 | warning | step profiling enabled (``--profile`` / ``TPX_PROFILE=1``) but the backend has no ``/metricz`` scrape path — ``tpx_profile_*`` summaries stay local to the replica's obs dir | target a scrape-reachable backend, or read the attribution locally with ``tpx profile`` |
| TPX220 | error | two mounts share a destination path | each mount needs a distinct dst |
| TPX221 | warning | mount destination is not absolute | use an absolute container path |
| TPX300 | info | no capability profile for the scheduler; capability rules skipped | builtin backends declare ``CAPABILITIES`` |
| TPX301 | error | mounts on a backend that does not materialize them | remove mounts or use local_docker / gke |
| TPX302 | warning | backend has no ``delete()``: supervised resubmits cannot clean up terminal attempts | expect leftover terminal jobs |
| TPX303 | error | multi-role AppDef on a single-role backend | split the app or use gke / slurm |
| TPX304 | error | multi-slice TPU role (``num_replicas > 1``) on a backend without DCN wiring | use num_replicas=1 or gke |
| TPX305 | error | backend only provisions TPU slices but the role has no ``resource.tpu`` | set resource.tpu or pick another backend |
| TPX306 | warning | ``max_retries`` set but the backend has no native restarts | run under ``tpx supervise`` |
| TPX307 | warning | backend builds concrete resource requests but cpu/memMB are unset | set Resource.cpu / Resource.memMB |
| TPX401 | warning | ``RetryPolicy.REPLICA`` on a TPU role (one host cannot rejoin the ICI collective) | use RetryPolicy.APPLICATION |
| TPX402 | error | ``max_retries < 0`` | use 0 to disable retries |
| TPX403 | warning | supervisor preemption budget on a backend that cannot classify preemptions | raise max_app_retries or switch backend |
| TPX404 | warning | role sets the supervisor's resume env var (it is injected on every resubmission) | let the supervisor drive resume |
| TPX501 | warning | supervisor resubmit budgets stack multiplicatively with the backend's native ``max_retries`` restarts | set max_retries=0 under ``tpx supervise`` |
| TPX502 | error | ``TPX_FAULT_PLAN`` set while submitting to a non-local backend (chaos drill would corrupt real cloud calls) | unset it or drill against local / local_docker |
| TPX503 | warning | policy budgets checkpoint-resume retries but no role passes a checkpoint-dir flag (every resubmit restarts from step 0) | pass ``--ckpt-dir`` to the app or drop ``checkpoint_dir`` |
| TPX601 | warning | hang detection under the control daemon (``TPX_CONTROL_ADDR``) on a backend without the ``watch`` capability — state changes surface at the watch poll interval | use a watch-capable backend, tighten ``TPX_WATCH_INTERVAL``, or unset ``TPX_CONTROL_ADDR`` |
| TPX602 | warning | fleet class ``batch``/``preemptible`` (a preemption-market victim) with neither ``elastic_reshape`` nor a checkpoint-dir flag — every market shrink/preemption costs full progress | make the gang elastic (policy ``elastic_reshape`` + mesh, submit ``elastic=true``) or pass ``--ckpt-dir`` |
| TPX603 | warning | pipeline promotion stage (``tpx/pipeline=promote`` metadata) on a backend without ``/metricz`` scrape — the canary burn-rate gate sees zero samples and silently degrades to eval-score-only | run the promote stage on a scrape-reachable backend (local, docker, gke, slurm) or accept eval-score-only gating |
| TPX604 | warning | simulation scenario names a backend other than ``sim`` — the virtual-time harness only drives the modeled executor, so every journaled placement is simulated regardless of the label | set ``"backend": "sim"`` (or drop the key) so the journal cannot be mistaken for a real-backend run |
| TPX605 | warning | federation config with a single cell (no failover possible — a drain or daemon loss leaves the router nowhere to spill), or a multi-cell promotion wave without per-cell rollback enabled (a bad candidate halted in one region still rolls into the next) | register at least two cells (``tpx cell add``); enable rollback with a finite ``burn_threshold > 0`` on every promote stage of a multi-cell wave |
| TPX700 | error | deep preflight: sharding propagation found a resharding boundary GSPMD resolves by involuntary full rematerialization (dim-sharded gather/dispatch into a batch/seq-sharded consumer with no output constraint) | pin the gather/combine output with ``with_sharding_constraint`` (see ``models/llama.py forward_features``), or train with ``torchx_tpu.examples.train_llama`` |
| TPX701 | error | deep preflight: static HBM fit exceeded — params + optimizer + gradients + activations + logits outgrow the per-chip budget under the headroom | raise ``fsdp``/``tp``, lower ``--batch``/``--seq``, or use ``--remat-policy full`` |
| TPX702 | warning | deep preflight: a DCN-classified mesh axis (``fsdp``/``ep``/``tp``/``sp``) carries ICI-scale collective traffic — cross-slice bandwidth will pace every step | keep fsdp/ep/tp/sp inside a slice; put only dp/pp on the cross-slice dimension |
| TPX703 | error | deep preflight: the role is plan-shaped but the ``--mesh`` spec cannot resolve onto its device count | make the axis sizes multiply out to slices × chips (or replicas × nproc) |
| TPX704 | warning | deep preflight: a serve-shaped role's params + KV pool do not fit the per-chip HBM | lower ``--max-batch``, shorten ``max_seq``, or use a larger-HBM generation |
| TPX705 | info | deep preflight skipped: no parallelism plan resolvable from the role args (``tpx explain`` only — the submit gate falls back to the TPX110 heuristic) | use a builtin ``--config`` name to enable static sharding/HBM analysis |
| TPX706 | error | the role's resolved plan diverges from the pinned ``tpx tune`` artifact (``$TPX_PLAN_ARTIFACT``): a tuned knob (config/mesh/batch/seq/remat/int8) was changed after tuning | re-run ``tpx tune`` for the new config, or fix the drifted flag to match the artifact (the message lists each diverging field) |
| TPX707 | error | the pinned ``$TPX_PLAN_ARTIFACT`` file is unreadable, malformed, or fails its content digest (edited by hand?) | re-emit the artifact with ``tpx tune``, or unset ``TPX_PLAN_ARTIFACT`` to submit unpinned |
| TPX901 | error | selfcheck: a jax-free layer imports jax eagerly — directly or through a chain of module-level imports (``tpx selfcheck``, whole-program import graph) | make the first edge of the evidence chain a function-local import |
| TPX910 | error | selfcheck: raw ``time.time/sleep/monotonic()`` call in a sim-hosted module (derived by reachability from ``sim/harness.py``) outside the clock seams | accept injected ``clock``/``sleep`` callables defaulting to the real ones |
| TPX920 | error | selfcheck: unguarded mutable attribute write in a class whose instances cross threads (thread-entry evidence in the message) | wrap the write in ``with self._lock:`` |
| TPX921 | warning | selfcheck: thread-crossing class allocates no lock at all | allocate ``self._lock = threading.Lock()`` in ``__init__`` |
| TPX930 | error | selfcheck: append handle on a journal path with no flush+fsync before the write is claimed durable | append through ``util.jsonl.append_jsonl`` |
| TPX931 | warning | selfcheck: state-file rewrite (``open(*.json, "w")``) without tmp + fsync + ``os.replace`` | rewrite through ``util.jsonl.rewrite_json`` |
| TPX932 | warning | selfcheck: journal reader hand-rolls ``json.loads`` per line instead of the torn-line-holdback helper | read through ``util.jsonl.iter_jsonl`` |
| TPX940 | warning | selfcheck: raw ``"TPX*"`` env literal outside ``settings.py`` bypasses the env registry | add/reuse an ``ENV_*`` constant in ``torchx_tpu/settings.py`` |
| TPX950 | error | selfcheck: raw ``subprocess.*`` in ``schedulers/`` outside the resilient ``_run_cmd``/``_popen`` seam | route it through the backend's ``_run_cmd`` |

The TPX9xx rows are emitted by ``tpx selfcheck``
(:mod:`torchx_tpu.analyze.selfcheck`), the whole-program invariant
analyzer over the launcher's own source tree, not by the submit-path
``analyze()`` gate.
"""

from torchx_tpu.analyze.diagnostics import (
    Diagnostic,
    LintError,
    LintReport,
    Severity,
)
from torchx_tpu.analyze.engine import analyze, analyze_component, capabilities_for
from torchx_tpu.analyze.explain import ExplainReport, deep_preflight, explain
from torchx_tpu.analyze.plan import (
    MODEL_SHAPES,
    ModelShape,
    ParallelPlan,
    PlanError,
    plan_from_role,
)
from torchx_tpu.analyze.propagation import Boundary, ShardingFlow, propagate
from torchx_tpu.analyze.rules import (
    RuleContext,
    all_rules,
    register_rule,
    rule,
)

__all__ = [
    "Severity",
    "Diagnostic",
    "LintReport",
    "LintError",
    "RuleContext",
    "rule",
    "register_rule",
    "all_rules",
    "analyze",
    "analyze_component",
    "capabilities_for",
    "ExplainReport",
    "explain",
    "deep_preflight",
    "ModelShape",
    "MODEL_SHAPES",
    "ParallelPlan",
    "PlanError",
    "plan_from_role",
    "Boundary",
    "ShardingFlow",
    "propagate",
]
