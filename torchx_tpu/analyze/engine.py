"""The analyzer engine: run every registered rule over one target.

Entry points:

* :func:`analyze` — lint an :class:`~torchx_tpu.specs.api.AppDef` (optionally
  specialized for a target scheduler + run opts + supervisor policy).
* :func:`analyze_component` — lint a component function's *source*
  (``specs/file_linter.py`` checks re-expressed as TPX00x diagnostics).
* :func:`capabilities_for` — resolve a builtin backend's declared
  :class:`~torchx_tpu.schedulers.api.SchedulerCapabilities`.

Every run opens a ``launcher.lint`` span through the obs pipeline and bumps
the ``tpx_lint_runs_total`` / ``tpx_lint_diagnostics_total`` counters, so
preflight rejections are visible in ``tpx trace`` timelines and metrics.
"""

from __future__ import annotations

import importlib
import inspect
from typing import Mapping, Optional

from torchx_tpu.analyze.diagnostics import Diagnostic, LintReport, Severity
from torchx_tpu.analyze.rules import RuleContext, all_rules
from torchx_tpu.schedulers.api import SchedulerCapabilities
from torchx_tpu.specs.api import AppDef, CfgVal
from torchx_tpu.supervisor.policy import SupervisorPolicy

_SEVERITY = {
    "error": Severity.ERROR,
    "warning": Severity.WARNING,
    "info": Severity.INFO,
}


def capabilities_for(scheduler: Optional[str]) -> Optional[SchedulerCapabilities]:
    """The declared feature profile of a builtin backend, or None when the
    scheduler is unknown / not importable (capability rules then skip).

    Resolution: the backend module named in
    :data:`~torchx_tpu.schedulers.DEFAULT_SCHEDULER_MODULES` declares a
    module-level ``CAPABILITIES`` constant; plugins may instead set the
    ``capabilities`` class attribute on their Scheduler subclass.
    """
    if not scheduler:
        return None
    from torchx_tpu.schedulers import DEFAULT_SCHEDULER_MODULES

    module_fn = DEFAULT_SCHEDULER_MODULES.get(scheduler)
    if module_fn is None:
        return None
    modname, _, _ = module_fn.partition(":")
    try:
        mod = importlib.import_module(modname)
    except Exception:  # noqa: BLE001 - missing optional backend deps
        return None
    cap = getattr(mod, "CAPABILITIES", None)
    return cap if isinstance(cap, SchedulerCapabilities) else None


def analyze(
    app: AppDef,
    scheduler: Optional[str] = None,
    cfg: Optional[Mapping[str, CfgVal]] = None,
    policy: Optional[SupervisorPolicy] = None,
    capabilities: Optional[SchedulerCapabilities] = None,
    gate: str = "api",
    session: str = "",
) -> LintReport:
    """Run all registered rules over ``app`` and return the report.

    Args:
        app: the AppDef to analyze.
        scheduler: target backend name; enables capability rules.
        cfg: run opts (raw or resolved) for scheduler-aware rules.
        policy: supervisor policy for retry-coherence rules.
        capabilities: explicit feature profile; defaults to
            :func:`capabilities_for` on ``scheduler``.
        gate: metric label for who ran the lint ("runner"/"cli"/"api").
        session: session name stamped on the ``launcher.lint`` span.
    """
    from torchx_tpu.obs import metrics as obs_metrics
    from torchx_tpu.obs import trace as obs_trace

    if capabilities is None:
        capabilities = capabilities_for(scheduler)
    ctx = RuleContext(
        app=app,
        scheduler=scheduler,
        cfg=cfg or {},
        capabilities=capabilities,
        policy=policy,
    )
    report = LintReport(target=app.name, scheduler=scheduler)
    with obs_trace.span(
        "launcher.lint",
        session=session,
        scheduler=scheduler,
        app=app.name,
        gate=gate,
    ) as sp:
        for _name, fn in all_rules().items():
            report.extend(list(fn(ctx)))
        summary = report.summary()
        if sp is not None:
            sp.attrs["errors"] = summary["error"]
            sp.attrs["warnings"] = summary["warning"]
    obs_metrics.LINT_RUNS.inc(
        gate=gate, status="errors" if report.has_errors else "clean"
    )
    for d in report.diagnostics:
        obs_metrics.LINT_DIAGNOSTICS.inc(code=d.code, severity=d.severity.value)
    return report


def analyze_component(name: str, gate: str = "api", session: str = "") -> LintReport:
    """Lint a component function's source: ``dist.spmd`` (builtin) or
    ``path/to/file.py:fn`` (custom). Returns file-linter findings (TPX00x)
    as a :class:`LintReport` — including warnings the component finder's
    hard validation drops."""
    from torchx_tpu.obs import metrics as obs_metrics
    from torchx_tpu.obs import trace as obs_trace
    from torchx_tpu.specs import file_linter

    report = LintReport(target=name)
    with obs_trace.span("launcher.lint", session=session, app=name, gate=gate) as sp:
        messages = []
        if ":" in name:
            path, _, fn_name = name.rpartition(":")
            import os

            if not os.path.isfile(path):
                report.extend(
                    [
                        Diagnostic(
                            code="TPX001",
                            severity=Severity.ERROR,
                            message=f"component file not found: {path}",
                            field=name,
                            hint="pass path/to/file.py:fn_name",
                        )
                    ]
                )
            else:
                messages = file_linter.validate(path, fn_name, include_warnings=True)
        else:
            from torchx_tpu.specs.finder import get_components

            components = get_components()
            if name not in components:
                report.extend(
                    [
                        Diagnostic(
                            code="TPX001",
                            severity=Severity.ERROR,
                            message=(
                                f"component {name!r} not found;"
                                f" available: {sorted(components)}"
                            ),
                            field=name,
                            hint="run `tpx builtins` to list components",
                        )
                    ]
                )
            else:
                fn = components[name].fn
                try:
                    path = inspect.getfile(fn)
                except TypeError:
                    path = None
                if path:
                    messages = file_linter.validate(
                        path, fn.__name__, include_warnings=True
                    )
        report.extend(
            [
                Diagnostic(
                    code=m.code,
                    severity=_SEVERITY.get(m.severity, Severity.ERROR),
                    message=m.description,
                    field=f"source:{m.line}:{m.char}",
                    hint="see the component authoring rules in docs/components.md",
                )
                for m in messages
            ]
        )
        summary = report.summary()
        if sp is not None:
            sp.attrs["errors"] = summary["error"]
            sp.attrs["warnings"] = summary["warning"]
    obs_metrics.LINT_RUNS.inc(
        gate=gate, status="errors" if report.has_errors else "clean"
    )
    for d in report.diagnostics:
        obs_metrics.LINT_DIAGNOSTICS.inc(code=d.code, severity=d.severity.value)
    return report
