"""Whole-program invariant analyzer for the launcher's own source tree.

The launcher's production claims rest on invariants that no unit test
can watch globally: jax-free layers stay jax-free *transitively*,
sim-hosted modules never read the wall clock (the sim journal must be a
pure function of the seed), shared state in the threaded control plane
is lock-guarded, every journal write is crash-safe, and every ``TPX_*``
env knob lives in the registry. ``tpx selfcheck`` proves them
statically over the whole ``torchx_tpu/`` tree: one parse per module,
one import graph, six passes, coded TPX9xx diagnostics on the standard
:class:`~torchx_tpu.analyze.diagnostics.LintReport` model (stable
``--json``, human render, exit 0 clean / 1 findings / 2 usage error).

Passes and codes
----------------

| code | severity | pass | meaning |
|---|---|---|---|
| TPX901 | error | jax-free | a jax-free layer imports jax eagerly — directly or through a chain of module-level imports (the evidence chain is in the message) |
| TPX910 | error | clock | raw ``time.time/sleep/monotonic()`` call in a sim-hosted module (derived by reachability from ``sim/harness.py``), outside the clock seams |
| TPX920 | error | locks | unguarded mutable attribute write in a class whose instances cross threads (thread-entry evidence in the message) |
| TPX921 | warning | locks | thread-crossing class allocates no lock at all |
| TPX930 | error | journal | append handle on a ``*.jsonl`` path with no flush+fsync before the write is claimed durable |
| TPX931 | warning | journal | state-file rewrite (``open(*.json, "w")``) without tmp+fsync+``os.replace`` |
| TPX932 | warning | journal | journal reader hand-rolls ``json.loads`` per line instead of the torn-line-holdback helper (``util.jsonl.iter_jsonl``) |
| TPX940 | warning | env | raw ``"TPX*"`` env literal outside ``settings.py`` bypasses the env registry |
| TPX950 | error | subprocess | raw ``subprocess.*`` in ``schedulers/`` outside the resilient ``_run_cmd``/``_popen`` seam |

Heuristic passes (TPX92x/93x) pair with a checked-in triaged baseline
(``selfcheck_baseline.json``, file+code keys, no line numbers):
pre-existing findings a human judged benign are suppressed; anything new
fails the tier-1 SELFCHECK gate. ``scripts/lint_internal.py`` survives
as a thin shim over :data:`~torchx_tpu.analyze.selfcheck.engine.LEGACY_PASSES`.
"""

from torchx_tpu.analyze.selfcheck.baseline import (
    BASELINE_FILENAME,
    Baseline,
    finding_file,
)
from torchx_tpu.analyze.selfcheck.engine import (
    LEGACY_PASSES,
    PASSES,
    PassContext,
    SelfCheckConfig,
    run_selfcheck,
)
from torchx_tpu.analyze.selfcheck.graph import (
    Edge,
    ImportGraph,
    ModuleInfo,
    build_graph,
)

__all__ = [
    "BASELINE_FILENAME",
    "Baseline",
    "finding_file",
    "LEGACY_PASSES",
    "PASSES",
    "PassContext",
    "SelfCheckConfig",
    "run_selfcheck",
    "Edge",
    "ImportGraph",
    "ModuleInfo",
    "build_graph",
]
