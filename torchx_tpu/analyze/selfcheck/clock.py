"""TPX910 — clock discipline on the *derived* sim-hosted module set.

Every module the virtual-time simulator hosts must reach the wall clock
only through its injected clock seam: one raw ``time.time()`` /
``time.sleep()`` / ``time.monotonic()`` call site breaks virtual-time
determinism silently — the sim keeps running, but the journal stops
being a pure function of the seed.

The old lint (``scripts/lint_internal.py`` rule 3) policed a
hand-maintained ``SIM_HOSTED`` tuple, which rotted as subsystems were
added. This pass derives the hosted set by **reachability**: the eager
import closure of ``sim/harness.py`` (everything the harness wires onto
the VirtualClock), plus configured extension roots (the supervisor,
which the sim drives through scenario events rather than imports), plus
any module annotated ``# tpx: sim-hosted``.

Only ``ast.Call`` nodes are flagged: ``clock: Callable[[], float] =
time.time`` default-argument references are the injection idiom itself
and must stay legal. ``time.perf_counter`` measures wall cost (never
scheduling) and is allowed everywhere; the clock seams themselves
(``sim/clock.py``, ``util/times.py``) are exempt.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING

from torchx_tpu.analyze.diagnostics import Diagnostic, Severity

if TYPE_CHECKING:
    from torchx_tpu.analyze.selfcheck.engine import PassContext

CODE = "TPX910"

#: time attributes that schedule or stamp (perf_counter deliberately absent)
WALL_CLOCK_CALLS = ("time", "sleep", "monotonic")

#: module-body comment that opts a module into the hosted set explicitly
SIM_HOSTED_ANNOTATION = "# tpx: sim-hosted"


def wall_clock_sites(tree: ast.Module) -> list[tuple[int, str]]:
    """Raw wall-clock *call* sites in one parsed module — the single-file
    primitive behind the legacy shim. Returns ``(lineno, attr)`` pairs."""
    sites: list[tuple[int, str]] = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
                and fn.attr in WALL_CLOCK_CALLS
            ):
                sites.append((node.lineno, fn.attr))
            self.generic_visit(node)

    V().visit(tree)
    return sites


def sim_hosted_modules(ctx: "PassContext") -> dict[str, str]:
    """Derive the hosted set: module name -> one-line evidence of *why*
    it is hosted (shown in the diagnostic message)."""
    hosted: dict[str, str] = {}
    entry = ctx.module_at(ctx.config.sim_entry)
    if entry is not None:
        why = f"in the eager import closure of {ctx.config.sim_entry}"
        for mod in sorted(ctx.graph.eager_closure(entry.name)):
            hosted[mod] = why
    for root in ctx.config.sim_extra_roots:
        for info in ctx.modules_under(root):
            hosted.setdefault(info.name, f"under sim extension root {root!r}")
    for info in ctx.graph.modules.values():
        if SIM_HOSTED_ANNOTATION in info.source:
            hosted.setdefault(info.name, "annotated '# tpx: sim-hosted'")
    return hosted


def check(ctx: "PassContext") -> list[Diagnostic]:
    """Flag raw wall-clock calls in every derived sim-hosted module."""
    out: list[Diagnostic] = []
    exempt = {
        ctx.module_at(p).name
        for p in ctx.config.clock_seams
        if ctx.module_at(p) is not None
    }
    for mod, why in sorted(sim_hosted_modules(ctx).items()):
        if mod in exempt:
            continue
        info = ctx.graph.modules[mod]
        for lineno, attr in wall_clock_sites(info.tree):
            out.append(
                ctx.finding(
                    CODE,
                    Severity.ERROR,
                    info,
                    lineno,
                    f"raw time.{attr}() in a sim-hosted module ({why});"
                    " virtual time silently diverges",
                    hint=(
                        "go through the injected clock seam"
                        " (sim/clock.py) — accept clock/sleep callables"
                        " defaulting to time.time/time.sleep"
                    ),
                )
            )
    return out
