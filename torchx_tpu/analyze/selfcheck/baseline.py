"""Triaged suppression baseline for ``tpx selfcheck``.

The passes are heuristic by design; findings a human has reviewed and
judged benign are recorded in a checked-in baseline file
(``selfcheck_baseline.json`` at the repo root) and suppressed on later
runs. Keys are **file + code only** — deliberately no line numbers, so
unrelated edits to a triaged file don't churn the baseline — and the
suppression file never grows implicitly: ``tpx selfcheck
--update-baseline`` rewrites it from the current findings, which a
reviewer then diffs like any other change.

Format (stable, sorted)::

    {
      "version": 1,
      "suppressions": {
        "torchx_tpu/serve/engine.py": ["TPX920"],
        ...
      }
    }
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from torchx_tpu.analyze.diagnostics import Diagnostic, LintReport

BASELINE_FILENAME = "selfcheck_baseline.json"


def finding_file(diag: Diagnostic) -> str:
    """The repo-relative file a selfcheck diagnostic is anchored to
    (its ``field`` is ``path:line``)."""
    return (diag.field or "").rsplit(":", 1)[0]


@dataclass
class Baseline:
    """file -> set of suppressed TPX9xx codes."""

    suppressions: dict[str, set[str]] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Read a baseline file; a missing file is an empty baseline
        (malformed content raises ``ValueError`` — a corrupt baseline
        must fail loudly, not silently unsuppress everything)."""
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "suppressions" not in doc:
            raise ValueError(f"not a selfcheck baseline: {path}")
        return cls(
            suppressions={
                str(file): set(map(str, codes))
                for file, codes in doc["suppressions"].items()
            }
        )

    @classmethod
    def from_report(cls, report: LintReport) -> "Baseline":
        """Baseline that suppresses exactly the report's findings."""
        sup: dict[str, set[str]] = {}
        for d in report.diagnostics:
            sup.setdefault(finding_file(d), set()).add(d.code)
        return cls(suppressions=sup)

    def is_suppressed(self, diag: Diagnostic) -> bool:
        """True when the diagnostic's file + code pair is baselined."""
        return diag.code in self.suppressions.get(finding_file(diag), ())

    def apply(self, report: LintReport) -> tuple[LintReport, int]:
        """Split a raw report into (unsuppressed report, suppressed
        count)."""
        kept = LintReport(target=report.target, scheduler=report.scheduler)
        suppressed = 0
        for d in report.diagnostics:
            if self.is_suppressed(d):
                suppressed += 1
            else:
                kept.diagnostics.append(d)
        kept.sort()
        return kept, suppressed

    def save(self, path: str) -> None:
        """Write the stable sorted form (atomic tmp + fsync + replace —
        the baseline gates CI and must never be observed torn)."""
        doc = {
            "version": 1,
            "suppressions": {
                file: sorted(codes)
                for file, codes in sorted(self.suppressions.items())
            },
        }
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
