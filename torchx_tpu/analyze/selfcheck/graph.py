"""Whole-program import graph over the ``torchx_tpu`` source tree.

One parse per module, shared by every pass (:mod:`.engine` owns the
cache). Two edge sets per module:

* **eager** — imports executed when the module is imported: module-level
  statements *and* class-body statements (a class body runs at import
  time). These are the edges the transitive jax-free proof (TPX901) and
  the sim-hosted reachability derivation (TPX910) walk.
* **lazy** — imports nested inside a function/method body. They are the
  sanctioned escape hatch for heavy deps (``tpx explain --aot``) and are
  deliberately NOT walked by the closure.

Importing a submodule executes every ancestor package's ``__init__``, so
an eager edge to ``torchx_tpu.control.events`` also adds an eager edge to
``torchx_tpu.control`` — without this, a jax import hidden in a package
``__init__`` would escape the proof.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class ModuleInfo:
    """One parsed source module of the scanned package."""

    #: dotted module name (``torchx_tpu.cli.main``; packages use the
    #: package name itself for their ``__init__.py``)
    name: str
    #: path relative to the repo root (``torchx_tpu/cli/main.py``)
    relpath: str
    #: absolute filesystem path
    path: str
    #: parsed AST (one parse, shared by all passes)
    tree: ast.Module
    #: raw source text (comment-level annotations, e.g. ``# tpx: shared``)
    source: str

    def source_lines(self) -> list[str]:
        """Source split into lines (1-indexed via ``lines[lineno - 1]``)."""
        return self.source.splitlines()


@dataclass
class Edge:
    """One import site: importer -> target at a line."""

    target: str
    lineno: int


@dataclass
class ImportGraph:
    """Eager/lazy import edges for every module of one package.

    Attributes:
        modules: dotted name -> :class:`ModuleInfo` for every ``.py`` file.
        eager: intra-package eager edges (module -> imported modules).
        lazy: intra-package function-local edges (not walked by closures).
        eager_external: eager imports leaving the package, by top-level
            distribution name (``jax``, ``numpy``, ``time``, ...).
        lazy_external: same for function-local imports.
    """

    modules: dict[str, ModuleInfo] = field(default_factory=dict)
    eager: dict[str, list[Edge]] = field(default_factory=dict)
    lazy: dict[str, list[Edge]] = field(default_factory=dict)
    eager_external: dict[str, list[Edge]] = field(default_factory=dict)
    lazy_external: dict[str, list[Edge]] = field(default_factory=dict)

    def eager_closure(self, start: str) -> set[str]:
        """Every module reachable from ``start`` over eager edges,
        ``start`` included."""
        seen = {start}
        stack = [start]
        while stack:
            mod = stack.pop()
            for e in self.eager.get(mod, ()):
                if e.target not in seen:
                    seen.add(e.target)
                    stack.append(e.target)
        return seen

    def eager_chain(self, start: str, dst: str) -> Optional[list[str]]:
        """Shortest eager import chain ``start -> ... -> dst`` (module
        names, both ends included), or None when unreachable. BFS with
        sorted neighbor order, so the evidence chain is deterministic."""
        if start == dst:
            return [start]
        prev: dict[str, str] = {}
        queue = [start]
        seen = {start}
        while queue:
            nxt: list[str] = []
            for mod in queue:
                for e in sorted(self.eager.get(mod, ()), key=lambda e: e.target):
                    if e.target in seen:
                        continue
                    seen.add(e.target)
                    prev[e.target] = mod
                    if e.target == dst:
                        chain = [dst]
                        while chain[-1] != start:
                            chain.append(prev[chain[-1]])
                        return list(reversed(chain))
                    nxt.append(e.target)
            queue = nxt
        return None

    def first_eager_edge(self, src: str, dst: str) -> Optional[Edge]:
        """The earliest eager import site of ``dst`` inside ``src``."""
        hits = [e for e in self.eager.get(src, ()) if e.target == dst]
        return min(hits, key=lambda e: e.lineno) if hits else None


def _iter_py_files(pkg_root: str) -> Iterator[str]:
    for root, dirs, files in os.walk(pkg_root):
        dirs.sort()
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def module_name_for(pkg_root: str, pkg_name: str, path: str) -> str:
    """Dotted module name of one source file under the package root."""
    rel = os.path.relpath(path, pkg_root)
    parts = rel[: -len(".py")].split(os.sep)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join([pkg_name, *parts]) if parts else pkg_name


def scan_package(pkg_root: str, pkg_name: str, repo_root: str) -> dict[str, ModuleInfo]:
    """Parse every ``.py`` file under ``pkg_root`` once."""
    modules: dict[str, ModuleInfo] = {}
    for path in _iter_py_files(pkg_root):
        with open(path) as f:
            source = f.read()
        modules[module_name_for(pkg_root, pkg_name, path)] = ModuleInfo(
            name=module_name_for(pkg_root, pkg_name, path),
            relpath=os.path.relpath(path, repo_root),
            path=path,
            tree=ast.parse(source, filename=path),
            source=source,
        )
    return modules


def _is_type_checking(test: ast.expr) -> bool:
    """True for ``TYPE_CHECKING`` / ``typing.TYPE_CHECKING`` guards."""
    if isinstance(test, ast.Name):
        return test.id == "TYPE_CHECKING"
    return (
        isinstance(test, ast.Attribute)
        and test.attr == "TYPE_CHECKING"
        and isinstance(test.value, ast.Name)
        and test.value.id == "typing"
    )


class _ImportCollector(ast.NodeVisitor):
    """Collect (dotted target, lineno, lazy) triples from one module.

    Depth counts enclosing function bodies only: class bodies execute at
    import time, so imports there stay eager."""

    def __init__(self, mod_name: str, is_package: bool) -> None:
        self.mod_name = mod_name
        self.is_package = is_package
        self.depth = 0
        self.found: list[tuple[str, int, bool]] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.depth += 1
        self.generic_visit(node)
        self.depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]
    visit_Lambda = visit_FunctionDef  # type: ignore[assignment]

    def visit_If(self, node: ast.If) -> None:
        # `if TYPE_CHECKING:` bodies never execute at runtime — imports
        # there are type-only and contribute no edge (eager OR lazy).
        if _is_type_checking(node.test):
            for stmt in node.orelse:
                self.visit(stmt)
            return
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.found.append((alias.name, node.lineno, self.depth > 0))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level == 0:
            base = node.module or ""
        else:
            # relative import: resolve against this module's package
            parts = self.mod_name.split(".")
            if not self.is_package:
                parts = parts[:-1]  # the containing package
            parts = parts[: len(parts) - (node.level - 1)]
            base = ".".join(parts)
            if node.module:
                base = f"{base}.{node.module}" if base else node.module
        lazy = self.depth > 0
        self.found.append((base, node.lineno, lazy))
        # `from M import a`: when M.a is itself a module, the import
        # binds and executes it — add the submodule edge too (resolution
        # against the scanned module set happens in build_graph).
        for alias in node.names:
            if alias.name != "*":
                self.found.append((f"{base}.{alias.name}", node.lineno, lazy))


def _ancestors(mod: str, pkg_name: str) -> Iterator[str]:
    parts = mod.split(".")
    for i in range(1, len(parts)):
        anc = ".".join(parts[:i])
        if anc == pkg_name or anc.startswith(pkg_name + "."):
            yield anc


def build_graph(
    pkg_root: str, pkg_name: str, repo_root: str
) -> ImportGraph:
    """Scan the package and resolve every import into graph edges."""
    modules = scan_package(pkg_root, pkg_name, repo_root)
    g = ImportGraph(modules=modules)
    for name, info in modules.items():
        is_package = info.relpath.endswith("__init__.py")
        collector = _ImportCollector(name, is_package)
        collector.visit(info.tree)
        eager: dict[str, int] = {}
        lazy: dict[str, int] = {}
        eager_ext: dict[str, int] = {}
        lazy_ext: dict[str, int] = {}
        for target, lineno, is_lazy in collector.found:
            if target in modules:
                intra: list[str] = [target]
            elif target == pkg_name or target.startswith(pkg_name + "."):
                # `from M import name` where name is a symbol, or a
                # dangling intra-package path: credit the longest prefix
                # that IS a scanned module.
                parts = target.split(".")
                intra = []
                for i in range(len(parts) - 1, 0, -1):
                    prefix = ".".join(parts[:i])
                    if prefix in modules:
                        intra = [prefix]
                        break
            else:
                top = target.split(".")[0]
                if not top:
                    continue
                bucket = lazy_ext if is_lazy else eager_ext
                if top not in bucket or lineno < bucket[top]:
                    bucket[top] = lineno
                continue
            for t in intra:
                # importing a submodule executes every ancestor package
                for resolved in (t, *_ancestors(t, pkg_name)):
                    if resolved == name or resolved not in modules:
                        continue
                    bucket = lazy if is_lazy else eager
                    if resolved not in bucket or lineno < bucket[resolved]:
                        bucket[resolved] = lineno
        g.eager[name] = [Edge(t, ln) for t, ln in sorted(eager.items())]
        g.lazy[name] = [Edge(t, ln) for t, ln in sorted(lazy.items())]
        g.eager_external[name] = [
            Edge(t, ln) for t, ln in sorted(eager_ext.items())
        ]
        g.lazy_external[name] = [
            Edge(t, ln) for t, ln in sorted(lazy_ext.items())
        ]
    return g
