"""The selfcheck pass engine: one parse, one graph, six passes.

:func:`run_selfcheck` scans the package tree once
(:mod:`.graph`), hands the shared :class:`PassContext` to every
registered pass, and aggregates the findings into the repo's standard
:class:`~torchx_tpu.analyze.diagnostics.LintReport` (stable ``--json``,
human render, deterministic order). The baseline is applied by the
caller (:mod:`torchx_tpu.cli.cmd_selfcheck` / the legacy shim), so the
raw findings stay inspectable.

Everything here is jax-free and stdlib-only: ``tpx selfcheck`` runs on
the CLI fast path.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from torchx_tpu.analyze.diagnostics import Diagnostic, LintReport, Severity
from torchx_tpu.analyze.selfcheck import (
    clock,
    envreg,
    jaxfree,
    journal,
    locks,
    subproc,
)
from torchx_tpu.analyze.selfcheck.graph import (
    ImportGraph,
    ModuleInfo,
    build_graph,
)

#: packages/modules (paths relative to the package root) that must stay
#: jax-free — transitively, over eager imports
DEFAULT_JAX_FREE = (
    "cli",
    "supervisor",
    "control",
    "analyze",
    "fleet",
    "tune",
    "pipelines",
    "parallel/mesh_config.py",
    "obs/telemetry.py",
    "obs/slo.py",
    "obs/stitch.py",
    "obs/profile.py",
    "sim",
)


@dataclass
class SelfCheckConfig:
    """What to scan and which seams/annotations are sanctioned.

    Attributes:
        repo_root: directory findings are reported relative to.
        pkg_root: the package source dir (``<repo>/torchx_tpu``).
        pkg_name: dotted package name (``torchx_tpu``).
        jax_free: path prefixes (relative to ``pkg_root``) proven
            transitively jax-free by TPX901.
        sim_entry: the sim harness whose eager import closure derives
            the sim-hosted set for TPX910.
        sim_extra_roots: path prefixes additionally treated as
            sim-hosted (subsystems the sim drives through events, not
            imports).
        clock_seams: modules allowed to touch the wall clock (the
            injected-clock seams themselves).
        journal_seams: modules exempt from TPX93x (the durable-IO
            helpers).
        settings_path: the env registry module (exempt from TPX940).
        schedulers_dir: tree checked by TPX950.
        subprocess_seams: function names sanctioned to call subprocess
            inside ``schedulers/``.
        shared_class_suffixes: class-name patterns treated as
            thread-crossing by TPX92x.
    """

    repo_root: str
    pkg_root: str
    pkg_name: str = "torchx_tpu"
    jax_free: tuple[str, ...] = DEFAULT_JAX_FREE
    sim_entry: str = "sim/harness.py"
    sim_extra_roots: tuple[str, ...] = ("supervisor",)
    clock_seams: tuple[str, ...] = ("sim/clock.py", "util/times.py")
    journal_seams: tuple[str, ...] = ("util/jsonl.py",)
    settings_path: str = "settings.py"
    schedulers_dir: str = "schedulers"
    subprocess_seams: tuple[str, ...] = ("_run_cmd", "_popen")
    shared_class_suffixes: tuple[str, ...] = (
        "Daemon",
        "Reconciler",
        "Collector",
        "Monitor",
    )

    @classmethod
    def for_repo(cls, repo_root: Optional[str] = None) -> "SelfCheckConfig":
        """Default config for this repository (or the installed package
        when no repo root is given)."""
        if repo_root is None:
            import torchx_tpu

            pkg_root = os.path.dirname(os.path.abspath(torchx_tpu.__file__))
            repo_root = os.path.dirname(pkg_root)
        else:
            pkg_root = os.path.join(repo_root, "torchx_tpu")
        return cls(repo_root=repo_root, pkg_root=pkg_root)


@dataclass
class PassContext:
    """Shared state handed to every pass: the parsed tree + config."""

    config: SelfCheckConfig
    graph: ImportGraph
    _by_pkg_path: dict[str, ModuleInfo] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for info in self.graph.modules.values():
            self._by_pkg_path[self.pkg_path(info)] = info

    def pkg_path(self, info: ModuleInfo) -> str:
        """``info``'s path relative to the package root, ``/``-separated
        (the form config prefixes use)."""
        rel = os.path.relpath(info.path, self.config.pkg_root)
        return rel.replace(os.sep, "/")

    def module_at(self, pkg_path: str) -> Optional[ModuleInfo]:
        """The module at a package-relative path, or None."""
        return self._by_pkg_path.get(pkg_path)

    def all_modules(self) -> list[ModuleInfo]:
        """Every scanned module, in deterministic name order."""
        return [
            self.graph.modules[n] for n in sorted(self.graph.modules)
        ]

    def modules_under(self, *prefixes: str) -> list[ModuleInfo]:
        """Modules whose package-relative path matches a prefix (exact
        file, or anything under a directory prefix)."""
        out = []
        for info in self.all_modules():
            p = self.pkg_path(info)
            for prefix in prefixes:
                if p == prefix or p.startswith(prefix.rstrip("/") + "/"):
                    out.append(info)
                    break
        return out

    def jax_free_modules(self) -> list[ModuleInfo]:
        """Every module under a jax-free root."""
        return self.modules_under(*self.config.jax_free)

    def finding(
        self,
        code: str,
        severity: Severity,
        info: ModuleInfo,
        lineno: int,
        message: str,
        hint: str = "",
    ) -> Diagnostic:
        """One selfcheck diagnostic anchored to ``file:line`` (the
        ``field`` carries the location; baseline keys on file + code)."""
        return Diagnostic(
            code=code,
            severity=severity,
            message=message,
            field=f"{info.relpath}:{lineno}",
            hint=hint,
        )


#: pass name -> callable; run order = table order (also the docs order)
PASSES: dict[str, Callable[[PassContext], list[Diagnostic]]] = {
    "jax-free": jaxfree.check,
    "clock": clock.check,
    "locks": locks.check,
    "journal": journal.check,
    "env": envreg.check,
    "subprocess": subproc.check,
}

#: the subset equivalent to the retired scripts/lint_internal.py rules
LEGACY_PASSES = ("jax-free", "clock", "subprocess")


def run_selfcheck(
    config: Optional[SelfCheckConfig] = None,
    passes: Optional[tuple[str, ...]] = None,
    only_files: Optional[set[str]] = None,
) -> LintReport:
    """Run the analyzer and return the RAW report (baseline not yet
    applied).

    Args:
        config: what to scan; defaults to this repository.
        passes: subset of :data:`PASSES` names to run (default: all).
        only_files: when given, keep only findings anchored in these
            repo-relative files (the ``--changed-only`` filter) — the
            graph is still built over the whole tree, so transitive
            proofs stay whole-program.
    """
    config = config or SelfCheckConfig.for_repo()
    unknown = set(passes or ()) - set(PASSES)
    if unknown:
        raise ValueError(f"unknown selfcheck pass(es): {sorted(unknown)}")
    graph = build_graph(config.pkg_root, config.pkg_name, config.repo_root)
    ctx = PassContext(config=config, graph=graph)
    report = LintReport(target="torchx_tpu selfcheck")
    for name in passes or tuple(PASSES):
        report.extend(PASSES[name](ctx))
    if only_files is not None:
        report.diagnostics = [
            d
            for d in report.diagnostics
            if (d.field or "").rsplit(":", 1)[0] in only_files
        ]
    report.sort()
    return report
