"""TPX901 — the transitive jax-free proof.

The jax-free layers (``cli/``, ``supervisor/``, ``control/``, ...) must
never import jax *eagerly*, directly or through any chain of eager
intra-package imports: ``tpx --help`` and the client-side supervisor run
on machines without an accelerator runtime, and one eager import
regresses CLI latency by seconds. The old module-level lint
(``scripts/lint_internal.py`` rule 1) only looked at each hand-listed
file's own import statements — a jax-free module importing a module that
imports jax slipped through. This pass walks the whole eager import
graph and reports the shortest offending chain as evidence.

Function-local (lazy) imports remain the sanctioned escape hatch and are
never walked.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING

from torchx_tpu.analyze.diagnostics import Diagnostic, Severity

if TYPE_CHECKING:
    from torchx_tpu.analyze.selfcheck.engine import PassContext

CODE = "TPX901"


def module_level_jax_imports(tree: ast.Module) -> list[tuple[int, str]]:
    """Module-level ``import jax`` / ``from jax ...`` sites in one parsed
    module — the single-file primitive behind the legacy shim
    (``scripts/lint_internal.py check_jax_free``). Returns
    ``(lineno, statement)`` pairs."""

    sites: list[tuple[int, str]] = []

    class V(ast.NodeVisitor):
        depth = 0

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

        def visit_Import(self, node: ast.Import) -> None:
            if self.depth == 0:
                for alias in node.names:
                    if alias.name == "jax" or alias.name.startswith("jax."):
                        sites.append((node.lineno, f"import {alias.name}"))

        def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
            if (
                self.depth == 0
                and node.module
                and (node.module == "jax" or node.module.startswith("jax."))
            ):
                sites.append((node.lineno, f"from {node.module} import ..."))

    V().visit(tree)
    return sites


def check(ctx: "PassContext") -> list[Diagnostic]:
    """Prove every module under a jax-free root stays jax-free
    transitively over eager imports."""
    out: list[Diagnostic] = []
    g = ctx.graph
    for info in ctx.jax_free_modules():
        direct = [
            e for e in g.eager_external.get(info.name, []) if e.target == "jax"
        ]
        if direct:
            out.append(
                ctx.finding(
                    CODE,
                    Severity.ERROR,
                    info,
                    direct[0].lineno,
                    "module-level jax import in a jax-free layer",
                    hint="import jax inside the function that needs it",
                )
            )
            continue
        for mod in sorted(g.eager_closure(info.name) - {info.name}):
            jax_edges = [
                e for e in g.eager_external.get(mod, []) if e.target == "jax"
            ]
            if not jax_edges:
                continue
            chain = g.eager_chain(info.name, mod) or [info.name, mod]
            rendered = " -> ".join(
                g.modules[m].relpath if m in g.modules else m for m in chain
            )
            entry = g.first_eager_edge(info.name, chain[1])
            out.append(
                ctx.finding(
                    CODE,
                    Severity.ERROR,
                    info,
                    entry.lineno if entry else 1,
                    f"jax-free layer transitively imports jax: {rendered}"
                    f" -> jax (jax imported at"
                    f" {g.modules[mod].relpath}:{jax_edges[0].lineno})",
                    hint=(
                        "make the first edge of the chain a function-local"
                        " import, or move the jax dependency out of the"
                        " eagerly-imported module"
                    ),
                )
            )
            break  # one chain per module is enough evidence
    return out
