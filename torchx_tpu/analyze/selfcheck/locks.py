"""TPX920/TPX921 — lock discipline for thread-crossing classes.

The threaded control plane (reconciler, telemetry collector, control
daemon, serve engine) shares instance state across threads. A class
whose instances cross a thread boundary must guard mutable attribute
writes with its lock: an unguarded ``self.x = ...`` racing a reader on
another thread is the exact bug class the step-down incidents in the
gang-scheduling literature trace back to.

A class is **thread-crossing** when any of:

* one of its own methods spawns ``threading.Thread(target=self.<m>)``
  (the instance's bound method runs on another thread) — the evidence
  chain in the diagnostic names this site;
* its name matches a known shared-service suffix (``Daemon``,
  ``Reconciler``, ``Collector``, ``Monitor``, ...);
* its ``class`` line (or the line above) carries a ``# tpx: shared``
  annotation.

For a thread-crossing class:

* **TPX921** (warning): the class allocates no lock at all (no
  ``self._x = threading.Lock()/RLock()/Condition()``) — there is nothing
  to guard with.
* **TPX920** (error): a mutable attribute write outside ``__init__``
  (construction happens-before the thread starts and is exempt) is not
  enclosed in ``with self.<lock>:``.

Heuristic by design: the baseline file is the triage mechanism for
sites a human has judged benign (e.g. writes that happen strictly
before the thread is started).
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Optional

from torchx_tpu.analyze.diagnostics import Diagnostic, Severity

if TYPE_CHECKING:
    from torchx_tpu.analyze.selfcheck.engine import PassContext
    from torchx_tpu.analyze.selfcheck.graph import ModuleInfo

CODE_UNGUARDED = "TPX920"
CODE_NO_LOCK = "TPX921"

SHARED_ANNOTATION = "# tpx: shared"

#: ``threading`` factories whose result counts as a guard
_LOCK_FACTORIES = ("Lock", "RLock", "Condition")

#: attributes whose writes are structurally safe: the lock itself is
#: assigned unguarded by definition, and thread/daemon handles are
#: written before the thread they name exists
_EXEMPT_ATTR_HINTS = ("lock", "cond", "mutex", "thread")


def _is_lock_factory(node: ast.expr) -> bool:
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES:
            return True
        if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
            return True
    return False


def _self_attr(node: ast.expr) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_thread_ctor(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return isinstance(fn.value, ast.Name) and fn.value.id == "threading"
    return isinstance(fn, ast.Name) and fn.id == "Thread"


class _ClassScan(ast.NodeVisitor):
    """One class body: lock attrs, thread-entry evidence, write sites.

    Run twice per class: the first sweep collects lock allocations (so a
    guard used in a method defined textually before ``__init__`` still
    resolves), the second records writes and guard coverage against the
    full lock set."""

    def __init__(self, known_locks: Optional[set[str]] = None) -> None:
        self.lock_attrs: set[str] = set(known_locks or ())
        #: (method, lineno) of a Thread(target=self.<m>) spawn
        self.thread_entries: list[tuple[str, int]] = []
        #: (attr, lineno, method, guarded)
        self.writes: list[tuple[str, int, str, bool]] = []
        self._method: Optional[str] = None
        self._guard_depth = 0

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        outer, self._method = self._method, node.name if self._method is None else self._method
        self.generic_visit(node)
        self._method = outer

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_With(self, node: ast.With) -> None:
        guards = sum(
            1
            for item in node.items
            if (attr := _self_attr(item.context_expr)) is not None
            and (
                attr in self.lock_attrs
                or any(h in attr.lower() for h in ("lock", "cond", "mutex"))
            )
        )
        self._guard_depth += guards
        self.generic_visit(node)
        self._guard_depth -= guards

    def visit_Call(self, node: ast.Call) -> None:
        if _is_thread_ctor(node) and self._method is not None:
            for kw in node.keywords:
                if kw.arg == "target" and (m := _self_attr(kw.value)):
                    self.thread_entries.append((m, node.lineno))
        self.generic_visit(node)

    def _record_write(self, target: ast.expr, lineno: int) -> None:
        attr = _self_attr(target)
        if attr is None or self._method is None:
            return
        self.writes.append(
            (attr, lineno, self._method, self._guard_depth > 0)
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    self._record_write(elt, node.lineno)
            else:
                self._record_write(t, node.lineno)
        # lock allocation: self.<x> = threading.Lock()
        if _is_lock_factory(node.value):
            for t in node.targets:
                if (attr := _self_attr(t)) is not None:
                    self.lock_attrs.add(attr)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, node.lineno)
            if _is_lock_factory(node.value) and (
                attr := _self_attr(node.target)
            ):
                self.lock_attrs.add(attr)
        self.generic_visit(node)


def _is_annotated_shared(info: "ModuleInfo", node: ast.ClassDef) -> bool:
    lines = info.source_lines()
    for lineno in (node.lineno, node.lineno - 1):
        if 1 <= lineno <= len(lines) and SHARED_ANNOTATION in lines[lineno - 1]:
            return True
    return False


def _classes(tree: ast.Module) -> list[ast.ClassDef]:
    out: list[ast.ClassDef] = []

    class V(ast.NodeVisitor):
        def visit_ClassDef(self, node: ast.ClassDef) -> None:
            out.append(node)
            self.generic_visit(node)

    V().visit(tree)
    return out


def check(ctx: "PassContext") -> list[Diagnostic]:
    """Flag unguarded shared-state writes in thread-crossing classes."""
    out: list[Diagnostic] = []
    for info in ctx.all_modules():
        for cls in _classes(info.tree):
            prescan = _ClassScan()
            for stmt in cls.body:
                prescan.visit(stmt)
            scan = _ClassScan(known_locks=prescan.lock_attrs)
            for stmt in cls.body:
                scan.visit(stmt)
            evidence: Optional[str] = None
            if scan.thread_entries:
                m, ln = scan.thread_entries[0]
                evidence = (
                    f"Thread(target=self.{m}) at {info.relpath}:{ln}"
                )
            elif not cls.name.startswith("_") and any(
                cls.name.endswith(suffix)
                for suffix in ctx.config.shared_class_suffixes
            ):
                # private helper classes (AST visitors, local accumulators)
                # are not shared services even when the suffix matches
                evidence = f"class name matches shared-service pattern {cls.name!r}"
            elif _is_annotated_shared(info, cls):
                evidence = "annotated '# tpx: shared'"
            if evidence is None:
                continue
            if not scan.lock_attrs:
                out.append(
                    ctx.finding(
                        CODE_NO_LOCK,
                        Severity.WARNING,
                        info,
                        cls.lineno,
                        f"thread-crossing class {cls.name} ({evidence})"
                        " allocates no lock; its mutable state cannot be"
                        " guarded",
                        hint="allocate self._lock = threading.Lock() in"
                        " __init__ and guard every cross-thread write",
                    )
                )
                continue
            for attr, lineno, method, guarded in scan.writes:
                if guarded or method == "__init__":
                    continue
                if attr in scan.lock_attrs or any(
                    h in attr.lower() for h in _EXEMPT_ATTR_HINTS
                ):
                    continue
                out.append(
                    ctx.finding(
                        CODE_UNGUARDED,
                        Severity.ERROR,
                        info,
                        lineno,
                        f"unguarded write to self.{attr} in"
                        f" {cls.name}.{method}; instances cross threads"
                        f" ({evidence})",
                        hint=f"wrap the write in `with self."
                        f"{sorted(scan.lock_attrs)[0]}:`",
                    )
                )
    return out
