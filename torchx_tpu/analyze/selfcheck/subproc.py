"""TPX950 — scheduler subprocess calls go through the resilient seam.

Raw ``subprocess.run/Popen/check_*/call`` in ``schedulers/`` bypasses
the retry/circuit-breaker wrapper (:mod:`torchx_tpu.resilience.call`):
one un-retried ``gcloud`` 503 then surfaces as a user-visible submit
failure. The only sanctioned call sites are the ``_run_cmd`` methods
(the seam each backend funnels through) and the local scheduler's
``_popen`` (data-plane replica spawn, not a control-plane call).

This is the old lint's rule 2 (``scripts/lint_internal.py``) rehosted
on the pass engine unchanged in semantics.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING

from torchx_tpu.analyze.diagnostics import Diagnostic, Severity

if TYPE_CHECKING:
    from torchx_tpu.analyze.selfcheck.engine import PassContext

CODE = "TPX950"

SUBPROCESS_CALLS = ("run", "Popen", "check_call", "check_output", "call")


def raw_subprocess_sites(
    tree: ast.Module, seam_funcs: tuple[str, ...]
) -> list[tuple[int, str]]:
    """``(lineno, call)`` for raw subprocess sites outside the seam
    functions — the single-file primitive behind the legacy shim."""
    sites: list[tuple[int, str]] = []

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.stack: list[str] = []

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

        def visit_Call(self, node: ast.Call) -> None:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "subprocess"
                and fn.attr in SUBPROCESS_CALLS
                and not any(f in seam_funcs for f in self.stack)
            ):
                sites.append((node.lineno, f"subprocess.{fn.attr}"))
            self.generic_visit(node)

    V().visit(tree)
    return sites


def check(ctx: "PassContext") -> list[Diagnostic]:
    """Flag raw subprocess sites in every ``schedulers/`` module."""
    out: list[Diagnostic] = []
    seams = ctx.config.subprocess_seams
    for info in ctx.modules_under(ctx.config.schedulers_dir):
        for lineno, call in raw_subprocess_sites(info.tree, seams):
            out.append(
                ctx.finding(
                    CODE,
                    Severity.ERROR,
                    info,
                    lineno,
                    f"raw {call} in schedulers/ outside the"
                    f" {'/'.join(seams)} seam",
                    hint="route it through the backend's resilient"
                    " _run_cmd",
                )
            )
    return out
