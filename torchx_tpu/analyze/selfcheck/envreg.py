"""TPX940 — the environment-variable registry.

``torchx_tpu/settings.py`` is the central registry of every ``TPX_*``
environment variable the framework reads or writes: the docs, the
preflight env rules (TPX202) and the schedulers' injection tables are
all generated against it. A raw string literal (``os.environ.get(
"TPX_FOO")``) elsewhere bypasses the registry — the knob becomes
undocumented, unflagged by TPX202, and invisible to grep-by-constant.

The pass flags any ``os.environ[...]`` subscript (read or write),
``os.environ.get/setdefault/pop(...)`` and ``os.getenv(...)`` whose key
is a string literal starting with ``TPX`` in any module other than
``settings.py``. Access through a named constant (``settings.ENV_*``)
is invisible to the pass by construction — that is the sanctioned
route.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING

from torchx_tpu.analyze.diagnostics import Diagnostic, Severity

if TYPE_CHECKING:
    from torchx_tpu.analyze.selfcheck.engine import PassContext

CODE = "TPX940"

_ENV_METHODS = ("get", "setdefault", "pop")


def _is_environ(node: ast.expr) -> bool:
    """``os.environ`` or a bare ``environ`` name."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id == "environ"


def _tpx_literal(node: ast.expr) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith("TPX"):
            return node.value
    return ""


def env_literal_sites(tree: ast.Module) -> list[tuple[int, str]]:
    """(lineno, key) pairs for raw ``TPX*`` env-literal access in one
    parsed module."""
    sites: list[tuple[int, str]] = []

    class V(ast.NodeVisitor):
        def visit_Subscript(self, node: ast.Subscript) -> None:
            if _is_environ(node.value) and (key := _tpx_literal(node.slice)):
                sites.append((node.lineno, key))
            self.generic_visit(node)

        def visit_Call(self, node: ast.Call) -> None:
            fn = node.func
            key = _tpx_literal(node.args[0]) if node.args else ""
            if key:
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _ENV_METHODS
                    and _is_environ(fn.value)
                ):
                    sites.append((node.lineno, key))
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "getenv"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "os"
                ):
                    sites.append((node.lineno, key))
                elif isinstance(fn, ast.Name) and fn.id == "getenv":
                    sites.append((node.lineno, key))
            self.generic_visit(node)

    V().visit(tree)
    return sites


def check(ctx: "PassContext") -> list[Diagnostic]:
    """Flag raw TPX env literals everywhere but the registry module."""
    out: list[Diagnostic] = []
    registry = ctx.module_at(ctx.config.settings_path)
    for info in ctx.all_modules():
        if registry is not None and info.name == registry.name:
            continue
        for lineno, key in env_literal_sites(info.tree):
            out.append(
                ctx.finding(
                    CODE,
                    Severity.WARNING,
                    info,
                    lineno,
                    f"raw env literal {key!r} outside settings.py bypasses"
                    " the env registry",
                    hint=(
                        "add/reuse an ENV_* constant in"
                        " torchx_tpu/settings.py and read through it"
                    ),
                )
            )
    return out
