"""TPX930/931/932 — crash-safe journaling discipline.

Every durable decision in the launcher travels through JSONL journals
(attempt ledger, control store, tune journal, pipeline journal, obs
sinks) and small JSON state files (manifests, calibration tables,
discovery files). The durability contract is uniform:

* **TPX930** (error): an append handle on a ``*.jsonl`` path must
  flush + ``os.fsync`` before the write can be claimed durable — a
  buffered append lost in a crash silently rewrites history on replay.
* **TPX931** (warning): a state-file rewrite (``open(path.json, "w")``)
  must go through tmp + fsync + ``os.replace`` so concurrent readers
  (and crash recovery) never observe a torn file.
* **TPX932** (warning): a journal *reader* must route through the
  torn-line-holdback helper (:func:`torchx_tpu.util.jsonl.iter_jsonl`)
  instead of hand-rolling ``json.loads`` per line — a killed writer
  leaves one torn final line, and ad-hoc readers get the holdback
  subtly wrong (skip-all-garbage vs hold-back-tail).

Analysis granularity is the innermost enclosing function: the open, the
fsync and the replace are expected to be visible in one function body
(that is how every sanctioned site in the repo is written). A path is
journal-shaped when its expression mentions ``.jsonl`` or ``journal``.
:mod:`torchx_tpu.util.jsonl` is the sanctioned seam and is exempt.
"""

from __future__ import annotations

import ast
from typing import TYPE_CHECKING, Optional

from torchx_tpu.analyze.diagnostics import Diagnostic, Severity

if TYPE_CHECKING:
    from torchx_tpu.analyze.selfcheck.engine import PassContext

CODE_APPEND_FSYNC = "TPX930"
CODE_REWRITE_ATOMIC = "TPX931"
CODE_READER_HOLDBACK = "TPX932"

#: calls that mark a function as routing through the sanctioned helpers
HELPER_NAMES = ("iter_jsonl", "read_jsonl", "append_jsonl", "rewrite_json")


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _open_mode(node: ast.Call) -> str:
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        if isinstance(node.args[1].value, str):
            return node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            if isinstance(kw.value.value, str):
                return kw.value.value
    return "r"


def _target_text(node: ast.Call) -> str:
    if not node.args:
        return ""
    try:
        return ast.unparse(node.args[0]).lower()
    except Exception:  # noqa: BLE001 - unparse of exotic nodes
        return ""


def _literal_target(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant):
        if isinstance(node.args[0].value, str):
            return node.args[0].value
    return None


class _FuncFacts(ast.NodeVisitor):
    """Everything this pass needs to know about one function body."""

    def __init__(self) -> None:
        self.opens: list[ast.Call] = []
        self.has_fsync = False
        self.has_replace = False
        self.has_loads = False
        self.uses_helper = False

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return  # nested functions are analyzed on their own

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        if isinstance(node.func, ast.Name) and name == "open":
            self.opens.append(node)
        elif name == "fsync":
            self.has_fsync = True
        elif name in ("replace", "rename"):
            self.has_replace = True
        elif name == "loads":
            self.has_loads = True
        elif name in HELPER_NAMES:
            self.uses_helper = True
        self.generic_visit(node)


def _functions(tree: ast.Module) -> list[ast.FunctionDef]:
    out: list[ast.FunctionDef] = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            out.append(node)
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    V().visit(tree)
    return out


def journal_sites(
    tree: ast.Module,
) -> list[tuple[str, int, str]]:
    """(code, lineno, detail) findings for one parsed module."""
    sites: list[tuple[str, int, str]] = []
    for fn in _functions(tree):
        facts = _FuncFacts()
        for stmt in fn.body:
            facts.visit(stmt)
        for call in facts.opens:
            mode = _open_mode(call)
            text = _target_text(call)
            journalish = ".jsonl" in text or "journal" in text
            if "a" in mode and journalish and not facts.has_fsync:
                sites.append(
                    (
                        CODE_APPEND_FSYNC,
                        call.lineno,
                        f"append handle on a journal path in {fn.name}()"
                        " with no os.fsync before the write is claimed"
                        " durable",
                    )
                )
            elif "w" in mode and not journalish:
                lit = _literal_target(call)
                if (
                    lit is not None
                    and lit.endswith(".json")
                    and not facts.has_replace
                ):
                    sites.append(
                        (
                            CODE_REWRITE_ATOMIC,
                            call.lineno,
                            f"state-file rewrite of {lit!r} in {fn.name}()"
                            " without tmp + fsync + os.replace; a crash"
                            " mid-write leaves a torn file",
                        )
                    )
            elif (
                "w" not in mode
                and "a" not in mode
                and "x" not in mode
                and journalish
                and facts.has_loads
                and not facts.uses_helper
            ):
                sites.append(
                    (
                        CODE_READER_HOLDBACK,
                        call.lineno,
                        f"hand-rolled journal reader in {fn.name}();"
                        " torn-line holdback must come from one helper",
                    )
                )
    return sites


_HINTS = {
    CODE_APPEND_FSYNC: (
        "append through util.jsonl.append_jsonl (O_APPEND + flush +"
        " os.fsync), or fsync the handle before returning"
    ),
    CODE_REWRITE_ATOMIC: (
        "write through util.jsonl.rewrite_json (tmp + fsync +"
        " os.replace)"
    ),
    CODE_READER_HOLDBACK: (
        "read through util.jsonl.iter_jsonl (skips exactly the torn"
        " final line)"
    ),
}


def check(ctx: "PassContext") -> list[Diagnostic]:
    """Apply the journaling rules to every module except the helper
    seam itself."""
    out: list[Diagnostic] = []
    exempt = {
        ctx.module_at(p).name
        for p in ctx.config.journal_seams
        if ctx.module_at(p) is not None
    }
    severities = {
        CODE_APPEND_FSYNC: Severity.ERROR,
        CODE_REWRITE_ATOMIC: Severity.WARNING,
        CODE_READER_HOLDBACK: Severity.WARNING,
    }
    for info in ctx.all_modules():
        if info.name in exempt:
            continue
        for code, lineno, detail in journal_sites(info.tree):
            out.append(
                ctx.finding(
                    code,
                    severities[code],
                    info,
                    lineno,
                    detail,
                    hint=_HINTS[code],
                )
            )
    return out
