"""Jax-free parallelism-plan IR for the deep preflight analyzer.

A :class:`ParallelPlan` is everything the static analyzer needs to reason
about one role's training (or serving) step *without importing jax*: the
model shape, the resolved mesh axis sizes, the batch geometry, and the
physical topology (device count, chips per slice, HBM per chip). It is
assembled purely from launcher-side facts — the role's arg list (the
trainer CLI flags after the ``spmd_main`` ``--`` separator), the
``TPX_MESH`` env override, ``parse_mesh_spec``, and the role's
:class:`~torchx_tpu.specs.api.TpuSlice` resource (or the CPU-sim
``--xla_force_host_platform_device_count`` flag).

The model shapes are a deliberately duplicated, arithmetic-only mirror of
``models/llama.py`` / ``models/moe.py`` (which import jax and therefore
cannot be used at lint time). Honesty of the mirror is enforced by
``tests/test_explain.py::test_model_shapes_match_jax_configs``, which
cross-checks ``param_count`` against the real configs where jax is
available.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Optional

from torchx_tpu import settings
from torchx_tpu.parallel.mesh_config import (
    AXES,
    MeshConfig,
    parse_mesh_spec,
)
from torchx_tpu.specs.api import Role

GIB = 1024**3

#: HBM budget assumed for roles whose topology carries no generation info
#: (CPU-sim roles, bare-process entrypoints) — v5e-class, the smallest
#: current-generation part, so the fit verdict errs conservative.
DEFAULT_HBM_BYTES = 16 * GIB

#: Entrypoint modules known to pin gather/combine outputs with explicit
#: ``with_sharding_constraint`` (models/llama.py forward_features), which
#: keeps expert-parallel meshes free of involuntary full remat. Mirrors
#: ``rules.REMAT_SAFE_MODULES`` (kept there for the heuristic fallback).
REMAT_SAFE_MODULES = ("torchx_tpu.examples.train_llama",)

#: Serve-shaped entrypoint modules: no optimizer state, KV pool instead
#: of activations.
SERVE_MODULES = ("torchx_tpu.apps.generate_server",)


class PlanError(ValueError):
    """A role *is* plan-shaped but the plan is inconsistent (e.g. the mesh
    spec cannot resolve onto the role's device count) — surfaced as a
    TPX703 error rather than silently skipping deep preflight."""


@dataclasses.dataclass(frozen=True)
class ModelShape:
    """Arithmetic-only model shape (jax-free mirror of LlamaConfig /
    MoEConfig — see the module docstring for the honesty contract)."""

    name: str
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    ffn_dim: int
    max_seq: int
    dtype_bytes: int
    tie_embeddings: bool = False
    loss_chunk: int = 512
    n_experts: int = 0  # 0 = dense
    top_k: int = 0
    capacity_factor: float = 2.0

    @property
    def head_dim(self) -> int:
        """Per-attention-head width (``dim / n_heads``)."""
        return self.dim // self.n_heads

    @property
    def is_moe(self) -> bool:
        """True when the FFN is a mixture-of-experts block."""
        return self.n_experts > 0

    def param_count(self) -> int:
        """Exact parameter count (mirror of LlamaConfig.param_count +
        the MoEConfig expert/router delta)."""
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        hd = self.head_dim
        per_layer = (
            d * self.n_heads * hd  # wq
            + 2 * d * self.n_kv_heads * hd  # wk, wv
            + self.n_heads * hd * d  # wo
            + 3 * d * f  # gate, up, down
            + 2 * d  # norms
        )
        total = self.n_layers * per_layer + v * d + d  # embed + final norm
        if not self.tie_embeddings:
            total += d * v
        if self.is_moe:
            ffn = 3 * d * f
            total += self.n_layers * (
                (self.n_experts - 1) * ffn + d * self.n_experts
            )
        return total

    def active_param_count(self) -> int:
        """Params touched per token (MoE: top_k experts; dense: all)."""
        if not self.is_moe:
            return self.param_count()
        d, f = self.dim, self.ffn_dim
        ffn = 3 * d * f
        dense = dataclasses.replace(self, n_experts=0, top_k=0).param_count()
        return dense + self.n_layers * (
            (self.top_k - 1) * ffn + d * self.n_experts
        )

    def flops_per_token(self) -> int:
        """Training FLOPs/token, fwd+bwd (mirror of
        ``LlamaConfig.flops_per_token`` / ``MoEConfig.flops_per_token``):
        ``6 * N + 12 * layers * dim * seq`` with N the ACTIVE parameter
        count (MoE counts only the top_k routed experts) — the MFU
        denominator the step profiler's roofline accounting reuses."""
        attn = 12 * self.n_layers * self.dim * self.max_seq
        return 6 * self.active_param_count() + attn

    def to_dict(self) -> dict:
        """Stable JSON form for the explain report."""
        return {
            "name": self.name,
            "params": self.param_count(),
            "active_params": self.active_param_count(),
            "dim": self.dim,
            "n_layers": self.n_layers,
            "n_heads": self.n_heads,
            "n_kv_heads": self.n_kv_heads,
            "ffn_dim": self.ffn_dim,
            "vocab_size": self.vocab_size,
            "dtype_bytes": self.dtype_bytes,
            "n_experts": self.n_experts,
            "top_k": self.top_k,
        }


#: Name -> shape for every builtin trainer/server ``--config`` choice.
#: The dtype_bytes mirror the preset dtypes (tiny shapes train in f32).
MODEL_SHAPES: dict[str, ModelShape] = {
    "tiny": ModelShape(
        name="tiny",
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq=128,
        dtype_bytes=4,
    ),
    "llama3_1b": ModelShape(
        name="llama3_1b",
        vocab_size=128256,
        dim=2048,
        n_layers=16,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=8192,
        max_seq=8192,
        dtype_bytes=2,
        tie_embeddings=True,
    ),
    "llama3_8b": ModelShape(
        name="llama3_8b",
        vocab_size=128256,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14336,
        max_seq=8192,
        dtype_bytes=2,
    ),
    "moe_tiny": ModelShape(
        name="moe_tiny",
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq=128,
        dtype_bytes=4,
        n_experts=4,
        top_k=2,
    ),
    "mixtral_8x7b": ModelShape(
        name="mixtral_8x7b",
        vocab_size=32000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14336,
        max_seq=8192,
        dtype_bytes=2,
        n_experts=8,
        top_k=2,
    ),
}


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """One role's statically-resolved parallelism plan.

    ``sizes`` are the concrete per-axis mesh sizes (every wildcard
    resolved); ``devices`` is the total device count the plan is laid out
    on. ``hbm_source`` records where the per-chip budget came from:
    ``"tpu_slice"`` (the role's TpuSlice generation), ``"override"``
    (caller-provided) or ``"assumed"`` (:data:`DEFAULT_HBM_BYTES`).
    """

    role: str
    model: ModelShape
    mesh_spec: str
    sizes: dict[str, int]
    batch: int
    seq: int
    remat_policy: str = "full"
    int8: bool = False
    ring_attention: bool = False
    serve: bool = False
    max_batch: int = 16  # serve decode slots
    serve_role: str = "unified"  # disaggregated serving: prefill | decode
    prefix_reserve: float = 0.0  # prefix-cache block reserve fraction
    devices: int = 1
    slices: int = 1
    chips_per_slice: int = 1
    hbm_bytes_per_chip: int = DEFAULT_HBM_BYTES
    hbm_source: str = "assumed"
    module: str = ""
    accelerator: str = ""
    remat_safe: bool = False
    notes: tuple[str, ...] = ()

    def axis(self, name: str) -> int:
        """Resolved size of one mesh axis (1 when absent)."""
        return int(self.sizes.get(name, 1))

    @property
    def data_shards(self) -> int:
        """Batch-dimension sharding factor (dp * fsdp)."""
        return self.axis("dp") * self.axis("fsdp")

    def to_dict(self) -> dict:
        """Stable JSON form for the explain report."""
        return {
            "role": self.role,
            "config": self.model.name,
            "mesh": {a: self.axis(a) for a in AXES},
            "batch": self.batch,
            "seq": self.seq,
            "remat_policy": self.remat_policy,
            "int8": self.int8,
            "ring_attention": self.ring_attention,
            "serve": self.serve,
            "serve_role": self.serve_role,
            "prefix_reserve": self.prefix_reserve,
            "devices": self.devices,
            "slices": self.slices,
            "chips_per_slice": self.chips_per_slice,
            "hbm_bytes_per_chip": self.hbm_bytes_per_chip,
            "hbm_source": self.hbm_source,
            "module": self.module,
            "accelerator": self.accelerator,
            "remat_safe": self.remat_safe,
            "model": self.model.to_dict(),
            "notes": list(self.notes),
        }


_HOST_DEVICE_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def _script_argv(role: Role) -> tuple[str, list[str]]:
    """(entry module, trainer argv) recovered from a role's arg list.

    Handles the ``dist.spmd`` shape (``-m torchx_tpu.apps.spmd_main ...
    -m <user module> -- <script args>``, where the user module is the
    *last* ``-m``/``--script`` value before the ``--`` separator) and the
    direct ``python -m <module> <args>`` shape.
    """
    args = [str(a) for a in role.args]
    module = ""
    if "--" in args:
        sep = args.index("--")
        head, tail = args[:sep], args[sep + 1 :]
    else:
        head, tail = args, []
    i = 0
    last_module_at = -1
    while i < len(head):
        if head[i] in ("-m", "--script") and i + 1 < len(head):
            module = head[i + 1]
            last_module_at = i + 1
            i += 2
            continue
        i += 1
    if tail:
        return module, tail
    # direct `python -m module flags...`: the flags follow the module
    if last_module_at >= 0:
        return module, head[last_module_at + 1 :]
    return module, []


def _flag_values(argv: list[str]) -> tuple[dict[str, str], set[str]]:
    """Last-wins ``--flag value`` / ``--flag=value`` map + the set of
    bare flags seen (for store_true options)."""
    values: dict[str, str] = {}
    bare: set[str] = set()
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok.startswith("--"):
            if "=" in tok:
                k, _, v = tok.partition("=")
                values[k] = v
            elif i + 1 < len(argv) and not argv[i + 1].startswith("-"):
                values[tok] = argv[i + 1]
                bare.add(tok)
                i += 2
                continue
            else:
                bare.add(tok)
        i += 1
    return values, bare


def _role_topology(
    role: Role, devices_override: Optional[int]
) -> tuple[Optional[int], int, int, int, str, str, list[str]]:
    """(devices, slices, chips_per_slice, hbm_bytes, hbm_source,
    accelerator, notes) from the role's resource / CPU-sim env."""
    notes: list[str] = []
    tpu = getattr(role.resource, "tpu", None)
    replicas = max(1, int(getattr(role, "num_replicas", 1) or 1))
    if tpu is not None:
        # dist.spmd semantics: num_replicas = slices when a TPU resource
        # is set (components/dist.py), chips stay within one slice on ICI
        chips = int(tpu.chips)
        hbm = tpu.hbm_bytes_per_chip
        devices = chips * replicas
        return (
            devices_override or devices,
            replicas,
            chips,
            hbm,
            "tpu_slice",
            tpu.accelerator_type,
            notes,
        )
    m = _HOST_DEVICE_RE.search(str(role.env.get(settings.ENV_XLA_FLAGS, "")))
    if m:
        nproc = int(m.group(1))
        devices = nproc * replicas
        notes.append(
            f"CPU-sim topology: {replicas} process(es) x {nproc} host"
            f" devices; HBM budget assumed {DEFAULT_HBM_BYTES // GIB} GiB"
        )
        return (
            devices_override or devices,
            replicas,
            nproc,
            DEFAULT_HBM_BYTES,
            "assumed",
            "cpu-sim",
            notes,
        )
    notes.append(
        "no TPU resource or CPU-sim device count on the role; HBM budget"
        f" assumed {DEFAULT_HBM_BYTES // GIB} GiB"
    )
    return devices_override, 1, 1, DEFAULT_HBM_BYTES, "assumed", "", notes


def plan_from_role(
    role: Role,
    *,
    devices: Optional[int] = None,
    hbm_bytes: Optional[int] = None,
) -> Optional[ParallelPlan]:
    """Resolve a role into a :class:`ParallelPlan`, or None when the role
    is not plan-shaped (no recognizable ``--config``): the caller then
    falls back to the TPX110 heuristic or skips deep preflight.

    Raises :class:`PlanError` when the role *is* plan-shaped but
    inconsistent (mesh spec that cannot resolve onto the device count,
    unknown wildcard with no device information).
    """
    module, argv = _script_argv(role)
    if not argv and not module:
        return None
    values, bare = _flag_values(argv)
    config = values.get("--config")
    if config is None or config not in MODEL_SHAPES:
        return None
    model = MODEL_SHAPES[config]
    serve = any(m in module for m in SERVE_MODULES)

    # the trainer honors $TPX_MESH over --mesh (examples/train_llama.py)
    mesh_spec = str(
        role.env.get(settings.ENV_TPX_MESH) or values.get("--mesh") or ""
    )
    try:
        mesh_cfg = parse_mesh_spec(mesh_spec) if mesh_spec else MeshConfig()
    except ValueError as e:
        raise PlanError(f"--mesh {mesh_spec!r}: {e}") from e

    n_devices, slices, chips_per_slice, hbm, hbm_source, accel, notes = (
        _role_topology(role, devices)
    )
    if hbm_bytes is not None:
        hbm, hbm_source = int(hbm_bytes), "override"
    if n_devices is not None:
        try:
            sizes = mesh_cfg.resolve(n_devices)
        except ValueError as e:
            raise PlanError(
                f"mesh {mesh_spec or 'default'} does not fit the role's"
                f" {n_devices} device(s): {e}"
            ) from e
    else:
        # device count unknown (bare entrypoint): wildcards collapse to 1
        # and the plan covers exactly the fixed axes
        sizes = {a: getattr(mesh_cfg, a) for a in AXES}
        wild = [a for a, s in sizes.items() if s == -1]
        for a in wild:
            sizes[a] = 1
        if wild:
            notes.append(
                f"device count unknown; wildcard axes {wild} assumed 1"
            )
        n_devices = math.prod(sizes.values())
        chips_per_slice = n_devices

    remat_policy = values.get("--remat-policy", "full")
    if remat_policy == "auto":
        remat_policy = "dots"  # the trainer's auto-push floor

    safe = any(
        m in module or m in (role.entrypoint or "") for m in REMAT_SAFE_MODULES
    )
    return ParallelPlan(
        role=role.name,
        model=model,
        mesh_spec=mesh_spec,
        sizes={a: int(s) for a, s in sizes.items()},
        batch=int(values.get("--batch", values.get("--max-batch", 8) if serve else 8)),
        seq=int(values.get("--seq", model.max_seq if serve else 128)),
        remat_policy=remat_policy,
        int8=("--int8" in bare or "--int8" in values),
        ring_attention=("--ring-attention" in bare or "--ring-attention" in values),
        serve=serve,
        max_batch=int(values.get("--max-batch", 16)),
        serve_role=str(values.get("--serve-role", "unified")),
        prefix_reserve=float(values.get("--prefix-cache-reserve", 0.0)),
        devices=int(n_devices),
        slices=slices,
        chips_per_slice=int(chips_per_slice),
        hbm_bytes_per_chip=int(hbm),
        hbm_source=hbm_source,
        module=module,
        accelerator=accel,
        remat_safe=safe,
        notes=tuple(notes),
    )
