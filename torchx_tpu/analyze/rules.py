"""Built-in rules + the pluggable rule registry for the preflight analyzer.

Every rule is a callable ``(RuleContext) -> Iterable[Diagnostic]`` registered
under a stable name. The engine (:mod:`torchx_tpu.analyze.engine`) runs all
registered rules over one AppDef; plugins and tests can add their own with
:func:`register_rule` / the :func:`rule` decorator.

Code families (full table in docs/api/analyze.md):

* ``TPX00x`` component source (emitted via ``specs/file_linter.py``)
* ``TPX01x`` AppDef structure
* ``TPX1xx`` TPU topology / resources
* ``TPX2xx`` env vars / macros / ports / mounts
* ``TPX3xx`` scheduler capability fit
* ``TPX4xx`` supervisor / retry coherence
* ``TPX5xx`` control-plane resilience coherence
* ``TPX6xx`` control-daemon coherence
* ``TPX7xx`` deep preflight (static sharding / HBM / collective analysis)
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from string import Template
from typing import Any, Callable, Iterable, Iterator, Mapping, Optional

from torchx_tpu import settings as s
from torchx_tpu.analyze.diagnostics import Diagnostic, Severity
from torchx_tpu.schedulers.api import SchedulerCapabilities
from torchx_tpu.specs.api import (
    AppDef,
    CfgVal,
    RetryPolicy,
    Role,
    _TPU_GENERATIONS,
)
from torchx_tpu.supervisor.policy import SupervisorPolicy


@dataclass
class RuleContext:
    """Everything a rule may look at for one analyzer run.

    Attributes:
        app: the AppDef under analysis (never None).
        scheduler: target scheduler name, or None when linting
            scheduler-agnostically.
        cfg: resolved (or raw) run opts for the scheduler, may be empty.
        capabilities: the target scheduler's feature profile, or None when
            the backend is unknown (capability rules then skip).
        policy: supervisor policy for retry-coherence rules, or None.
    """

    app: AppDef
    scheduler: Optional[str] = None
    cfg: Optional[Mapping[str, CfgVal]] = None
    capabilities: Optional[SchedulerCapabilities] = None
    policy: Optional[SupervisorPolicy] = None


Rule = Callable[[RuleContext], Iterable[Diagnostic]]

_RULES: dict[str, Rule] = {}


def register_rule(name: str, fn: Rule) -> None:
    """Register (or replace) a rule under a stable name."""
    _RULES[name] = fn


def rule(name: str) -> Callable[[Rule], Rule]:
    """Decorator form of :func:`register_rule`."""

    def deco(fn: Rule) -> Rule:
        register_rule(name, fn)
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    """Snapshot of the registry (name -> rule), insertion-ordered."""
    return dict(_RULES)


# ---------------------------------------------------------------------------
# Env var ownership
# ---------------------------------------------------------------------------

#: Env vars the launcher injects into every replica: a role that sets one
#: corrupts the rendezvous/identity wiring — always an error.
LAUNCHER_OWNED_ENV = frozenset(
    {
        s.ENV_TPX_APP_ID,
        s.ENV_TPX_JOB_ID,
        s.ENV_TPX_REPLICA_ID,
        s.ENV_TPX_ROLE_NAME,
        s.ENV_TPX_NUM_REPLICAS,
        s.ENV_TPX_SLICE_ID,
        s.ENV_TPX_HOST_ID,
        s.ENV_TPX_HOSTS_PER_SLICE,
        s.ENV_TPX_MIN_REPLICAS,
        s.ENV_TPX_COORDINATOR_HOST,
        s.ENV_MEGASCALE_COORDINATOR_ADDRESS,
        s.ENV_MEGASCALE_NUM_SLICES,
        s.ENV_MEGASCALE_SLICE_ID,
        s.ENV_TPU_WORKER_ID,
        s.ENV_TPU_WORKER_HOSTNAMES,
    }
)

#: Reserved-prefix vars that are nonetheless legitimate user knobs (the
#: framework documents them as inputs); setting one is not even a warning.
USER_SETTABLE_ENV = frozenset(
    {
        s.ENV_TPX_SIMULATE_PREEMPTION_EXIT,
        s.ENV_TPX_RESUME_STEP,
        s.ENV_TPX_FUSED_NORM,
        s.ENV_TPX_ERROR_FILE,
        s.ENV_TPX_LOG_DIR,
        s.ENV_TPX_TRACE,
        s.ENV_TPX_TRACE_ID,
        s.ENV_TPX_PARENT_SPAN,
        s.ENV_TPX_EVENT_DESTINATION,
        s.ENV_TPX_OBS_DIR,
        s.ENV_TPX_NO_LINT,
        s.ENV_TPX_TRACKERS,
        s.ENV_TPX_PARENT_RUN_ID,
        s.ENV_TPX_INTERNAL_SESSION_ID,
        s.ENV_TPU_VISIBLE_CHIPS,
        s.ENV_TPU_PROCESS_BOUNDS,
        s.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS,
        s.ENV_TPU_SKIP_MDS_QUERY,
        "TPU_STDERR_LOG_LEVEL",
        "TPU_MIN_LOG_LEVEL",
        "TPU_LIBRARY_PATH",
    }
)

#: Prefixes the launcher considers reserved for platform wiring.
RESERVED_ENV_PREFIXES = ("TPX_", "TPU_", "MEGASCALE_")

#: Macro identifiers ``macros.Values.substitute`` knows how to resolve.
KNOWN_MACROS = frozenset(
    {"img_root", "app_id", "replica_id", "num_replicas", "coordinator_env"}
)


def unknown_macro_names(value: str) -> set[str]:
    """Identifiers in ``${...}``/``$...`` placeholders that are not launcher
    macros. ``$$`` escapes (runtime shell expansion) are ignored — that is
    the documented way to defer expansion to the replica's shell."""
    out: set[str] = set()
    for m in Template.pattern.finditer(value):
        name = m.group("named") or m.group("braced")
        if name and name not in KNOWN_MACROS:
            out.add(name)
    return out


def _tpu_roles(app: AppDef) -> Iterator[Role]:
    for role in app.roles:
        if role.resource is not None and role.resource.tpu is not None:
            yield role


# ---------------------------------------------------------------------------
# TPX01x — AppDef structure
# ---------------------------------------------------------------------------


@rule("structure")
def check_structure(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX010-TPX015: roles exist, are uniquely named, runnable and sized."""
    app = ctx.app
    if not app.roles:
        yield Diagnostic(
            code="TPX010",
            severity=Severity.ERROR,
            message=f"AppDef {app.name!r} has no roles",
            field="roles",
            hint="add at least one Role to the AppDef",
        )
        return
    seen: set[str] = set()
    for role in app.roles:
        if role.name in seen:
            yield Diagnostic(
                code="TPX014",
                severity=Severity.ERROR,
                role=role.name,
                field="name",
                message=f"duplicate role name {role.name!r}",
                hint="role names must be unique within an AppDef",
            )
        seen.add(role.name)
        if not role.entrypoint:
            yield Diagnostic(
                code="TPX011",
                severity=Severity.ERROR,
                role=role.name,
                field="entrypoint",
                message=f"role {role.name!r} has no entrypoint",
                hint="set Role.entrypoint to the command to run",
            )
        if role.num_replicas <= 0:
            yield Diagnostic(
                code="TPX012",
                severity=Severity.ERROR,
                role=role.name,
                field="num_replicas",
                message=f"num_replicas must be positive, got {role.num_replicas}",
                hint="set num_replicas >= 1",
            )
        if role.min_replicas is not None and not (
            0 < role.min_replicas <= role.num_replicas
        ):
            yield Diagnostic(
                code="TPX013",
                severity=Severity.ERROR,
                role=role.name,
                field="min_replicas",
                message=(
                    f"min_replicas={role.min_replicas} must satisfy"
                    f" 0 < min_replicas <= num_replicas={role.num_replicas}"
                ),
                hint="lower min_replicas or raise num_replicas",
            )
        if not role.image:
            yield Diagnostic(
                code="TPX015",
                severity=Severity.WARNING,
                role=role.name,
                field="image",
                message=f"role {role.name!r} has no image",
                hint=(
                    "container backends need an image; the local scheduler"
                    " treats it as a path root"
                ),
            )


# ---------------------------------------------------------------------------
# TPX1xx — TPU topology / resources
# ---------------------------------------------------------------------------


@rule("topology")
def check_topology(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX101-TPX103: slice sizes that exist, topology shapes that match the
    generation, and TPU chips kept out of ``resource.devices``."""
    for role in ctx.app.roles:
        res = role.resource
        if res is None:
            continue
        tpu = res.tpu
        if tpu is not None:
            info = _TPU_GENERATIONS[tpu.accelerator]
            single = info["single_host_chips"]
            per_vm = info["multi_host_vm_chips"]
            if tpu.chips > single and tpu.chips % per_vm:
                yield Diagnostic(
                    code="TPX101",
                    severity=Severity.ERROR,
                    role=role.name,
                    field="resource.tpu.chips",
                    message=(
                        f"no {tpu.accelerator} slice has {tpu.chips} chips:"
                        f" multi-host slices are built from {per_vm}-chip"
                        f" hosts (single-host max is {single})"
                    ),
                    hint=(
                        f"use a chip count <= {single} or a multiple of"
                        f" {per_vm} (e.g. {max(per_vm, tpu.chips // per_vm * per_vm)})"
                    ),
                )
            elif tpu.accelerator in ("v5e", "v6e") and tpu.chips > 256:
                yield Diagnostic(
                    code="TPX101",
                    severity=Severity.ERROR,
                    role=role.name,
                    field="resource.tpu.chips",
                    message=(
                        f"{tpu.accelerator} pods top out at 256 chips,"
                        f" got {tpu.chips}"
                    ),
                    hint="use num_replicas > 1 (multi-slice DCN) beyond one pod",
                )
            if tpu.topology:
                dims = tpu.topology.split("x")
                if tpu.accelerator in ("v5e", "v6e") and len(dims) != 2:
                    yield Diagnostic(
                        code="TPX102",
                        severity=Severity.ERROR,
                        role=role.name,
                        field="resource.tpu.topology",
                        message=(
                            f"{tpu.accelerator} slices are 2D meshes;"
                            f" topology {tpu.topology!r} has {len(dims)} dims"
                        ),
                        hint='use a 2D shape like "4x8"',
                    )
                elif tpu.accelerator in ("v4", "v5p") and len(dims) != 3:
                    yield Diagnostic(
                        code="TPX102",
                        severity=Severity.ERROR,
                        role=role.name,
                        field="resource.tpu.topology",
                        message=(
                            f"{tpu.accelerator} slices are 3D tori;"
                            f" topology {tpu.topology!r} has {len(dims)} dims"
                        ),
                        hint='use a 3D shape like "2x2x4"',
                    )
        for key in res.devices:
            if "tpu" in key.lower():
                yield Diagnostic(
                    code="TPX103",
                    severity=Severity.ERROR,
                    role=role.name,
                    field=f"resource.devices.{key}",
                    message=(
                        f"TPU chips do not go in resource.devices ({key!r});"
                        " they are allocated via resource.tpu"
                    ),
                    hint="set resource.tpu = TpuSlice(...) instead",
                )


#: Mesh axes of the trainer's canonical 6-axis mesh (parallel/mesh.py
#: ``AXES``, duplicated here because analyze never imports jax).
MESH_AXES = frozenset({"pp", "dp", "fsdp", "ep", "tp", "sp"})

#: Entrypoint modules known to pin gather outputs with explicit sharding
#: constraints (models/llama.py forward_features), making expert-parallel
#: meshes remat-free. Custom trainer modules get the TPX110 warning.
REMAT_SAFE_MODULES = ("torchx_tpu.examples.train_llama",)


def _mesh_specs(role: Role) -> Iterator[str]:
    """Values of ``--mesh`` arguments in a role's arg list (both the
    two-token ``--mesh dp=2,...`` and one-token ``--mesh=dp=2,...``
    spellings)."""
    args = [str(a) for a in role.args]
    for i, a in enumerate(args):
        if a == "--mesh" and i + 1 < len(args):
            yield args[i + 1]
        elif a.startswith("--mesh="):
            yield a.split("=", 1)[1]


@rule("mesh")
def check_mesh(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX110-TPX111: mesh axis specs in role args.

    TPX110 is the launch-time twin of the runtime remat push: a mesh that
    shards experts (``ep``) while also sharding weights or sequence
    (``fsdp``/``sp``) makes the embedding/expert gathers transition
    between a dim-sharded operand layout and a batch/seq-sharded output
    layout. GSPMD partitions that gather by replicate+reslice —
    "involuntary full rematerialization", warned on every compile and
    paid in HBM + latency — unless the model pins the gather outputs with
    explicit ``with_sharding_constraint``. The stock trainer does; a
    custom entrypoint module probably does not, so warn before the job
    ever reaches a pod.

    The heuristic is the FALLBACK: when the role resolves into a full
    :class:`~torchx_tpu.analyze.plan.ParallelPlan` (a recognizable
    ``--config``), real sharding propagation owns the question and emits
    TPX700 with the exact boundary instead (``check_deep_preflight``) —
    the pattern-match would double-report, so it stands down. TPX111
    (unknown axis names) always runs; it is pure spec hygiene.
    """
    from torchx_tpu.analyze.plan import PlanError, plan_from_role

    for role in ctx.app.roles:
        args = [str(a) for a in role.args]
        safe = any(
            m in (role.entrypoint or "") or m in args for m in REMAT_SAFE_MODULES
        )
        try:
            superseded = plan_from_role(role) is not None
        except PlanError:
            superseded = True  # broken plan: TPX703 owns the role
        for spec in _mesh_specs(role):
            sizes: dict[str, int] = {}
            for pair in spec.split(","):
                if not pair.strip():
                    continue
                axis, _, value = pair.partition("=")
                axis = axis.strip()
                try:
                    sizes[axis] = int(value)
                except ValueError:
                    sizes[axis] = 0  # unparseable size: still report the axis
                if axis not in MESH_AXES:
                    yield Diagnostic(
                        code="TPX111",
                        severity=Severity.ERROR,
                        role=role.name,
                        field="args.--mesh",
                        message=(
                            f"unknown mesh axis {axis!r} in --mesh {spec!r};"
                            f" the trainer mesh has axes"
                            f" {'/'.join(sorted(MESH_AXES))}"
                        ),
                        hint="fix the axis name (e.g. fsdp=-1, not fsd=-1)",
                    )
            ep = sizes.get("ep", 1)
            paired = [
                a for a in ("fsdp", "sp") if sizes.get(a, 1) > 1 or sizes.get(a) == -1
            ]
            if (ep > 1 or ep == -1) and paired and not safe and not superseded:
                yield Diagnostic(
                    code="TPX110",
                    severity=Severity.WARNING,
                    role=role.name,
                    field="args.--mesh",
                    message=(
                        f"--mesh {spec!r} pairs expert parallelism (ep) with"
                        f" {'/'.join(paired)} sharding: embedding/expert"
                        " gathers then reshard dim-sharded -> batch/seq-"
                        "sharded, which GSPMD partitions by involuntary"
                        " full rematerialization (replicate + reslice)"
                        " unless gather outputs carry explicit sharding"
                        " constraints"
                    ),
                    hint=(
                        "pin gather outputs with with_sharding_constraint"
                        " (see models/llama.py forward_features), or use"
                        " torchx_tpu.examples.train_llama which already"
                        " does"
                    ),
                )


#: Fused-kernel tileability (ops/fused.py ``FLASH_HEAD_DIMS`` /
#: ``flash_shapes_ok`` / ``norm_shapes_ok``, duplicated here because
#: analyze never imports jax): flash attention tiles head_dims of
#: 64/128/256 over 128-token blocks; the fused norm needs a lane-aligned
#: model dim.
_FUSED_HEAD_DIMS = frozenset({64, 128, 256})
_FUSED_LANE = 128


def _flag_value(role: Role, flag: str) -> Optional[str]:
    """Last value of ``flag`` in a role's arg list (both the two-token
    ``--flag v`` and one-token ``--flag=v`` spellings)."""
    args = [str(a) for a in role.args]
    found: Optional[str] = None
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            found = args[i + 1]
        elif a.startswith(flag + "="):
            found = a.split("=", 1)[1]
    return found


@rule("kernels")
def check_kernels(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX112: ``--kernels pallas`` that will silently fall back.

    The trainer degrades ``--kernels pallas`` to the reference XLA ops
    whenever the Mosaic kernels cannot run: on a non-TPU backend, or when
    the model/sequence shapes do not tile (flash attention needs a
    head_dim of 64/128/256 and a 128-divisible sequence; the fused norm
    needs a lane-aligned model dim). The job still trains — but the MFU
    the flag was supposed to buy never materializes, so surface the
    fallback at submit time instead of letting someone discover it in a
    profile three hours into a run.
    """
    from torchx_tpu.analyze.plan import MODEL_SHAPES

    for role in ctx.app.roles:
        if _flag_value(role, "--kernels") != "pallas":
            continue
        on_tpu = role.resource is not None and role.resource.tpu is not None
        if not on_tpu:
            yield Diagnostic(
                code="TPX112",
                severity=Severity.WARNING,
                role=role.name,
                field="args.--kernels",
                message=(
                    "--kernels pallas on a non-TPU backend: the fused"
                    " Mosaic kernels need TPU cores, so the trainer will"
                    " silently fall back to the reference XLA ops"
                ),
                hint=(
                    "request a TPU resource, or drop the flag (use"
                    " --kernels interpret only for parity testing — it"
                    " runs the kernels in the Pallas interpreter, slowly)"
                ),
            )
            continue
        config = _flag_value(role, "--config")
        model = MODEL_SHAPES.get(config or "")
        if model is None:
            continue  # unknown config: nothing shape-checkable
        problems = []
        if model.head_dim not in _FUSED_HEAD_DIMS:
            problems.append(
                f"head_dim {model.head_dim} (flash attention tiles"
                f" {'/'.join(str(d) for d in sorted(_FUSED_HEAD_DIMS))})"
            )
        if model.dim % _FUSED_LANE:
            problems.append(
                f"dim {model.dim} (fused norm needs a multiple of"
                f" {_FUSED_LANE})"
            )
        seq_raw = _flag_value(role, "--seq")
        try:
            seq = int(seq_raw) if seq_raw is not None else None
        except ValueError:
            seq = None
        if seq is not None and (seq < _FUSED_LANE or seq % _FUSED_LANE):
            problems.append(
                f"seq {seq} (flash attention needs a multiple of"
                f" {_FUSED_LANE})"
            )
        if problems:
            yield Diagnostic(
                code="TPX112",
                severity=Severity.WARNING,
                role=role.name,
                field="args.--kernels",
                message=(
                    f"--kernels pallas with config {config!r} cannot"
                    f" tile: {'; '.join(problems)} — the affected ops"
                    " fall back to the reference XLA path"
                ),
                hint=(
                    "pick a config whose shapes tile (head_dim 64/128/"
                    "256, dim and seq multiples of 128), or drop the"
                    " flag; the fallback is correct, just not fused"
                ),
            )


# ---------------------------------------------------------------------------
# TPX2xx — env / macros / ports / mounts
# ---------------------------------------------------------------------------


@rule("env")
def check_env(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX201-TPX203: launcher-owned env overrides (error), reserved-prefix
    collisions (warning) and JAX runtime config (info)."""
    for role in ctx.app.roles:
        for key in role.env:
            if key in LAUNCHER_OWNED_ENV:
                yield Diagnostic(
                    code="TPX201",
                    severity=Severity.ERROR,
                    role=role.name,
                    field=f"env.{key}",
                    message=(
                        f"env var {key!r} is injected by the launcher"
                        " (replica identity / rendezvous wiring); setting it"
                        " in the role corrupts the gang bootstrap"
                    ),
                    hint="remove it from Role.env — every scheduler sets it",
                )
            elif key in USER_SETTABLE_ENV:
                continue
            elif key.startswith(RESERVED_ENV_PREFIXES):
                yield Diagnostic(
                    code="TPX202",
                    severity=Severity.WARNING,
                    role=role.name,
                    field=f"env.{key}",
                    message=(
                        f"env var {key!r} uses a reserved prefix"
                        f" ({'/'.join(RESERVED_ENV_PREFIXES)}) but is not a"
                        " documented knob"
                    ),
                    hint="rename it unless you are targeting platform internals",
                )
            elif key.startswith("JAX_"):
                yield Diagnostic(
                    code="TPX203",
                    severity=Severity.INFO,
                    role=role.name,
                    field=f"env.{key}",
                    message=(
                        f"env var {key!r} configures the JAX runtime;"
                        " make sure it is intentional"
                    ),
                )


@rule("macros")
def check_macros(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX204: ``${...}`` placeholders that no launcher macro resolves."""
    for role in ctx.app.roles:
        fields: list[tuple[str, str]] = [("entrypoint", role.entrypoint)]
        fields += [(f"args[{i}]", a) for i, a in enumerate(role.args)]
        fields += [(f"env.{k}", v) for k, v in role.env.items()]
        for i, m in enumerate(role.mounts):
            for attr in ("src_path", "dst_path"):
                val = getattr(m, attr, None)
                if val:
                    fields.append((f"mounts[{i}].{attr}", val))
        for where, value in fields:
            if not isinstance(value, str):
                continue
            for name in sorted(unknown_macro_names(value)):
                yield Diagnostic(
                    code="TPX204",
                    severity=Severity.WARNING,
                    role=role.name,
                    field=where,
                    message=(
                        f"${{{name}}} is not a launcher macro"
                        f" (known: {', '.join(sorted(KNOWN_MACROS))}); it will"
                        " pass through to the replica shell unexpanded by the"
                        " launcher"
                    ),
                    hint=(
                        f"use $${{{name}}} to make runtime shell expansion"
                        " explicit, or fix the macro name"
                    ),
                )


@rule("ports")
def check_ports(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX210-TPX211: duplicate and out-of-range ports in ``port_map``."""
    for role in ctx.app.roles:
        by_port: dict[int, str] = {}
        for name, port in role.port_map.items():
            if not 0 < port < 65536:
                yield Diagnostic(
                    code="TPX211",
                    severity=Severity.ERROR,
                    role=role.name,
                    field=f"port_map.{name}",
                    message=f"port {port} for {name!r} is out of range 1-65535",
                    hint="pick a valid TCP port",
                )
            elif port in by_port:
                yield Diagnostic(
                    code="TPX210",
                    severity=Severity.ERROR,
                    role=role.name,
                    field=f"port_map.{name}",
                    message=(
                        f"port {port} is mapped twice"
                        f" ({by_port[port]!r} and {name!r})"
                    ),
                    hint="give each named port a distinct number",
                )
            else:
                by_port[port] = name


@rule("serve_ports")
def check_serve_ports(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX212: a serve-shaped role (its args bind a ``--port``) whose port
    has no ``port_map`` entry — routers and serve pools discover replica
    endpoints through the port map, so an unmapped server port is
    unreachable through every launcher surface that consumes it."""
    for role in ctx.app.roles:
        args = [str(a) for a in role.args]
        ports: list[tuple[int, int]] = []  # (arg index, port)
        for i, a in enumerate(args):
            if a == "--port" and i + 1 < len(args):
                raw = args[i + 1]
            elif a.startswith("--port="):
                raw = a.split("=", 1)[1]
            else:
                continue
            try:
                ports.append((i, int(raw)))
            except ValueError:
                continue
        mapped = set(role.port_map.values())
        for i, port in ports:
            if port == 0:
                continue  # ephemeral: the server reports its bound port
            if port not in mapped:
                yield Diagnostic(
                    code="TPX212",
                    severity=Severity.WARNING,
                    role=role.name,
                    field=f"args[{i}]",
                    message=(
                        f"role binds --port {port} but port_map has no"
                        f" entry for it"
                        + (
                            f" (mapped: {sorted(mapped)})"
                            if mapped
                            else " (port_map is empty)"
                        )
                    ),
                    hint=(
                        f'add port_map={{"http": {port}}} to the role so'
                        " routers and serve pools can reach it"
                    ),
                )


@rule("serve_disagg")
def check_serve_disagg(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX213: a disaggregated serving role (``--serve-role prefill`` or
    ``decode``) with no KV transfer path declared — neither a
    ``--kv-transfer`` arg nor ``tpx/kv_transfer`` role metadata. A
    prefill gang with nowhere to stream its computed KV blocks (or a
    decode gang no prefill can reach) is an assembly error: every
    request would prefill and then fail, so it is an ERROR at submit,
    before any chip is provisioned."""
    from torchx_tpu.serve.kv_transfer import ROLE_METADATA_KEY

    def _flag_value(args: list[str], flag: str) -> Optional[str]:
        for i, a in enumerate(args):
            if a == flag and i + 1 < len(args):
                return args[i + 1]
            if a.startswith(flag + "="):
                return a.split("=", 1)[1]
        return None

    for role in ctx.app.roles:
        args = [str(a) for a in role.args]
        serve_role = _flag_value(args, "--serve-role")
        if serve_role not in ("prefill", "decode"):
            continue
        if _flag_value(args, "--kv-transfer"):
            continue
        if role.metadata.get(ROLE_METADATA_KEY):
            continue
        yield Diagnostic(
            code="TPX213",
            severity=Severity.ERROR,
            role=role.name,
            field="args",
            message=(
                f"role declares --serve-role {serve_role} but no KV"
                f" transfer path (no --kv-transfer arg and no"
                f" {ROLE_METADATA_KEY!r} metadata)"
            ),
            hint=(
                "declare the prefill->decode path: --kv-transfer"
                " http:<decode-url>[,...] | file:<dir> | local, or use"
                " components.serve.generate_server_disagg which wires"
                " both roles"
            ),
        )


@rule("serve_slo")
def check_serve_slo(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX214: a role declaring SLO specs (``--slo`` args or ``tpx/slo``
    metadata) on a backend whose capability profile has no ``/metricz``
    scrape path. The telemetry plane's burn rates come from scraping
    replica metrics; on an unreachable backend every SLO over replica
    metrics sees zero samples, so the burn stays zero and the alert can
    never fire — a silent no-op, hence a WARNING before submit."""
    from torchx_tpu.obs.slo import ROLE_METADATA_KEY as SLO_METADATA_KEY

    cap = ctx.capabilities
    if ctx.scheduler is None or cap is None or cap.metricz_scrape:
        return
    for role in ctx.app.roles:
        args = [str(a) for a in role.args]
        has_slo = any(
            a == "--slo" or a.startswith("--slo=") for a in args
        ) or bool(role.metadata.get(SLO_METADATA_KEY))
        if not has_slo:
            continue
        yield Diagnostic(
            code="TPX214",
            severity=Severity.WARNING,
            role=role.name,
            field="args",
            message=(
                f"role declares SLO specs but scheduler"
                f" {ctx.scheduler!r} has no /metricz scrape path"
                " (metricz_scrape=False); burn rates over replica"
                " metrics will stay zero and the alerts can never fire"
            ),
            hint=(
                "target a scrape-reachable backend (local, docker, gke,"
                " slurm), or push metrics via the obs textfile sink and"
                " drop the replica-scrape SLOs"
            ),
        )


@rule("profile_scrape")
def check_profile_scrape(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX215: step profiling enabled (the trainer's ``--profile`` flag
    or ``TPX_PROFILE=1`` in the role env) on a backend whose capability
    profile has no ``/metricz`` scrape path. The profiler still writes
    its per-step journal and ``tpx profile`` still renders it from the
    replica's obs dir, but the ``tpx_profile_*`` summary gauges are
    published via replica scrape — unreachable backend means no fleet
    MFU / data-wait panels in ``tpx top``, which is usually why
    profiling was turned on. WARNING, not ERROR: local-only attribution
    is still useful."""
    cap = ctx.capabilities
    if ctx.scheduler is None or cap is None or cap.metricz_scrape:
        return
    for role in ctx.app.roles:
        # exact-flag match: --profile-dir (the xprof trace flag) is a
        # different feature and must not trigger this rule
        enabled = any(
            str(a) == "--profile" for a in role.args
        ) or str(role.env.get(s.ENV_TPX_PROFILE, "")).lower() in (
            "1",
            "true",
            "yes",
            "on",
        )
        if not enabled:
            continue
        yield Diagnostic(
            code="TPX215",
            severity=Severity.WARNING,
            role=role.name,
            field="args",
            message=(
                f"role enables step profiling but scheduler"
                f" {ctx.scheduler!r} has no /metricz scrape path"
                " (metricz_scrape=False); tpx_profile_* summaries stay"
                " local to the replica's obs dir and tpx top shows no"
                " MFU / data-wait panels"
            ),
            hint=(
                "target a scrape-reachable backend (local, docker, gke,"
                " slurm) to publish the summaries, or read them locally"
                " with `tpx profile` / the obs textfile sink"
            ),
        )


@rule("mounts")
def check_mounts(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX220-TPX221: duplicate destinations and relative paths in mounts."""
    for role in ctx.app.roles:
        seen: dict[str, int] = {}
        for i, m in enumerate(role.mounts):
            dst = getattr(m, "dst_path", None)
            if not dst:
                continue
            if dst in seen:
                yield Diagnostic(
                    code="TPX220",
                    severity=Severity.ERROR,
                    role=role.name,
                    field=f"mounts[{i}].dst_path",
                    message=(
                        f"mount destination {dst!r} is used by both"
                        f" mounts[{seen[dst]}] and mounts[{i}]"
                    ),
                    hint="each mount needs a distinct destination path",
                )
            else:
                seen[dst] = i
            if not dst.startswith("/") and "${" not in dst:
                yield Diagnostic(
                    code="TPX221",
                    severity=Severity.WARNING,
                    role=role.name,
                    field=f"mounts[{i}].dst_path",
                    message=f"mount destination {dst!r} is not absolute",
                    hint="use an absolute container path",
                )


# ---------------------------------------------------------------------------
# TPX3xx — scheduler capability fit
# ---------------------------------------------------------------------------


@rule("capabilities")
def check_capabilities(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX300-TPX307: AppDef features the target backend cannot honor."""
    if ctx.scheduler is None:
        return
    cap = ctx.capabilities
    if cap is None:
        yield Diagnostic(
            code="TPX300",
            severity=Severity.INFO,
            message=(
                f"no capability profile for scheduler {ctx.scheduler!r};"
                " capability rules skipped"
            ),
            hint=(
                "builtin backends declare CAPABILITIES in their module;"
                " plugins can set Scheduler.capabilities"
            ),
        )
        return
    app = ctx.app
    if len(app.roles) > 1 and not cap.multi_role:
        yield Diagnostic(
            code="TPX303",
            severity=Severity.ERROR,
            field="roles",
            message=(
                f"scheduler {ctx.scheduler!r} launches exactly one role per"
                f" job; AppDef has {len(app.roles)}"
            ),
            hint="split the app or pick a multi-role backend (gke, slurm)",
        )
    if not cap.delete:
        yield Diagnostic(
            code="TPX302",
            severity=Severity.WARNING,
            message=(
                f"scheduler {ctx.scheduler!r} has no delete(); supervised"
                " resubmission cannot clean up terminal attempts"
            ),
            hint="expect leftover terminal jobs when using tpx supervise",
        )
    for role in app.roles:
        if role.mounts and not cap.mounts:
            yield Diagnostic(
                code="TPX301",
                severity=Severity.ERROR,
                role=role.name,
                field="mounts",
                message=(
                    f"scheduler {ctx.scheduler!r} does not materialize"
                    f" mounts; {len(role.mounts)} mount(s) would be silently"
                    " dropped"
                ),
                hint="remove the mounts or use local_docker / gke",
            )
        if cap.requires_tpu and (role.resource is None or role.resource.tpu is None):
            yield Diagnostic(
                code="TPX305",
                severity=Severity.ERROR,
                role=role.name,
                field="resource.tpu",
                message=(
                    f"scheduler {ctx.scheduler!r} only provisions TPU slices;"
                    f" role {role.name!r} has no resource.tpu"
                ),
                hint="set resource.tpu = TpuSlice(...) or pick another backend",
            )
        if (
            role.resource is not None
            and role.resource.tpu is not None
            and role.num_replicas > 1
            and not cap.multislice
        ):
            yield Diagnostic(
                code="TPX304",
                severity=Severity.ERROR,
                role=role.name,
                field="num_replicas",
                message=(
                    f"scheduler {ctx.scheduler!r} cannot wire multi-slice"
                    f" DCN training (TPU role with num_replicas="
                    f"{role.num_replicas})"
                ),
                hint="use num_replicas=1 or a multislice backend (gke)",
            )
        if role.max_retries > 0 and not cap.native_retries:
            yield Diagnostic(
                code="TPX306",
                severity=Severity.WARNING,
                role=role.name,
                field="max_retries",
                message=(
                    f"scheduler {ctx.scheduler!r} does not honor"
                    f" max_retries={role.max_retries} natively"
                ),
                hint="run under `tpx supervise` for client-side resubmission",
            )
        if (
            cap.concrete_resources
            and (role.resource is None or role.resource.tpu is None)
            and (role.resource is None or role.resource.cpu <= 0 or role.resource.memMB <= 0)
        ):
            yield Diagnostic(
                code="TPX307",
                severity=Severity.WARNING,
                role=role.name,
                field="resource",
                message=(
                    f"scheduler {ctx.scheduler!r} builds concrete resource"
                    " requests but cpu/memMB are unset; backend defaults"
                    " apply"
                ),
                hint="set Resource.cpu and Resource.memMB explicitly",
            )


# ---------------------------------------------------------------------------
# TPX4xx — supervisor / retry coherence
# ---------------------------------------------------------------------------


@rule("retries")
def check_retries(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX401-TPX404: retry budgets and policies that cannot do what they
    promise (gang semantics, preemption classification, resume injection)."""
    cap = ctx.capabilities
    policy = ctx.policy
    for role in ctx.app.roles:
        if role.max_retries < 0:
            yield Diagnostic(
                code="TPX402",
                severity=Severity.ERROR,
                role=role.name,
                field="max_retries",
                message=f"max_retries must be >= 0, got {role.max_retries}",
                hint="use 0 to disable retries",
            )
        if (
            role.resource is not None
            and role.resource.tpu is not None
            and role.retry_policy == RetryPolicy.REPLICA
        ):
            yield Diagnostic(
                code="TPX401",
                severity=Severity.WARNING,
                role=role.name,
                field="retry_policy",
                message=(
                    "RetryPolicy.REPLICA on a TPU role: restarting one host"
                    " cannot rejoin the ICI collective — the whole gang must"
                    " restart"
                ),
                hint="use RetryPolicy.APPLICATION (the TPU default)",
            )
        if policy is not None and policy.resume_env in role.env:
            yield Diagnostic(
                code="TPX404",
                severity=Severity.WARNING,
                role=role.name,
                field=f"env.{policy.resume_env}",
                message=(
                    f"role sets {policy.resume_env!r} but the supervisor"
                    " injects it from the checkpoint manifest on every"
                    " resubmission; the role value will be overwritten"
                ),
                hint="drop it from Role.env and let the supervisor drive resume",
            )
    if (
        policy is not None
        and policy.max_preemptions > 0
        and cap is not None
        and not cap.classifies_preemption
    ):
        yield Diagnostic(
            code="TPX403",
            severity=Severity.WARNING,
            message=(
                f"policy allows {policy.max_preemptions} preemption"
                f" resubmits but scheduler {ctx.scheduler!r} cannot classify"
                " preemptions — they will be counted as app errors"
                f" (budget {policy.max_app_retries})"
            ),
            hint=(
                "raise max_app_retries or use a backend that classifies"
                " preemption (gke, tpu_vm, slurm, local)"
            ),
        )


# ---------------------------------------------------------------------------
# TPX5xx — control-plane resilience coherence
# ---------------------------------------------------------------------------

#: backends where a fault plan only sabotages the operator's own machine;
#: anywhere else it corrupts a real cloud submission.
_FAULT_PLAN_SAFE_SCHEDULERS = frozenset({"local", "local_docker"})


@rule("resilience")
def check_resilience(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX501-TPX502: resilience knobs that compose into surprises.

    Three restart layers can stack: the backend's native per-role restarts
    (``Role.max_retries`` honored in place), the supervisor's per-class
    resubmission budgets, and the control-plane seam's own call retries.
    The first two multiply — every supervisor resubmit re-arms the full
    native budget — which is easy to configure by accident and miserable
    to debug at 3am (TPX501). And a ``TPX_FAULT_PLAN`` chaos drill left in
    the environment must never ride along into a real cloud submission
    (TPX502)."""
    policy = ctx.policy
    cap = ctx.capabilities
    if policy is not None and cap is not None and cap.native_retries:
        supervisor_budget = (
            policy.max_preemptions
            + policy.max_infra_retries
            + policy.max_app_retries
        )
        native = max((r.max_retries for r in ctx.app.roles), default=0)
        if supervisor_budget > 0 and native > 0:
            worst = (supervisor_budget + 1) * (native + 1) - 1
            yield Diagnostic(
                code="TPX501",
                severity=Severity.WARNING,
                field="max_retries",
                message=(
                    f"supervisor budgets ({supervisor_budget} resubmits)"
                    f" stack MULTIPLICATIVELY with scheduler"
                    f" {ctx.scheduler!r}'s native max_retries ({native}):"
                    f" every resubmit re-arms the full native budget, up to"
                    f" {worst} total restarts"
                ),
                hint=(
                    "set Role.max_retries=0 under tpx supervise (let the"
                    " supervisor own restarts), or skip supervise and keep"
                    " native retries"
                ),
            )
    if ctx.scheduler and ctx.scheduler not in _FAULT_PLAN_SAFE_SCHEDULERS:
        from torchx_tpu.resilience.faults import fault_plan_active

        if fault_plan_active():
            yield Diagnostic(
                code="TPX502",
                severity=Severity.ERROR,
                field=s.ENV_TPX_FAULT_PLAN,
                message=(
                    f"{s.ENV_TPX_FAULT_PLAN} is set but the target scheduler"
                    f" is {ctx.scheduler!r}: a fault-injection drill against"
                    " a real control plane fabricates failures on live cloud"
                    " calls (retries, breaker trips, even aborted submits)"
                ),
                hint=(
                    "unset TPX_FAULT_PLAN, or drill against the local /"
                    " local_docker schedulers"
                ),
            )


#: role-arg spellings that tell the app where to checkpoint; if none
#: appears anywhere the app never writes the directory the supervisor
#: watches for resume steps.
_CKPT_DIR_FLAGS = ("--ckpt-dir", "--checkpoint-dir", "--ckpt_dir")


@rule("recovery")
def check_recovery(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX503: supervision configured for checkpoint-resume but the job
    never checkpoints.

    ``SupervisorPolicy.checkpoint_dir`` makes every resubmission inject
    ``TPX_RESUME_STEP`` from the checkpoint manifest — but the manifest
    only exists if the *application* saves checkpoints there. A policy
    with resume retries whose roles pass no checkpoint-dir flag restarts
    from step 0 on every preemption: the retries "work" while silently
    discarding all progress. Catch the incoherence before submit."""
    policy = ctx.policy
    if policy is None or not policy.checkpoint_dir:
        return
    resume_budget = (
        policy.max_preemptions
        + policy.max_infra_retries
        + policy.max_hang_retries
    )
    if resume_budget <= 0:
        return
    for role in ctx.app.roles:
        args = list(role.args) + [role.entrypoint]
        if any(flag in str(a) for a in args for flag in _CKPT_DIR_FLAGS):
            return
    yield Diagnostic(
        code="TPX503",
        severity=Severity.WARNING,
        field="checkpoint_dir",
        message=(
            f"policy watches checkpoint_dir={policy.checkpoint_dir!r} with"
            f" {resume_budget} resume retries budgeted, but no role passes a"
            f" checkpoint-dir flag ({'/'.join(_CKPT_DIR_FLAGS)}) — every"
            " resubmission will restart from step 0"
        ),
        hint=(
            "point the app at the same directory (e.g."
            f" --ckpt-dir {policy.checkpoint_dir}) so saved steps feed"
            " TPX_RESUME_STEP, or drop checkpoint_dir from the policy"
        ),
    )


# ---------------------------------------------------------------------------
# TPX6xx — control-plane (daemon / watch) coherence
# ---------------------------------------------------------------------------


@rule("control-plane")
def check_control_plane(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX601: hang detection expects event latency the backend can't give.

    Under the control daemon (``TPX_CONTROL_ADDR`` set), supervision
    waits ride the reconciler's watch streams — terminal transitions and
    gang-health signals arrive at event latency on backends that declare
    the ``watch`` capability (local sidecars, GKE's kubectl stream). On a
    backend WITHOUT it, the same interface silently degrades to the
    generic poll adapter, so a policy that budgets hang detection
    (``hang_deadline_seconds``) will observe hangs only at the watch poll
    interval — worth knowing before the 3am page arrives late."""
    policy = ctx.policy
    cap = ctx.capabilities
    if policy is None or cap is None:
        return
    if getattr(policy, "hang_deadline_seconds", 0) <= 0:
        return
    if not os.environ.get(s.ENV_TPX_CONTROL_ADDR, "").strip():
        return
    if cap.watch:
        return
    yield Diagnostic(
        code="TPX601",
        severity=Severity.WARNING,
        field="hang_deadline_seconds",
        message=(
            f"supervisor hang detection"
            f" (hang_deadline_seconds={policy.hang_deadline_seconds:g}) runs"
            f" through the control daemon ({s.ENV_TPX_CONTROL_ADDR} is set),"
            f" but scheduler {ctx.scheduler!r} has no native watch source —"
            " state changes surface at the watch POLL interval, so"
            " hang-detection latency degrades by up to that interval"
        ),
        hint=(
            "target a watch-capable backend (local, gke), tighten"
            f" {s.ENV_TPX_WATCH_INTERVAL}, or run this job outside the"
            " daemon (unset TPX_CONTROL_ADDR) to poll directly"
        ),
    )


@rule("fleet-class")
def check_fleet_class(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX602: a preemptible-class gang with no way to survive preemption.

    Under the fleet scheduler, ``batch`` and ``preemptible`` classes are
    the preemption market's victims: a higher class that cannot place
    will shrink them (elastic reshape) or checkpoint-preempt them. A role
    in one of those classes that is neither elastic
    (``SupervisorPolicy.elastic_reshape``) nor checkpointing (no
    checkpoint-dir flag, same detection as TPX503) loses ALL progress on
    every market action — it runs, but every preemption restarts it from
    step 0. The class is read from ``role.metadata["fleet/class"]`` or
    the injected ``$TPX_FLEET_CLASS`` role env."""
    if ctx.policy is not None and getattr(ctx.policy, "elastic_reshape", False):
        return
    for role in ctx.app.roles:
        klass = str(
            role.metadata.get("fleet/class")
            or role.env.get(s.ENV_TPX_FLEET_CLASS)
            or ""
        ).strip()
        if klass not in ("batch", "preemptible"):
            continue
        args = list(role.args) + [role.entrypoint]
        if any(flag in str(a) for a in args for flag in _CKPT_DIR_FLAGS):
            continue
        yield Diagnostic(
            code="TPX602",
            severity=Severity.WARNING,
            field="fleet/class",
            message=(
                f"role {role.name!r} runs in fleet class {klass!r} — a"
                " preemption-market victim class — but is neither elastic"
                " (no SupervisorPolicy.elastic_reshape) nor checkpointing"
                f" (no {'/'.join(_CKPT_DIR_FLAGS)} flag): every market"
                " shrink or preemption will cost its full progress"
            ),
            hint=(
                "make the gang elastic (policy elastic_reshape + a mesh"
                " spec, submit with elastic=true) so the market shrinks it"
                " instead of killing it, or pass a checkpoint-dir flag so"
                " a preempted attempt resumes from its last step"
            ),
        )


@rule("promotion-scrape")
def check_promotion_scrape(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX603: a promotion stage on a backend the canary gate can't see.

    The pipeline engine's promote stage gates promote-to-100% on BOTH the
    eval score and the SLO engine's live burn rate over the canary
    replicas. Burn rates come from scraping replica ``/metricz``; on a
    backend whose capability profile has no scrape path the burn signal
    sees zero samples, so the canary gate silently degrades to
    eval-score-only — an SLO regression on the canary would promote
    anyway. Promotion stages are recognized by the
    ``tpx/pipeline=promote`` role metadata the pipeline executor stamps."""
    from torchx_tpu.pipelines.dag import ROLE_METADATA_KEY

    cap = ctx.capabilities
    if ctx.scheduler is None or cap is None or cap.metricz_scrape:
        return
    for role in ctx.app.roles:
        if role.metadata.get(ROLE_METADATA_KEY) != "promote":
            continue
        yield Diagnostic(
            code="TPX603",
            severity=Severity.WARNING,
            role=role.name,
            field="metadata",
            message=(
                f"promotion stage targets scheduler {ctx.scheduler!r}"
                " which has no /metricz scrape path"
                " (metricz_scrape=False): the canary burn-rate gate sees"
                " zero samples and silently degrades to eval-score-only —"
                " an SLO regression on the canary replicas would be"
                " promoted to 100%"
            ),
            hint=(
                "run the promote stage on a scrape-reachable backend"
                " (local, docker, gke, slurm) so the burn gate has"
                " samples, or accept eval-score-only gating and lower the"
                " eval threshold margin accordingly"
            ),
        )


def check_sim_scenario(scenario: Mapping[str, Any]) -> Iterator[Diagnostic]:
    """TPX604: a simulation scenario naming a backend other than ``sim``.

    Not an AppDef rule — scenarios are plain dicts, so ``tpx sim`` calls
    this directly instead of going through the engine. The virtual-time
    harness only ever drives :class:`~torchx_tpu.sim.executor
    .SimExecutor`; a scenario declaring ``"backend": "gke"`` (say,
    copied from a production job file) still runs entirely in the
    simulator, and an operator reading the journal could mistake modeled
    placements for real ones. WARNING, never gating: the run is valid,
    the label is misleading."""
    backend = scenario.get("backend")
    if backend is None or str(backend) == "sim":
        return
    yield Diagnostic(
        code="TPX604",
        severity=Severity.WARNING,
        field="backend",
        message=(
            f"scenario {str(scenario.get('name', '?'))!r} names backend"
            f" {str(backend)!r}, but the simulator only drives the"
            " virtual-time executor — every placement in the journal is"
            " modeled, none touch a real scheduler"
        ),
        hint=(
            'set "backend": "sim" (or drop the key) so the journal'
            " cannot be mistaken for a real-backend run"
        ),
    )


def check_federation_config(
    config: Mapping[str, Any]
) -> Iterator[Diagnostic]:
    """TPX605: a federation setup that cannot actually fail over.

    Like TPX604, not an AppDef rule — federation configs (scenario dicts
    with a ``cells`` list, or ``tpx cell`` registry snapshots) are plain
    dicts, called directly by the CLI. Two shapes warn:

    * a single registered cell: every routing decision has exactly one
      answer, so a drain or daemon loss drops traffic — the federation
      layer is pure overhead until a second cell exists;
    * multiple cells with a promotion wave configured but per-cell
      rollback disabled (``rollback: false``, or a promote stage whose
      ``burn_threshold`` can never fire): a bad candidate promoted into
      region 1 rolls on into region 2 — the wave's whole point is that
      it halts.

    WARNING, never gating: both setups run, they just degrade the
    property the operator presumably wanted."""
    cells = list(config.get("cells") or [])
    if len(cells) < 2:
        yield Diagnostic(
            code="TPX605",
            severity=Severity.WARNING,
            field="cells",
            message=(
                f"federation config has {len(cells)} cell(s) — no"
                " failover is possible: a drain or daemon loss leaves"
                " the router nowhere to spill"
            ),
            hint=(
                "register at least two cells (`tpx cell add`) or run"
                " single-cell without the federation layer"
            ),
        )
        return
    promote = config.get("promote")
    stages: list[Mapping[str, Any]] = []
    if isinstance(promote, Mapping):
        stages = [promote]
    for entry in config.get("pipelines") or []:
        spec = entry.get("spec") if isinstance(entry, Mapping) else None
        if isinstance(spec, Mapping):
            for stage in spec.get("stages") or []:
                if (
                    isinstance(stage, Mapping)
                    and str(stage.get("kind", "")) == "promote"
                ):
                    stages.append(stage)
    for stage in stages:
        rollback_off = stage.get("rollback") is False
        try:
            threshold = float(stage.get("burn_threshold", 1.0))
        except (TypeError, ValueError):
            threshold = 1.0
        if rollback_off or threshold <= 0.0 or not math.isfinite(threshold):
            name = str(stage.get("name", "promote"))
            yield Diagnostic(
                code="TPX605",
                severity=Severity.WARNING,
                field=f"promote.{name}",
                message=(
                    f"multi-cell promotion stage {name!r} has per-cell"
                    " rollback disabled"
                    + (
                        ""
                        if rollback_off
                        else f" (burn_threshold={threshold!r} can never"
                        " fire)"
                    )
                    + " — a bad candidate halted in one region will"
                    " still roll into the next"
                ),
                hint=(
                    "enable rollback and set a finite burn_threshold > 0"
                    " on every promote stage of a multi-cell wave"
                ),
            )


# ---------------------------------------------------------------------------
# TPX7xx — deep preflight: static sharding / HBM / collective analysis
# ---------------------------------------------------------------------------


@rule("deep-preflight")
def check_deep_preflight(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX700-TPX704: the jax-free static analysis pass.

    For every role whose args resolve into a
    :class:`~torchx_tpu.analyze.plan.ParallelPlan` (a recognizable
    ``--config`` plus mesh/topology facts), propagate named shardings
    through the train/serve step, compute the static HBM fit and classify
    per-axis collective traffic ICI vs DCN — the full report is
    ``tpx explain``; this rule feeds the same diagnostics into the submit
    gate. Roles with no resolvable plan are silently skipped here (the
    TPX110 heuristic covers them); ``tpx explain`` additionally reports
    the skip as TPX705 info.
    """
    from torchx_tpu.analyze.explain import deep_preflight

    for role in ctx.app.roles:
        _plan, diags = deep_preflight(role)
        for d in diags:
            if d.code == "TPX705":
                continue  # explain-only: the gate stays quiet on skips
            yield d


@rule("plan-artifact")
def check_plan_artifact(ctx: RuleContext) -> Iterator[Diagnostic]:
    """TPX706/TPX707: the tuned-plan pin.

    When ``$TPX_PLAN_ARTIFACT`` points at a ``tpx tune`` winner artifact,
    every plan-shaped role must resolve to the SAME tuned knobs (config,
    mesh, batch, seq, remat policy, int8) — divergence is TPX706, and an
    artifact that cannot be trusted (unreadable, malformed, content
    digest mismatch) is TPX707. Roles with no resolvable plan are
    skipped: the pin constrains tuned trainers, not sidecars. Unset pin
    = rule silent, so nothing changes for untuned submits.
    """
    from torchx_tpu.analyze.explain import (
        artifact_diff_diagnostics,
        deep_preflight,
    )
    from torchx_tpu.tune.artifact import pinned_artifact_path

    path = pinned_artifact_path()
    if not path:
        return
    broken_reported = False
    for role in ctx.app.roles:
        plan, _diags = deep_preflight(role)
        if plan is None:
            continue
        diags, _detail = artifact_diff_diagnostics(path, role.name, plan)
        for d in diags:
            if d.code == "TPX707":
                if broken_reported:
                    continue  # one broken-artifact error, not one per role
                broken_reported = True
            yield d
