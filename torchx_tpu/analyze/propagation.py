"""Jax-free named-sharding propagation over the train-step layer graph.

This walks the same dataflow ``models/llama.py`` / ``models/moe.py``
compile — embed gather -> (per layer) qkv projections -> attention
(flash or ring) -> output projection -> FFN or MoE dispatch/combine ->
cross-entropy -> gradient sync — carrying the canonical named shardings
(``parallel/mesh.py``: batch over ``("dp","fsdp")``, sequence over
``sp``, model dims over ``tp``, experts over ``ep``, params over
``("fsdp","tp")``), and records every point where GSPMD must insert a
collective to move between the producer's layout and the consumer's:
a :class:`Boundary`.

Boundary kinds:

* ``allgather`` — a dim-sharded operand is gathered (ZeRO-3 params over
  ``fsdp``, K/V over ``sp`` without ring attention).
* ``allreduce`` — partial sums over a contracted sharded dim (``tp``
  output projections, gradient sync over ``dp``/``fsdp``).
* ``alltoall`` — token redistribution onto the expert layout (``ep``).
* ``permute`` — neighbor collective-permute (ring attention K/V rotation
  over ``sp``, pipeline stage hand-off over ``pp``).
* ``full_remat`` — the involuntary-full-rematerialization resolution:
  a gather/dispatch whose operand is dim-sharded while its output is
  batch/seq-sharded, *and* nothing pins the output layout. GSPMD then
  partitions by replicate+reslice — the compile-time warning the
  MULTICHIP r03/r04 dryrun legs chase. The stock trainer
  (``plan.REMAT_SAFE_MODULES``) pins these outputs with
  ``with_sharding_constraint``; custom entrypoints get the ERROR.

Everything here is pure arithmetic on axis names and the resolved
:class:`~torchx_tpu.analyze.plan.ParallelPlan` — no jax import, ever
(enforced by ``scripts/lint_internal.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

from torchx_tpu.analyze.plan import ParallelPlan

Dim = tuple[str, ...]


def _spec(*dims: "str | Dim | None") -> tuple[Dim, ...]:
    """Normalize a PartitionSpec-like description to per-dim axis tuples."""
    out: list[Dim] = []
    for d in dims:
        if d is None:
            out.append(())
        elif isinstance(d, str):
            out.append((d,))
        else:
            out.append(tuple(d))
    return tuple(out)


def render_spec(dims: tuple[Dim, ...]) -> str:
    """Human/JSON-stable ``P(...)`` rendering of a per-dim axis layout."""
    parts = []
    for d in dims:
        if not d:
            parts.append("None")
        elif len(d) == 1:
            parts.append(f"'{d[0]}'")
        else:
            parts.append("(" + ", ".join(f"'{a}'" for a in d) + ")")
    return "P(" + ", ".join(parts) + ")"


@dataclasses.dataclass(frozen=True)
class Boundary:
    """One resharding point GSPMD must bridge with a collective."""

    op: str  # graph site, e.g. "embed.gather", "layer.mlp_out"
    kind: str  # allgather | allreduce | alltoall | permute | full_remat
    axes: tuple[str, ...]  # mesh axes the collective runs over
    producer: str  # rendered spec of the produced layout
    consumer: str  # rendered spec the consumer needs
    note: str = ""

    def to_dict(self) -> dict:
        """Stable JSON form for the explain report."""
        return {
            "op": self.op,
            "kind": self.kind,
            "axes": list(self.axes),
            "producer": self.producer,
            "consumer": self.consumer,
            "note": self.note,
        }


@dataclasses.dataclass
class ShardingFlow:
    """The propagation result for one plan."""

    boundaries: list[Boundary]
    batch_spec: str
    activation_spec: str

    @property
    def full_remat(self) -> bool:
        """True when any boundary resolves by involuntary full remat."""
        return any(b.kind == "full_remat" for b in self.boundaries)

    def to_dict(self) -> dict:
        """Stable JSON form for the explain report."""
        return {
            "batch_spec": self.batch_spec,
            "activation_spec": self.activation_spec,
            "full_remat": self.full_remat,
            "boundaries": [b.to_dict() for b in self.boundaries],
        }


def _live(plan: ParallelPlan, *axes: str) -> tuple[str, ...]:
    """The subset of ``axes`` actually sharded (size > 1) in the plan,
    in canonical mesh-axis order."""
    from torchx_tpu.parallel.mesh_config import AXES

    live = {a for a in axes if plan.axis(a) > 1}
    return tuple(a for a in AXES if a in live)


def propagate(plan: ParallelPlan) -> ShardingFlow:
    """Propagate named shardings through the plan's train/serve step and
    return every resharding boundary in graph order."""
    boundaries: list[Boundary] = []
    data = _live(plan, "dp", "fsdp")  # batch-dim axes
    sp = plan.axis("sp") > 1
    tp = plan.axis("tp") > 1
    ep = plan.axis("ep") > 1
    pp = plan.axis("pp") > 1

    seq_dim: Dim = ("sp",) if sp else ()
    act = _spec(data, seq_dim, None)  # residual stream [b, s, d]
    act_s = render_spec(act)
    batch_s = render_spec(_spec(data, seq_dim))

    def add(op: str, kind: str, axes: Iterable[str], producer, consumer, note=""):
        axes = tuple(axes)
        if not axes:
            return
        boundaries.append(
            Boundary(
                op=op,
                kind=kind,
                axes=axes,
                producer=producer if isinstance(producer, str) else render_spec(producer),
                consumer=consumer if isinstance(consumer, str) else render_spec(consumer),
                note=note,
            )
        )

    # -- embedding gather: table P(None, 'fsdp') indexed by batch/seq-
    # sharded token ids; the output must land on the residual layout.
    table = _spec(None, "fsdp")
    if "fsdp" in _live(plan, "fsdp"):
        gather_unsafe = ep and not plan.remat_safe
        add(
            "embed.gather",
            "full_remat" if gather_unsafe else "allgather",
            ("fsdp",) + (_live(plan, "ep") if gather_unsafe else ()),
            table,
            act,
            note=(
                "dim-sharded table gathered to a batch/seq-sharded output;"
                " unpinned under an expert-parallel mesh GSPMD resolves"
                " this by replicate+reslice (involuntary full remat)"
                if gather_unsafe
                else "embedding table all-gathered over fsdp for the lookup"
            ),
        )

    # -- per-layer attention block
    if "fsdp" in _live(plan, "fsdp"):
        add(
            "layer.qkv",
            "allgather",
            ("fsdp",),
            _spec(None, "fsdp", "tp"),
            _spec(None, None, "tp"),
            note="ZeRO-3: layer projection weights all-gathered over fsdp",
        )
    if sp:
        if plan.ring_attention:
            add(
                "attn.ring",
                "permute",
                ("sp",),
                _spec(data, "sp", None, None),
                _spec(data, "sp", None, None),
                note="ring attention: K/V blocks rotate around sp via"
                " collective-permute, one hop per step",
            )
        else:
            add(
                "attn.kv_allgather",
                "allgather",
                ("sp",),
                _spec(data, "sp", None, None),
                _spec(data, None, None, None),
                note="full attention over a sp-sharded sequence gathers"
                " K/V along sp (use --ring-attention to stream instead)",
            )
    if tp:
        add(
            "layer.attn_out",
            "allreduce",
            ("tp",),
            _spec(data, seq_dim, "tp"),
            act,
            note="wo contracts the tp-sharded head dim: partial sums"
            " all-reduced over tp",
        )

    # -- FFN: dense MLP or MoE dispatch/combine
    if plan.model.is_moe:
        expert_layout = _spec(("ep", "tp"), None, None)  # [E, cap, d]
        if ep:
            dispatch_unsafe = not plan.remat_safe and bool(
                _live(plan, "fsdp", "sp")
            )
            add(
                "moe.dispatch",
                "full_remat" if dispatch_unsafe else "alltoall",
                _live(plan, "ep", "fsdp", "sp")
                if dispatch_unsafe
                else ("ep",),
                act,
                expert_layout,
                note=(
                    "token dispatch resharding batch/seq-sharded"
                    " activations onto the ep expert layout with no"
                    " output constraint: GSPMD replicates + reslices"
                    " (involuntary full remat) — pin the combine output"
                    " with with_sharding_constraint"
                    if dispatch_unsafe
                    else "tokens all-to-all'd onto the expert layout"
                ),
            )
            add(
                "moe.combine",
                "alltoall",
                ("ep",),
                expert_layout,
                act,
                note="expert outputs all-to-all'd back to the token layout",
            )
        elif tp:
            add(
                "moe.experts",
                "allreduce",
                ("tp",),
                _spec(("ep", "tp"), None, None),
                act,
                note="ep=1: experts shard over tp only; combine partial"
                " sums all-reduce over tp",
            )
    else:
        if tp:
            add(
                "layer.mlp_out",
                "allreduce",
                ("tp",),
                _spec(data, seq_dim, "tp"),
                act,
                note="w_down contracts the tp-sharded ffn dim: partial"
                " sums all-reduced over tp",
            )

    # -- pipeline stage boundary
    if pp:
        add(
            "pp.stage",
            "permute",
            ("pp",),
            act,
            act,
            note="microbatch activations hand off stage->stage over pp",
        )

    # -- cross-entropy over the (fsdp, tp)-sharded lm_head
    if not plan.serve:
        head_axes = _live(plan, "fsdp", "tp")
        if head_axes:
            add(
                "loss.ce",
                "allreduce",
                _live(plan, "tp") or head_axes,
                _spec(data, seq_dim, "tp"),
                _spec(data, seq_dim),
                note="vocab-sharded logits: softmax normalizer all-reduced"
                " over tp (lm_head all-gathered over fsdp)",
            )
        # -- backward gradient sync
        grad_axes = _live(plan, "dp")
        if grad_axes or "fsdp" in _live(plan, "fsdp"):
            add(
                "grad.sync",
                "allreduce",
                _live(plan, "dp", "fsdp"),
                _spec(None, "fsdp", "tp"),
                _spec(None, "fsdp", "tp"),
                note="backward: gradients reduce-scattered over fsdp and"
                " all-reduced over dp",
            )

    return ShardingFlow(
        boundaries=boundaries, batch_spec=batch_s, activation_spec=act_s
    )
