"""Jax-free static HBM-fit and collective-traffic cost model.

Given a resolved :class:`~torchx_tpu.analyze.plan.ParallelPlan` this
computes, with plain arithmetic:

* :func:`hbm_fit` — per-chip HBM bytes by component (params, optimizer
  state, gradients, activation footprint per remat policy, CE logits,
  KV pool for serve-shaped roles) against the per-chip budget. The
  sharding math follows ``models/llama.py param_specs`` (params over
  ``fsdp`` x ``tp``, layers over ``pp``; activations over
  ``dp``/``fsdp`` x ``sp``) and the optimizer follows
  ``parallel/aot_fit.model_state_bytes_per_device`` (AdamW: two moments
  in the param dtype, so model state = 3x params).
* :func:`collective_traffic` — per-step bytes each mesh axis moves per
  device (ring-algorithm ``(k-1)/k`` scaling), classified ICI vs DCN via
  :func:`~torchx_tpu.parallel.mesh_config.axis_networks`.

These are first-order estimates — no XLA fusion, padding or scheduling —
meant to be cross-checked against ``parallel/aot_fit.compile_fit`` (the
``tpx explain --aot`` mode) and the measured BENCH step-time breakdown
(``bench.py`` embeds both so prediction error is tracked per round).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

from torchx_tpu.analyze.plan import ParallelPlan
from torchx_tpu.parallel.mesh_config import axis_networks

GIB = 1024**3

#: fraction of per-chip HBM the fit may use (mirrors
#: ``parallel/aot_fit.DEFAULT_HEADROOM`` without importing it — aot_fit
#: imports jax at module level).
DEFAULT_HEADROOM = 0.9

#: mesh axes whose collectives are latency/bandwidth-critical enough that
#: routing them over DCN is (almost) always a mistake — the TPX702 set.
ICI_BOUND_AXES = ("fsdp", "ep", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class HbmFit:
    """Per-chip static memory fit."""

    components: dict[str, int]  # name -> bytes (per chip)
    total_bytes: int
    budget_bytes: int  # per-chip HBM capacity
    headroom: float
    fits: bool
    source: str  # where the budget came from (plan.hbm_source)

    @property
    def verdict(self) -> str:
        return "fits" if self.fits else "exceeds"

    def to_dict(self) -> dict:
        return {
            "components": dict(sorted(self.components.items())),
            "total_bytes": self.total_bytes,
            "budget_bytes": self.budget_bytes,
            "headroom": self.headroom,
            "usable_bytes": int(self.budget_bytes * self.headroom),
            "fits": self.fits,
            "verdict": self.verdict,
            "source": self.source,
        }


@dataclasses.dataclass(frozen=True)
class AxisTraffic:
    """Per-step collective bytes one mesh axis moves, per device."""

    axis: str
    size: int
    network: str  # ici | dcn | mixed
    bytes_per_step: int
    ops: tuple[str, ...]

    def to_dict(self) -> dict:
        return {
            "axis": self.axis,
            "size": self.size,
            "network": self.network,
            "bytes_per_step": self.bytes_per_step,
            "ops": list(self.ops),
        }


def _ring(k: int) -> float:
    """Ring-algorithm per-device traffic factor for a k-way collective."""
    return (k - 1) / k if k > 1 else 0.0


def _scale_of(calibration: Optional[Any], attr: str) -> float:
    """Extract one multiplicative correction from a calibration object
    (duck-typed: ``tune.calibrate.CalibrationScales`` or anything with
    the attribute). ``None``/absent/non-positive -> identity, so every
    existing caller and golden fixture is bit-identical."""
    if calibration is None:
        return 1.0
    try:
        scale = float(getattr(calibration, attr, 1.0) or 1.0)
    except (TypeError, ValueError):
        return 1.0
    return scale if scale > 0 else 1.0


def overlap_discount(calibration: Optional[Any]) -> float:
    """Fraction of the serialized collective time to actually charge:
    ``1 - overlap_frac`` from the calibration's measured comm/compute
    overlap (the step profiler's ``1 - exposed/modeled``). Duck-typed
    like :func:`_scale_of`; ``None``/absent/zero overlap -> 1.0, so
    uncalibrated predictions and golden fixtures stay bit-identical.
    Clamped so at least 5% of the collective time is always charged."""
    if calibration is None:
        return 1.0
    try:
        frac = float(getattr(calibration, "overlap_frac", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 1.0
    return 1.0 - min(max(frac, 0.0), 0.95)


def hbm_fit(
    plan: ParallelPlan,
    headroom: float = DEFAULT_HEADROOM,
    calibration: Optional[Any] = None,
) -> HbmFit:
    """Static per-chip HBM usage vs the plan's per-chip budget.

    ``calibration`` (a ``tune.calibrate.CalibrationScales`` learned from
    measured runs) rescales the activation term — the only component that
    is an estimate rather than exact arithmetic."""
    m = plan.model
    dtype = m.dtype_bytes
    pp = plan.axis("pp")
    tp = plan.axis("tp")
    sp = plan.axis("sp")
    ep = plan.axis("ep")
    data = plan.data_shards
    # params shard over (fsdp, tp); MoE expert weights over (ep, tp) — in
    # both cases the product of model-axis shards; layers split over pp.
    param_shards = pp * plan.axis("fsdp") * tp * (ep if m.is_moe else 1)
    # ceil-divide: a shard can't be smaller than one replica of the
    # unsharded remainder (norms, embeddings replicate over tp)
    param_bytes = math.ceil(m.param_count() * dtype / param_shards)

    comps: dict[str, int] = {}
    b_local = max(1, math.ceil(plan.batch / data))
    s_local = max(1, math.ceil(plan.seq / sp))

    if plan.serve:
        comps["params"] = (
            math.ceil(m.param_count() / param_shards)
            if plan.int8
            else param_bytes
        )
        # paged KV pool sized for max_batch full-length sequences
        # (serve/kv_pool.plan_pool block math, dense upper bound)
        comps["kv_pool"] = math.ceil(
            plan.max_batch
            * m.n_layers
            * 2  # K and V
            * m.max_seq
            * m.n_kv_heads
            * m.head_dim
            * dtype
            / tp
        )
        comps["decode_state"] = plan.max_batch * m.dim * dtype
        # a declared prefix-cache reserve holds that fraction of the pool
        # for cached prefixes ON TOP of the live-sequence budget above —
        # the fit verdict must see the worst case where both are full
        if plan.prefix_reserve > 0:
            comps["prefix_cache"] = math.ceil(
                plan.prefix_reserve * comps["kv_pool"]
            )
    else:
        comps["params"] = param_bytes
        comps["optimizer"] = 2 * param_bytes  # AdamW mu+nu in param dtype
        comps["gradients"] = param_bytes  # transient backward peak
        comps["activations"] = int(
            _activation_bytes(plan, b_local, s_local)
            * _scale_of(calibration, "activation_scale")
        )
        comps["logits"] = _logits_bytes(plan, b_local, s_local)
        comps["batch"] = b_local * plan.seq * 4 * 2  # tokens + targets i32

    total = sum(comps.values())
    budget = plan.hbm_bytes_per_chip
    return HbmFit(
        components=comps,
        total_bytes=total,
        budget_bytes=budget,
        headroom=headroom,
        fits=total <= int(budget * headroom),
        source=plan.hbm_source,
    )


def _activation_bytes(plan: ParallelPlan, b: int, s: int) -> int:
    """Per-chip activation footprint for the plan's remat policy.

    ``full`` keeps only the per-layer residual checkpoints (the
    ``lax.scan`` carry) plus one layer's working set; ``dots`` also saves
    every projection output per layer; ``dots_attn`` adds the attention
    output. Mirrors the ``jax.checkpoint`` policies models/llama.py
    installs.
    """
    m = plan.model
    dtype = m.dtype_bytes
    tp = plan.axis("tp")
    layers = max(1, math.ceil(m.n_layers / plan.axis("pp")))
    d = m.dim
    token_bytes = b * s * dtype  # one [b_local, s_local] slice, 1 unit wide

    residuals = layers * token_bytes * d
    saved = 0
    if plan.remat_policy in ("dots", "dots_attn"):
        per_layer_units = (
            m.n_heads * m.head_dim / tp  # q
            + 2 * m.n_kv_heads * m.head_dim / tp  # k, v
            + d  # attn residual add
            + 2 * m.ffn_dim / tp  # gate, up
            + d  # mlp residual add
        )
        if plan.remat_policy == "dots_attn":
            per_layer_units += m.n_heads * m.head_dim / tp
        saved = int(layers * token_bytes * per_layer_units)
    # one layer's live working set during (re)compute
    working_units = 4 * d + 2 * m.ffn_dim / tp
    working = int(token_bytes * working_units)
    if m.is_moe:
        # GShard dispatch/combine one-hots [b, s, E, capacity] in f32 and
        # the dispatched expert inputs [E/ep, capacity, d]
        e_local = max(1, math.ceil(m.n_experts / plan.axis("ep")))
        cap = max(1, int(m.capacity_factor * s * m.top_k / m.n_experts))
        working += 2 * b * s * m.n_experts * cap * 4
        working += e_local * cap * b * d * dtype
    return int(residuals + saved + working)


def _logits_bytes(plan: ParallelPlan, b: int, s: int) -> int:
    """CE logits footprint: f32 [b, chunk, vocab/tp] (+ its grad) when
    loss chunking is on, the full [b, s, vocab/tp] otherwise."""
    m = plan.model
    chunk = min(s, m.loss_chunk) if m.loss_chunk else s
    return int(2 * b * chunk * math.ceil(m.vocab_size / plan.axis("tp")) * 4)


def collective_traffic(
    plan: ParallelPlan, calibration: Optional[Any] = None
) -> list[AxisTraffic]:
    """Per-step, per-device collective bytes for every live mesh axis,
    classified ICI vs DCN from the slice topology. ``calibration``
    rescales every axis's bytes by the learned ``collective_scale``."""
    m = plan.model
    dtype = m.dtype_bytes
    pp = plan.axis("pp")
    tp = plan.axis("tp")
    sp = plan.axis("sp")
    ep = plan.axis("ep")
    dp = plan.axis("dp")
    fsdp = plan.axis("fsdp")
    data = plan.data_shards
    b = max(1, math.ceil(plan.batch / data))
    s = max(1, math.ceil(plan.seq / sp))
    layers = max(1, math.ceil(m.n_layers / pp))
    act_tok = b * s * dtype
    # param bytes one device must see un-fsdp-sharded (tp/ep/pp shards
    # stay local; fsdp is what gets gathered)
    param_slice = m.param_count() * dtype / (pp * tp * (ep if m.is_moe else 1))

    networks = axis_networks(plan.sizes, plan.chips_per_slice)
    coll_scale = _scale_of(calibration, "collective_scale")
    out: list[AxisTraffic] = []

    def add(axis: str, size: int, nbytes: float, ops: tuple[str, ...]):
        out.append(
            AxisTraffic(
                axis=axis,
                size=size,
                network=networks.get(axis, "none"),
                bytes_per_step=int(nbytes * coll_scale),
                ops=ops,
            )
        )

    if fsdp > 1 and not plan.serve:
        # ZeRO-3: all-gather params fwd + bwd, reduce-scatter grads
        add(
            "fsdp",
            fsdp,
            3 * _ring(fsdp) * param_slice,
            ("allgather_params_fwd", "allgather_params_bwd", "reducescatter_grads"),
        )
    if dp > 1 and not plan.serve:
        add(
            "dp",
            dp,
            2 * _ring(dp) * param_slice / fsdp,
            ("allreduce_grads",),
        )
    if tp > 1:
        # 2 all-reduces per layer (attn out, mlp/moe out), fwd + bwd
        # mirrors; all-reduce ring moves 2(k-1)/k x N
        ops_per_step = 4 * layers
        add(
            "tp",
            tp,
            ops_per_step * 2 * _ring(tp) * act_tok * m.dim,
            ("allreduce_partials",),
        )
    if sp > 1:
        kv_bytes = act_tok * 2 * m.n_kv_heads * m.head_dim
        if plan.ring_attention:
            add("sp", sp, layers * (sp - 1) * kv_bytes, ("ring_kv_permute",))
        else:
            add("sp", sp, layers * 2 * _ring(sp) * kv_bytes, ("allgather_kv",))
    if ep > 1 and m.is_moe:
        # dispatch + combine all-to-alls, fwd + bwd
        add(
            "ep",
            ep,
            4 * _ring(ep) * act_tok * m.dim * m.top_k,
            ("alltoall_dispatch", "alltoall_combine"),
        )
    if pp > 1:
        add(
            "pp",
            pp,
            2 * act_tok * m.dim * (pp - 1) / pp,
            ("stage_activations",),
        )
    return out
