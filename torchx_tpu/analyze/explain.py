"""Deep preflight: the ``tpx explain`` report and TPX7xx diagnostics.

Combines the jax-free plan IR (:mod:`~torchx_tpu.analyze.plan`), the
sharding propagation (:mod:`~torchx_tpu.analyze.propagation`) and the
cost model (:mod:`~torchx_tpu.analyze.costmodel`) into one report per
AppDef: every resharding boundary, the per-chip HBM fit, and per-axis
collective traffic classified ICI vs DCN — plus the TPX7xx diagnostics
the submit gate consumes (``rules.check_deep_preflight``).

TPX7xx family:

* **TPX700** (error) — propagation found a resharding boundary GSPMD
  resolves by involuntary full rematerialization.
* **TPX701** (error) — static HBM fit exceeds the per-chip budget.
* **TPX702** (warning) — a DCN-classified mesh axis carries
  fsdp/ep/tp/sp-scale collective traffic.
* **TPX703** (error) — the role looks plan-shaped but the mesh spec
  cannot resolve onto its device count.
* **TPX704** (warning) — a serve-shaped role's KV pool does not fit
  next to the parameters.
* **TPX705** (info) — no plan resolvable; deep preflight skipped
  (``tpx explain`` only — the submit gate stays silent and the TPX110
  heuristic covers the role).
* **TPX706** (error) — the role's resolved plan diverges from a pinned
  tune plan artifact (``$TPX_PLAN_ARTIFACT`` / ``--artifact``).
* **TPX707** (error) — the pinned plan artifact is unreadable, malformed
  or fails its content digest.

Every :func:`explain` run opens a ``launcher.explain`` span and bumps the
``tpx_explain_*`` metrics. The optional ``aot=True`` cross-check is the
single place this pipeline touches jax (lazily, via
``parallel/aot_fit.compile_fit``); everything else stays jax-free,
enforced by ``scripts/lint_internal.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from torchx_tpu.analyze import costmodel, propagation
from torchx_tpu.analyze.costmodel import ICI_BOUND_AXES
from torchx_tpu.analyze.diagnostics import Diagnostic, Severity
from torchx_tpu.analyze.plan import ParallelPlan, PlanError, plan_from_role
from torchx_tpu.specs.api import AppDef, Role

GIB = 1024**3


def _gib(n: int) -> str:
    return f"{n / GIB:.2f} GiB" if n >= GIB // 8 else f"{n / 2**20:.1f} MiB"


def deep_preflight(
    role: Role,
    *,
    devices: Optional[int] = None,
    hbm_bytes: Optional[int] = None,
    headroom: float = costmodel.DEFAULT_HEADROOM,
    calibration: Optional[Any] = None,
) -> tuple[Optional[ParallelPlan], list[Diagnostic]]:
    """Run the deep preflight over one role: ``(plan, diagnostics)``.

    ``plan`` is None when the role is not plan-shaped (TPX705 info is
    then the only diagnostic) or when the plan itself is broken (TPX703
    error). Shared by the submit-gate rule, ``tpx explain`` and the
    ``tpx tune`` static-prune stage (which passes its per-generation
    ``calibration`` scales so verdicts reflect measured reality).
    """
    try:
        plan = plan_from_role(role, devices=devices, hbm_bytes=hbm_bytes)
    except PlanError as e:
        return None, [
            Diagnostic(
                code="TPX703",
                severity=Severity.ERROR,
                role=role.name,
                field="args.--mesh",
                message=f"parallelism plan is inconsistent: {e}",
                hint="make the mesh axis sizes multiply out to the role's"
                " device count (slices x chips, or replicas x nproc)",
            )
        ]
    if plan is None:
        return None, [
            Diagnostic(
                code="TPX705",
                severity=Severity.INFO,
                role=role.name,
                message=(
                    "no parallelism plan resolvable from the role args (no"
                    " recognized --config); deep preflight skipped"
                ),
                hint="use a builtin --config name to enable static"
                " sharding/HBM analysis",
            )
        ]
    diags: list[Diagnostic] = []
    flow = propagation.propagate(plan)
    for b in flow.boundaries:
        if b.kind != "full_remat":
            continue
        diags.append(
            Diagnostic(
                code="TPX700",
                severity=Severity.ERROR,
                role=role.name,
                field=f"sharding.{b.op}",
                message=(
                    f"involuntary full rematerialization at {b.op}:"
                    f" {b.producer} -> {b.consumer} over"
                    f" {'/'.join(b.axes)} — {b.note}"
                ),
                hint="pin the gather/combine output with"
                " with_sharding_constraint (models/llama.py"
                " forward_features), or train with"
                " torchx_tpu.examples.train_llama",
            )
        )

    fit = costmodel.hbm_fit(plan, headroom=headroom, calibration=calibration)
    if not fit.fits:
        over = fit.total_bytes - int(fit.budget_bytes * fit.headroom)
        if plan.serve:
            diags.append(
                Diagnostic(
                    code="TPX704",
                    severity=Severity.WARNING,
                    role=role.name,
                    field="resource.tpu",
                    message=(
                        f"serve KV pool does not fit: params + {plan.max_batch}"
                        f"-slot KV pool need {_gib(fit.total_bytes)} of"
                        f" {_gib(int(fit.budget_bytes * fit.headroom))} usable"
                        f" HBM ({_gib(over)} over, budget {fit.source})"
                    ),
                    hint="lower --max-batch, shorten max_seq, or move to a"
                    " larger-HBM generation",
                )
            )
        else:
            diags.append(
                Diagnostic(
                    code="TPX701",
                    severity=Severity.ERROR,
                    role=role.name,
                    field="resource.tpu",
                    message=(
                        f"static HBM fit exceeded: {_gib(fit.total_bytes)}"
                        f" needed vs {_gib(int(fit.budget_bytes * fit.headroom))}"
                        f" usable per chip ({_gib(over)} over; components:"
                        + ", ".join(
                            f" {k}={_gib(v)}"
                            for k, v in sorted(
                                fit.components.items(),
                                key=lambda kv: -kv[1],
                            )
                        )
                        + f"; budget {fit.source})"
                    ),
                    hint="raise fsdp/tp, lower --batch/--seq, or use"
                    " --remat-policy full",
                )
            )

    traffic = costmodel.collective_traffic(plan, calibration=calibration)
    for t in traffic:
        if t.axis in ICI_BOUND_AXES and t.network in ("dcn", "mixed"):
            diags.append(
                Diagnostic(
                    code="TPX702",
                    severity=Severity.WARNING,
                    role=role.name,
                    field="args.--mesh",
                    message=(
                        f"mesh axis {t.axis}={t.size} spans the {t.network}"
                        f" network (slice size {plan.chips_per_slice}) but"
                        f" carries ~{_gib(t.bytes_per_step)}/step of"
                        f" {'/'.join(t.ops)} traffic — ICI-bound"
                        " collectives over DCN will pace the step"
                    ),
                    hint="keep fsdp/ep/tp/sp inside a slice and put only"
                    " dp/pp on the cross-slice dimension",
                )
            )
    return plan, diags


@dataclasses.dataclass
class ExplainReport:
    """The full deep-preflight report for one AppDef."""

    target: str = ""
    scheduler: Optional[str] = None
    roles: list[dict[str, Any]] = dataclasses.field(default_factory=list)

    @property
    def diagnostics(self) -> list[Diagnostic]:
        """All diagnostics across every role, in role order."""
        return [d for r in self.roles for d in r.get("_diags", [])]

    @property
    def has_errors(self) -> bool:
        """True when any diagnostic is error severity (CLI exit 1)."""
        return any(d.severity is Severity.ERROR for d in self.diagnostics)

    def summary(self) -> dict[str, int]:
        """Diagnostic counts by severity name."""
        out = {"error": 0, "warning": 0, "info": 0}
        for d in self.diagnostics:
            out[d.severity.value] += 1
        return out

    def to_dict(self) -> dict[str, Any]:
        """Stable JSON form (``tpx explain --json``; schema version 1,
        golden-filed in tests/test_explain.py)."""
        roles = []
        for r in self.roles:
            entry = {k: v for k, v in r.items() if not k.startswith("_")}
            entry["diagnostics"] = [d.to_dict() for d in r.get("_diags", [])]
            roles.append(entry)
        return {
            "version": 1,
            "target": self.target,
            "scheduler": self.scheduler,
            "roles": roles,
            "summary": self.summary(),
        }

    def render(self) -> str:
        """Human-readable multi-section report (what ``tpx explain``
        prints)."""
        s = self.summary()
        sched = f" [scheduler: {self.scheduler}]" if self.scheduler else ""
        lines = [
            f"{self.target or 'app'}: deep preflight — {s['error']} error(s),"
            f" {s['warning']} warning(s), {s['info']} info{sched}"
        ]
        for r in self.roles:
            plan = r.get("plan")
            if plan is None:
                lines.append(f"\nrole {r['role']}: no plan (deep preflight skipped)")
                for d in r.get("_diags", []):
                    lines.append(f"  {d.severity.value:<7} {d.code} {d.message}")
                continue
            mesh = ",".join(
                f"{a}={v}" for a, v in plan["mesh"].items() if v != 1
            ) or "(single device)"
            lines.append(
                f"\nrole {r['role']}: {plan['config']} on {plan['devices']}"
                f" device(s) ({plan['slices']} slice(s) x"
                f" {plan['chips_per_slice']} chips"
                f"{', ' + plan['accelerator'] if plan['accelerator'] else ''})"
                f"  mesh {mesh}  batch {plan['batch']} seq {plan['seq']}"
                f"  remat {plan['remat_policy']}"
            )
            sh = r["sharding"]
            lines.append(
                f"  sharding: activations {sh['activation_spec']}"
                + ("  ** INVOLUNTARY FULL REMAT **" if sh["full_remat"] else "")
            )
            if sh["boundaries"]:
                lines.append("  | boundary | kind | axes | producer -> consumer |")
                lines.append("  |---|---|---|---|")
                for b in sh["boundaries"]:
                    lines.append(
                        f"  | {b['op']} | {b['kind']} |"
                        f" {','.join(b['axes'])} |"
                        f" {b['producer']} -> {b['consumer']} |"
                    )
            hbm = r["hbm"]
            comp = ", ".join(
                f"{k} {_gib(v)}"
                for k, v in sorted(
                    hbm["components"].items(), key=lambda kv: -kv[1]
                )
            )
            lines.append(
                f"  hbm: {_gib(hbm['total_bytes'])} of"
                f" {_gib(hbm['usable_bytes'])} usable per chip"
                f" ({hbm['budget_bytes'] // GIB} GiB x {hbm['headroom']}"
                f" headroom, {hbm['source']}) -> {hbm['verdict'].upper()}"
            )
            lines.append(f"       {comp}")
            if r["collectives"]:
                lines.append("  | axis | size | network | bytes/step | ops |")
                lines.append("  |---|---|---|---|---|")
                for t in r["collectives"]:
                    lines.append(
                        f"  | {t['axis']} | {t['size']} | {t['network']} |"
                        f" {_gib(t['bytes_per_step'])} |"
                        f" {','.join(t['ops'])} |"
                    )
            art = r.get("artifact")
            if art:
                lines.append(
                    f"  artifact: pinned {art['digest'][:12]}… -> "
                    + (
                        "DIVERGES: " + "; ".join(art["diffs"])
                        if art["diverges"]
                        else "matches the tuned plan"
                    )
                )
            aot = r.get("aot")
            if aot:
                if aot.get("error"):
                    lines.append(f"  aot: cross-check failed: {aot['error']}")
                else:
                    lines.append(
                        f"  aot: compiled args {_gib(aot['args_bytes'])}"
                        f" (static {_gib(aot['static_state_bytes'])},"
                        f" {aot['state_agreement_pct']:+.1f}%), temps"
                        f" {_gib(aot['temp_bytes'])}, peak"
                        f" {_gib(aot['peak_bytes'])} ->"
                        f" {'FITS' if aot['fits'] else 'EXCEEDS'}"
                    )
            for d in r.get("_diags", []):
                lines.append(
                    f"  {d.severity.value:<7} {d.code} [{d.location}]"
                    f" {d.message}"
                )
                if d.hint:
                    lines.append(f"          fix: {d.hint}")
        return "\n".join(lines)


def artifact_diff_diagnostics(
    artifact_path: str, role_name: str, plan: Optional[ParallelPlan]
) -> tuple[list[Diagnostic], Optional[dict[str, Any]]]:
    """Diff one role's resolved plan against a pinned tune artifact.

    Returns ``(diagnostics, detail)`` — TPX707 when the artifact cannot
    be trusted (unreadable/malformed/digest mismatch), TPX706 when the
    plan diverges from the pinned winner on any tuned knob. ``detail``
    is the JSON-safe record ``tpx explain`` embeds (None for non-plan
    roles under a broken artifact). Shared by :func:`explain` and the
    submit gate's ``rules.check_plan_artifact``."""
    from torchx_tpu.tune.artifact import ArtifactError, load_artifact

    try:
        art = load_artifact(artifact_path)
    except ArtifactError as e:
        return [
            Diagnostic(
                code="TPX707",
                severity=Severity.ERROR,
                role=role_name,
                field="env.TPX_PLAN_ARTIFACT",
                message=f"pinned plan artifact rejected: {e}",
                hint="re-run `tpx tune` to regenerate the artifact; never"
                " edit it by hand (the digest is content-addressed)",
            )
        ], None
    if plan is None:
        return [], None
    diffs = art.diff_plan(plan.to_dict())
    detail: dict[str, Any] = {
        "path": artifact_path,
        "digest": art.digest,
        "candidate": art.candidate,
        "diverges": bool(diffs),
        "diffs": diffs,
    }
    if not diffs:
        return [], detail
    return [
        Diagnostic(
            code="TPX706",
            severity=Severity.ERROR,
            role=role_name,
            field="args",
            message=(
                "plan diverges from the pinned tune artifact"
                f" ({art.digest[:12]}…): " + "; ".join(diffs)
            ),
            hint="match the tuned config (see `tpx explain --artifact`),"
            " re-run `tpx tune`, or drop the $TPX_PLAN_ARTIFACT pin",
        )
    ], detail


def explain(
    app: AppDef,
    *,
    scheduler: Optional[str] = None,
    devices: Optional[int] = None,
    hbm_bytes: Optional[int] = None,
    headroom: float = costmodel.DEFAULT_HEADROOM,
    aot: bool = False,
    artifact: Optional[str] = None,
    calibration: Optional[Any] = None,
    session: str = "",
    gate: str = "api",
) -> ExplainReport:
    """Deep-preflight every role of ``app`` and return the report.

    ``artifact`` diffs each plan-shaped role against a pinned tune plan
    artifact (TPX706/707); ``calibration`` applies learned per-generation
    cost-model scales (see :mod:`torchx_tpu.tune.calibrate`)."""
    from torchx_tpu.obs import metrics as obs_metrics
    from torchx_tpu.obs import trace as obs_trace

    report = ExplainReport(target=app.name, scheduler=scheduler)
    with obs_trace.span(
        "launcher.explain",
        session=session,
        scheduler=scheduler,
        app=app.name,
        gate=gate,
    ) as sp:
        for role in app.roles:
            plan, diags = deep_preflight(
                role,
                devices=devices,
                hbm_bytes=hbm_bytes,
                headroom=headroom,
                calibration=calibration,
            )
            entry: dict[str, Any] = {"role": role.name, "_diags": diags}
            if artifact:
                art_diags, art_detail = artifact_diff_diagnostics(
                    artifact, role.name, plan
                )
                diags.extend(art_diags)
                if art_detail is not None:
                    entry["artifact"] = art_detail
            if plan is None:
                entry["plan"] = None
            else:
                flow = propagation.propagate(plan)
                fit = costmodel.hbm_fit(
                    plan, headroom=headroom, calibration=calibration
                )
                entry["plan"] = plan.to_dict()
                entry["sharding"] = flow.to_dict()
                entry["hbm"] = fit.to_dict()
                entry["collectives"] = [
                    t.to_dict()
                    for t in costmodel.collective_traffic(
                        plan, calibration=calibration
                    )
                ]
                obs_metrics.EXPLAIN_HBM_TOTAL_BYTES.set(
                    fit.total_bytes, role=role.name
                )
                if aot:
                    entry["aot"] = _aot_cross_check(plan, fit, headroom)
            report.roles.append(entry)
        summary = report.summary()
        if sp is not None:
            sp.attrs["errors"] = summary["error"]
            sp.attrs["warnings"] = summary["warning"]
    obs_metrics.EXPLAIN_RUNS.inc(
        gate=gate, status="errors" if report.has_errors else "clean"
    )
    for d in report.diagnostics:
        obs_metrics.EXPLAIN_DIAGNOSTICS.inc(
            code=d.code, severity=d.severity.value
        )
    return report


def _aot_cross_check(
    plan: ParallelPlan, fit: costmodel.HbmFit, headroom: float
) -> dict[str, Any]:
    """Cross-check the static fit against the XLA compiler's own memory
    analysis (``parallel/aot_fit.compile_fit``) — the ONE jax-importing
    path in this pipeline, entered only on ``--aot``.

    Compares the compiler's argument bytes (params + optimizer state +
    batch, what lives across steps) against the static prediction of the
    same quantity; temps are reported but not scored (the CPU backend's
    attention fallback inflates them far past TPU reality).
    """
    import os

    static_state = (
        fit.components.get("params", 0)
        + fit.components.get("optimizer", 0)
        + fit.components.get("batch", 0)
    )
    try:
        import jax  # noqa: F401 - deliberate lazy import

        if len(jax.devices()) != plan.devices:
            return {
                "error": (
                    f"plan needs {plan.devices} device(s) but the jax"
                    f" runtime has {len(jax.devices())}; set"
                    " XLA_FLAGS=--xla_force_host_platform_device_count="
                    f"{plan.devices} (before jax initializes)"
                ),
                "static_state_bytes": static_state,
            }
        import dataclasses as _dc

        import numpy as np
        from jax.sharding import Mesh

        from torchx_tpu.examples.train_llama import all_configs
        from torchx_tpu.parallel.aot_fit import compile_fit
        from torchx_tpu.parallel.mesh_config import AXES

        cfg = all_configs()[plan.model.name]()
        cfg = _dc.replace(
            cfg,
            remat_policy=plan.remat_policy if cfg.remat else cfg.remat_policy,
            use_ring_attention=plan.ring_attention,
        )
        shape = tuple(plan.axis(a) for a in AXES)
        devs = np.array(jax.devices()).reshape(shape)
        mesh = Mesh(devs, AXES)
        r = compile_fit(
            cfg,
            mesh,
            plan.batch,
            plan.seq,
            hbm_bytes=plan.hbm_bytes_per_chip,
            headroom=headroom,
        )
        agreement = (
            100.0 * (static_state - r.args_bytes) / r.args_bytes
            if r.args_bytes
            else 0.0
        )
        return {
            "args_bytes": int(r.args_bytes),
            "temp_bytes": int(r.temp_bytes),
            "peak_bytes": int(r.peak_bytes),
            "fits": bool(r.fits),
            "static_state_bytes": int(static_state),
            "state_agreement_pct": agreement,
            "platform": jax.default_backend(),
            "note": (
                "temps are a CPU-backend upper bound"
                if os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
                else ""
            ),
        }
    except Exception as e:  # noqa: BLE001 - aot is best-effort advisory
        return {"error": str(e), "static_state_bytes": static_state}
