"""TTL-with-coalescing cache for scheduler ``describe`` responses.

Every layer of the launcher polls: ``Runner.wait`` ticks, the supervisor
polls *through* ``wait``, ``tpx status`` scripts poll in loops, and log
streaming waits for the app to start. Without a cache each layer issues
its own control-plane call — duplicated gcloud/kubectl round-trips that
put the control plane back on the critical path. This cache gives every
``Runner`` three guarantees:

* **TTL sharing** — passive readers (``status``/``describe``) within
  ``TPX_DESCRIBE_CACHE_TTL`` seconds (default
  :data:`~torchx_tpu.settings.DEFAULT_DESCRIBE_CACHE_TTL`) share one
  backend response.
* **Coalescing** — concurrent fetches of the same app share one in-flight
  backend call instead of stampeding the control plane.
* **Terminal pinning** — a terminal state is immutable, so it is cached
  forever and can never be stale; ``wait``/``supervise`` loops that
  re-check a finished app cost zero backend calls.

``wait()`` polls pass ``fresh=True``: they are cache *writers* (always
refresh through to the backend, modulo coalescing), so a wait loop can
never spin on a stale non-terminal entry, and fault-injection /
resilience semantics of the underlying describe seam are preserved.

Errors are never cached; mutations (``cancel``/``delete``/``resize``)
must call :meth:`DescribeCache.invalidate`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from torchx_tpu import settings
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.schedulers.api import DescribeAppResponse
from torchx_tpu.specs.api import is_terminal


def cache_ttl() -> float:
    """TTL for non-terminal entries: $TPX_DESCRIBE_CACHE_TTL, else the
    default; malformed values fall back to the default, negatives clamp
    to 0 (= no caching of non-terminal states)."""
    raw = os.environ.get(settings.ENV_TPX_DESCRIBE_CACHE_TTL)
    if raw is None or not raw.strip():
        return settings.DEFAULT_DESCRIBE_CACHE_TTL
    try:
        return max(0.0, float(raw))
    except ValueError:
        return settings.DEFAULT_DESCRIBE_CACHE_TTL


class _Entry:
    __slots__ = ("resp", "at", "terminal")

    def __init__(self, resp: DescribeAppResponse, at: float, terminal: bool) -> None:
        self.resp = resp
        self.at = at
        self.terminal = terminal


class _Inflight:
    __slots__ = ("event", "resp", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.resp: Optional[DescribeAppResponse] = None
        self.error: Optional[BaseException] = None


class DescribeCache:
    """One instance per :class:`~torchx_tpu.runner.api.Runner`; keyed by
    ``(scheduler, app_id)``. Thread-safe (the fan-out paths hit it from
    worker threads)."""

    def __init__(self, ttl: Optional[float] = None) -> None:
        # ttl=None: read the env per call, so tests / long-lived runners
        # can retune without rebuilding the Runner
        self._ttl = ttl
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, str], _Entry] = {}
        self._inflight: dict[tuple[str, str], _Inflight] = {}

    def get(
        self,
        scheduler: str,
        app_id: str,
        fetch: Callable[[], Optional[DescribeAppResponse]],
        fresh: bool = False,
    ) -> Optional[DescribeAppResponse]:
        """The cached response, or ``fetch()`` routed through the cache.

        ``fresh=True`` (wait polls) bypasses the TTL — but still serves
        pinned terminal states and still coalesces onto an in-flight
        fetch (a result that just landed *is* fresh).
        """
        key = (scheduler, app_id)
        ttl = self._ttl if self._ttl is not None else cache_ttl()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and (
                entry.terminal
                or (not fresh and ttl > 0 and time.monotonic() - entry.at < ttl)
            ):
                obs_metrics.DESCRIBE_CACHE_HITS.inc(scheduler=scheduler)
                return entry.resp
            flight = self._inflight.get(key)
            if flight is None:
                flight = _Inflight()
                self._inflight[key] = flight
                owner = True
            else:
                owner = False
        if not owner:
            # coalesce: share the call another thread already has in flight
            flight.event.wait()
            if flight.error is not None:
                raise flight.error
            obs_metrics.DESCRIBE_CACHE_HITS.inc(scheduler=scheduler)
            return flight.resp
        obs_metrics.DESCRIBE_CACHE_MISSES.inc(scheduler=scheduler)
        try:
            resp = fetch()
        except BaseException as e:
            with self._lock:
                self._inflight.pop(key, None)
            flight.error = e  # errors are never cached
            flight.event.set()
            raise
        with self._lock:
            self._inflight.pop(key, None)
            if resp is not None:
                self._entries[key] = _Entry(
                    resp, time.monotonic(), is_terminal(resp.state)
                )
            else:
                # app no longer known to the backend: drop any stale entry
                self._entries.pop(key, None)
        flight.resp = resp
        flight.event.set()
        return resp

    def put(
        self, scheduler: str, app_id: str, resp: Optional[DescribeAppResponse]
    ) -> None:
        """Install a response a WATCH stream (reconciler) observed — the
        same writer path a completing ``get(fresh=True)`` takes: the entry
        is stamped now, terminal states are pinned forever, and ``None``
        (backend forgot the app) drops any stale entry. This is how watch
        events refresh the cache without a second cache layer."""
        with self._lock:
            if resp is None:
                self._entries.pop((scheduler, app_id), None)
                return
            self._entries[(scheduler, app_id)] = _Entry(
                resp, time.monotonic(), is_terminal(resp.state)
            )

    def invalidate(self, scheduler: str, app_id: Optional[str] = None) -> None:
        """Drop cached entries after a mutation (``cancel``/``delete``/
        ``resize``); ``app_id=None`` drops every entry for the scheduler."""
        with self._lock:
            if app_id is not None:
                self._entries.pop((scheduler, app_id), None)
            else:
                for key in [k for k in self._entries if k[0] == scheduler]:
                    del self._entries[key]
