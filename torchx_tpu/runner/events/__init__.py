"""Telemetry: every Runner API call is wrapped in :func:`log_event`.

Reference analog: torchx/runner/events/__init__.py:79-175. Events go to a
non-propagating logger named ``torchx_tpu.events`` whose destination is
pluggable via $TPX_EVENT_DESTINATION (default: "null" — drop; "console" —
stderr; "log" — normal logging; "jsonl"/"prom" — the durable obs sinks).
Organizations point this at their telemetry pipeline with a logging
handler.

This logger is also the span pipeline: :mod:`torchx_tpu.obs.trace`
serializes completed spans onto it, and when tracing is enabled
(``$TPX_TRACE``, default on) a JSONL sink is attached so both record kinds
persist under ``~/.torchx_tpu/obs/<session>/`` regardless of the chosen
destination. ``log_event`` opens a ``runner.<api>`` span around each call,
which is how the whole Runner surface shows up in ``tpx trace`` without
per-method instrumentation.
"""

from __future__ import annotations

import logging
import os
import time
import traceback
from types import TracebackType
from typing import Optional, Type

from torchx_tpu import settings
from torchx_tpu.runner.events.api import TpxEvent
from torchx_tpu.util.times import epoch_usec, stamp_event

_events_logger: Optional[logging.Logger] = None


def get_events_logger(destination: Optional[str] = None) -> logging.Logger:
    """The process-wide telemetry logger (non-propagating; destination
    from ``TPX_EVENT_DESTINATION``, default "null"). The durable JSONL
    trace sink rides alongside the chosen destination; it checks
    ``$TPX_TRACE`` per record, so attaching it unconditionally costs
    nothing when tracing is off."""
    global _events_logger
    if _events_logger is None:
        from torchx_tpu.obs.sinks import JsonlTraceHandler
        from torchx_tpu.runner.events.handlers import get_destination_handler

        dest = destination or os.environ.get(
            settings.ENV_TPX_EVENT_DESTINATION, "null"
        )
        logger = logging.getLogger("torchx_tpu.events")
        logger.setLevel(logging.INFO)
        logger.propagate = False  # never leak telemetry into app logs
        logger.addHandler(get_destination_handler(dest))
        if dest != "jsonl":  # don't write the trace file twice
            logger.addHandler(JsonlTraceHandler())
        _events_logger = logger
    return _events_logger


def record(event: TpxEvent) -> None:
    """Emit one serialized :class:`TpxEvent` to the events logger,
    stamping any unset time fields (:func:`~torchx_tpu.util.times.stamp_event`)
    and the current trace/span correlation ids at emit time."""
    stamp_event(event)
    if event.trace_id is None or event.span_id is None:
        from torchx_tpu.obs import trace as obs_trace

        if event.trace_id is None:
            event.trace_id = obs_trace.current_trace_id()
        if event.span_id is None:
            event.span_id = obs_trace.current_span_id()
    get_events_logger().info(event.serialize())


class log_event:
    """Context manager measuring cpu/wall time and capturing exceptions for
    one Runner API call. Also opens a ``runner.<api>`` span for the call's
    duration (sharing the event's timing and trace correlation) and feeds
    the API latency/call metrics."""

    def __init__(
        self,
        api: str,
        scheduler: str = "",
        app_id: Optional[str] = None,
        app_image: Optional[str] = None,
        runcfg: Optional[str] = None,
        session: str = "",
    ) -> None:
        self._event = TpxEvent(
            session=session,
            scheduler=scheduler,
            api=api,
            app_id=app_id,
            app_image=app_image,
            runcfg=runcfg,
        )

    def __enter__(self) -> "log_event":
        from torchx_tpu.obs import trace as obs_trace

        self._start_cpu = time.process_time_ns()
        self._start_wall = time.perf_counter_ns()
        self._event.start_epoch_time_usec = epoch_usec()
        self._span, self._token = obs_trace.start_span(
            f"runner.{self._event.api}",
            session=self._event.session,
            scheduler=self._event.scheduler or None,
            app_id=self._event.app_id,
        )
        if self._span is not None:
            self._event.trace_id = self._span.trace_id
            self._event.span_id = self._span.span_id
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc: Optional[BaseException],
        tb: Optional[TracebackType],
    ) -> bool:
        from torchx_tpu.obs import metrics as obs_metrics
        from torchx_tpu.obs import trace as obs_trace

        self._event.cpu_time_usec = (time.process_time_ns() - self._start_cpu) // 1000
        self._event.wall_time_usec = (time.perf_counter_ns() - self._start_wall) // 1000
        if exc is not None:
            self._event.raw_exception = "".join(
                traceback.format_exception(exc_type, exc, tb)
            )
            self._event.exception_type = exc_type.__name__ if exc_type else None
            if tb is not None:
                frame = traceback.extract_tb(tb)[-1]
                self._event.exception_source_location = (
                    f"{frame.filename}:{frame.lineno}:{frame.name}"
                )
        wall_s = self._event.wall_time_usec / 1e6
        obs_metrics.API_LATENCY.observe(
            wall_s, api=self._event.api, scheduler=self._event.scheduler
        )
        obs_metrics.API_CALLS.inc(
            api=self._event.api,
            scheduler=self._event.scheduler,
            status="error" if exc is not None else "ok",
        )
        if self._span is not None:
            # the call may have learned the app id mid-flight (schedule)
            if self._event.app_id:
                self._span.attrs["app_id"] = self._event.app_id
        obs_trace.end_span(self._span, self._token, exc=exc)
        record(self._event)
        return False
