"""Telemetry: every Runner API call is wrapped in :func:`log_event`.

Reference analog: torchx/runner/events/__init__.py:79-175. Events go to a
non-propagating logger named ``torchx_tpu.events`` whose destination is
pluggable via $TPX_EVENT_DESTINATION (default: "null" — drop; "console" —
stderr; "log" — normal logging). Organizations point this at their
telemetry pipeline with a logging handler.
"""

from __future__ import annotations

import logging
import os
import time
import traceback
from types import TracebackType
from typing import Optional, Type

from torchx_tpu.runner.events.api import TpxEvent

_events_logger: Optional[logging.Logger] = None


def get_events_logger(destination: Optional[str] = None) -> logging.Logger:
    """The process-wide telemetry logger (non-propagating; destination
    from ``TPX_EVENT_DESTINATION``, default "null")."""
    global _events_logger
    if _events_logger is None:
        from torchx_tpu.runner.events.handlers import get_destination_handler

        dest = destination or os.environ.get("TPX_EVENT_DESTINATION", "null")
        logger = logging.getLogger("torchx_tpu.events")
        logger.setLevel(logging.INFO)
        logger.propagate = False  # never leak telemetry into app logs
        logger.addHandler(get_destination_handler(dest))
        _events_logger = logger
    return _events_logger


def record(event: TpxEvent) -> None:
    """Emit one serialized :class:`TpxEvent` to the events logger."""
    get_events_logger().info(event.serialize())


class log_event:
    """Context manager measuring cpu/wall time and capturing exceptions for
    one Runner API call."""

    def __init__(
        self,
        api: str,
        scheduler: str = "",
        app_id: Optional[str] = None,
        app_image: Optional[str] = None,
        runcfg: Optional[str] = None,
        session: str = "",
    ) -> None:
        self._event = TpxEvent(
            session=session,
            scheduler=scheduler,
            api=api,
            app_id=app_id,
            app_image=app_image,
            runcfg=runcfg,
        )

    def __enter__(self) -> "log_event":
        self._start_cpu = time.process_time_ns()
        self._start_wall = time.perf_counter_ns()
        self._event.start_epoch_time_usec = int(time.time() * 1e6)
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc: Optional[BaseException],
        tb: Optional[TracebackType],
    ) -> bool:
        self._event.cpu_time_usec = (time.process_time_ns() - self._start_cpu) // 1000
        self._event.wall_time_usec = (time.perf_counter_ns() - self._start_wall) // 1000
        if exc is not None:
            self._event.raw_exception = "".join(
                traceback.format_exception(exc_type, exc, tb)
            )
            self._event.exception_type = exc_type.__name__ if exc_type else None
            if tb is not None:
                frame = traceback.extract_tb(tb)[-1]
                self._event.exception_source_location = (
                    f"{frame.filename}:{frame.lineno}:{frame.name}"
                )
        record(self._event)
        return False
