"""Pluggable telemetry destinations (reference analog:
torchx/runner/events/handlers.py).

The events logger routes through one handler chosen by
$TPX_EVENT_DESTINATION: "null" (default — drop), "console"/"log" (stderr),
"jsonl" (durable trace sink under ~/.torchx_tpu/obs/<session>/), "prom"
(Prometheus textfile metrics flusher). Organizations register richer
destinations (e.g. a BigQuery or Cloud Logging shipper) with
:func:`register_destination` or the ``tpx.event_handlers`` entry-point
group.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable


def _jsonl_handler() -> logging.Handler:
    from torchx_tpu.obs.sinks import JsonlTraceHandler

    return JsonlTraceHandler()


def _prom_handler() -> logging.Handler:
    from torchx_tpu.obs.sinks import PromMetricsHandler

    return PromMetricsHandler()


_DESTINATIONS: dict[str, Callable[[], logging.Handler]] = {
    "null": logging.NullHandler,
    "console": lambda: logging.StreamHandler(sys.stderr),
    "log": lambda: logging.StreamHandler(sys.stderr),
    # durable obs sinks (lazy imports: handlers.py must stay import-light)
    "jsonl": _jsonl_handler,
    "prom": _prom_handler,
}

# Entry-point factories already resolved once: load_group re-reads the
# installed-distribution metadata on every call, which is milliseconds of
# filesystem work — far too slow to repeat per get_events_logger miss.
_RESOLVED_EP_FACTORIES: dict[str, Callable[[], logging.Handler]] = {}


def register_destination(name: str, factory: Callable[[], logging.Handler]) -> None:
    _DESTINATIONS[name] = factory


def get_destination_handler(dest: str) -> logging.Handler:
    factory = _DESTINATIONS.get(dest) or _RESOLVED_EP_FACTORIES.get(dest)
    if factory is None:
        from torchx_tpu.util.entrypoints import load_group

        ep = load_group("tpx.event_handlers").get(dest)
        if ep is not None:
            try:
                factory = ep()
            except Exception as e:  # noqa: BLE001 - fall back to null
                logging.getLogger(__name__).warning(
                    "event destination %r failed to load (%s);"
                    " telemetry will be dropped",
                    dest,
                    e,
                )
                factory = None
            else:
                # cache successes only: a broken handler should be retried
                # (and re-warned about) on the next resolution
                _RESOLVED_EP_FACTORIES[dest] = factory
    if factory is None:
        factory = logging.NullHandler
    try:
        return factory()
    except Exception as e:  # noqa: BLE001 - telemetry must never break clients
        logging.getLogger(__name__).warning(
            "event handler %r failed to construct (%s); dropping telemetry",
            dest,
            e,
        )
        return logging.NullHandler()
