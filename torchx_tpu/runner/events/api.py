"""Telemetry event model (reference analog: torchx/runner/events/api.py:24-58)."""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, fields
from typing import Optional


@dataclass
class TpxEvent:
    """One client-API telemetry record.

    ``trace_id``/``span_id`` correlate the event with the active
    :class:`~torchx_tpu.obs.trace.Span` (stamped at emit by
    :func:`~torchx_tpu.runner.events.record`), so the JSONL sink's events
    attach to the right node of the ``tpx trace`` timeline.
    """

    session: str
    scheduler: str
    api: str
    app_id: Optional[str] = None
    app_image: Optional[str] = None
    app_metadata: Optional[dict] = None
    runcfg: Optional[str] = None
    source: str = "UNKNOWN"
    cpu_time_usec: Optional[int] = None
    wall_time_usec: Optional[int] = None
    start_epoch_time_usec: Optional[int] = None
    raw_exception: Optional[str] = None
    exception_type: Optional[str] = None
    exception_source_location: Optional[str] = None
    trace_id: Optional[str] = None
    span_id: Optional[str] = None

    def __str__(self) -> str:
        return self.serialize()

    def serialize(self) -> str:
        return json.dumps(asdict(self))

    @staticmethod
    def deserialize(data: str) -> "TpxEvent":
        """Parse a serialized event, dropping unknown fields — an old
        reader must survive records written by a newer emitter (the JSONL
        sink persists events across versions)."""
        obj = json.loads(data)
        known = {f.name for f in fields(TpxEvent)}
        return TpxEvent(**{k: v for k, v in obj.items() if k in known})
