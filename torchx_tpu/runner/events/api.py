"""Telemetry event model (reference analog: torchx/runner/events/api.py:24-58)."""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass
from typing import Optional


@dataclass
class TpxEvent:
    """One client-API telemetry record."""

    session: str
    scheduler: str
    api: str
    app_id: Optional[str] = None
    app_image: Optional[str] = None
    app_metadata: Optional[dict] = None
    runcfg: Optional[str] = None
    source: str = "UNKNOWN"
    cpu_time_usec: Optional[int] = None
    wall_time_usec: Optional[int] = None
    start_epoch_time_usec: Optional[int] = None
    raw_exception: Optional[str] = None
    exception_type: Optional[str] = None
    exception_source_location: Optional[str] = None

    def __str__(self) -> str:
        return self.serialize()

    def serialize(self) -> str:
        return json.dumps(asdict(self))

    @staticmethod
    def deserialize(data: str) -> "TpxEvent":
        return TpxEvent(**json.loads(data))
