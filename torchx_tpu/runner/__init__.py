from torchx_tpu.runner.api import Runner, get_runner  # noqa: F401
