"""Runner: session-scoped client API over all schedulers.

Reference analog: torchx/runner/api.py (679 LoC). The Runner resolves
components, materializes AppDefs, builds workspaces, submits via the chosen
scheduler, and exposes the full monitor surface
(status/wait/cancel/delete/describe/log_lines/list). Every public call is
wrapped in a telemetry :func:`log_event`.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import random
import threading
import time
from datetime import datetime
from types import TracebackType
from typing import Any, Callable, Iterable, Iterator, Mapping, Optional, Type

from torchx_tpu import settings
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.runner.describe_cache import DescribeCache
from torchx_tpu.runner.events import log_event
from torchx_tpu.schedulers import (
    SchedulerFactory,
    get_scheduler_factories,
)
from torchx_tpu.schedulers.api import ListAppResponse, Scheduler, Stream
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppHandle,
    AppStatus,
    CfgVal,
    make_app_handle,
    parse_app_handle,
    runopts,
)
from torchx_tpu.util.session import get_session_id_or_create_new
from torchx_tpu.util.times import poll_intervals

logger = logging.getLogger(__name__)


class UnknownSchedulerError(KeyError):
    """Raised when a handle/arg names a scheduler that is not registered."""

    def __init__(self, scheduler: str, available: list[str]) -> None:
        self.message = (
            f"unknown scheduler {scheduler!r}; available: {available}"
        )
        super().__init__(self.message)

    def __str__(self) -> str:
        return self.message


class Runner:
    """A named session owning lazily-created scheduler instances."""

    def __init__(
        self,
        name: str,
        scheduler_factories: Mapping[str, SchedulerFactory],
        component_defaults: Optional[Mapping[str, Mapping[str, str]]] = None,
        scheduler_params: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self._name = name
        self._scheduler_factories = dict(scheduler_factories)
        self._scheduler_instances: dict[str, Scheduler] = {}
        self._component_defaults = dict(component_defaults or {})
        self._scheduler_params = dict(scheduler_params or {})
        self._describe_cache = DescribeCache()
        # set via attach_reconciler: wait() then wakes on watch events
        # instead of sleeping out its poll interval
        self._reconciler: Optional[Any] = None
        # fan-out paths create scheduler instances from worker threads
        self._sched_locks_guard = threading.Lock()
        self._sched_locks: dict[str, threading.Lock] = {}

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "Runner":
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc: Optional[BaseException],
        tb: Optional[TracebackType],
    ) -> bool:
        self.close()
        return False

    def close(self) -> None:
        """Release every lazily-created scheduler instance (also runs on
        context-manager exit); the Runner must not be used afterwards."""
        for sched in self._scheduler_instances.values():
            sched.close()
        self._scheduler_instances.clear()

    # -- component path ----------------------------------------------------

    def run_component(
        self,
        component: str,
        component_args: list[str],
        scheduler: str,
        cfg: Optional[Mapping[str, CfgVal]] = None,
        workspace: Optional[str] = None,
        parent_run_id: Optional[str] = None,
        no_lint: bool = False,
    ) -> AppHandle:
        """Resolve a component (builtin name / file.py:fn), materialize it
        with the given CLI-style args, and run it."""
        with obs_trace.span(
            "runner.run_component",
            session=self._name,
            component=component,
            scheduler=scheduler,
        ):
            dryrun_info = self.dryrun_component(
                component,
                component_args,
                scheduler,
                cfg,
                workspace,
                parent_run_id,
                no_lint=no_lint,
            )
            return self.schedule(dryrun_info)

    def dryrun_component(
        self,
        component: str,
        component_args: list[str],
        scheduler: str,
        cfg: Optional[Mapping[str, CfgVal]] = None,
        workspace: Optional[str] = None,
        parent_run_id: Optional[str] = None,
        no_lint: bool = False,
    ) -> AppDryRunInfo:
        """:meth:`run_component` up to (and including) the scheduler's
        dryrun: returns the fully materialized request without submitting
        — the launcher's central testability/inspection hook."""
        from torchx_tpu.specs.builders import materialize_appdef
        from torchx_tpu.specs.finder import get_component

        component_def = get_component(component)
        app = materialize_appdef(
            component_def.fn,
            component_args,
            self._component_defaults.get(component),
        )
        return self.dryrun(
            app,
            scheduler,
            cfg,
            workspace=workspace,
            parent_run_id=parent_run_id,
            no_lint=no_lint,
        )

    # -- run path ----------------------------------------------------------

    def run(
        self,
        app: AppDef,
        scheduler: str,
        cfg: Optional[Mapping[str, CfgVal]] = None,
        workspace: Optional[str] = None,
        parent_run_id: Optional[str] = None,
        no_lint: bool = False,
    ) -> AppHandle:
        """Run a pre-built AppDef: :meth:`dryrun` then :meth:`schedule`."""
        with obs_trace.span(
            "runner.run", session=self._name, scheduler=scheduler, app=app.name
        ):
            dryrun_info = self.dryrun(
                app,
                scheduler,
                cfg,
                workspace=workspace,
                parent_run_id=parent_run_id,
                no_lint=no_lint,
            )
            return self.schedule(dryrun_info)

    def dryrun(
        self,
        app: AppDef,
        scheduler: str,
        cfg: Optional[Mapping[str, CfgVal]] = None,
        workspace: Optional[str] = None,
        parent_run_id: Optional[str] = None,
        no_lint: bool = False,
    ) -> AppDryRunInfo:
        """Validate + lint + build workspace + materialize the scheduler
        request.

        The preflight analyzer (:mod:`torchx_tpu.analyze`) gates here:
        error-severity diagnostics raise
        :class:`~torchx_tpu.analyze.LintError` before anything is built.
        Bypass with ``no_lint=True`` (CLI ``--no-lint``) or ``TPX_NO_LINT=1``.

        Works on a deep copy: workspace builds mutate role.image and tracker
        env injection mutates role.env; the caller's AppDef stays pristine.
        """
        app = copy.deepcopy(app)
        cfg = dict(cfg or {})
        # validation (reference runner/api.py:346-369)
        if not app.roles:
            raise ValueError(f"AppDef {app.name} has no roles")
        for role in app.roles:
            if not role.entrypoint:
                raise ValueError(f"role {role.name} has no entrypoint")
            if role.num_replicas <= 0:
                raise ValueError(
                    f"role {role.name} has num_replicas={role.num_replicas}; must be > 0"
                )
            if role.min_replicas is not None and not (
                0 < role.min_replicas <= role.num_replicas
            ):
                raise ValueError(
                    f"role {role.name}: 0 < min_replicas <= num_replicas violated"
                )

        sched = self._scheduler(scheduler)
        if not no_lint and os.environ.get(
            settings.ENV_TPX_NO_LINT, ""
        ).strip().lower() not in ("1", "true", "yes", "on"):
            from torchx_tpu.analyze import LintError, analyze

            report = analyze(
                app,
                scheduler=scheduler,
                cfg=cfg,
                capabilities=sched.capabilities,  # None -> registry lookup
                gate="runner",
                session=self._name,
            )
            if report.has_errors:
                raise LintError(report)
        with log_event(
            "dryrun",
            scheduler,
            app_image=app.roles[0].image,
            runcfg=json.dumps(cfg, default=str),
            session=self._name,
        ):
            self._inject_tracker_env(app, parent_run_id)
            self._inject_trace_env(app)
            resolved_cfg = sched.run_opts().resolve(cfg)
            sched._pre_build_validate(app, resolved_cfg)
            from torchx_tpu.specs.api import Workspace
            from torchx_tpu.workspace.api import WorkspaceMixin

            if isinstance(sched, WorkspaceMixin):
                if workspace:
                    ws = Workspace.from_str(workspace)
                    for role in app.roles:
                        role.workspace = (
                            ws if role.workspace is None else ws.merge_into(role.workspace)
                        )
                with obs_trace.span(
                    "workspace.build", session=self._name, scheduler=scheduler
                ):
                    sched.build_workspaces(app.roles, resolved_cfg)
            sched._validate(app, resolved_cfg)
            return sched.materialize_dryrun(app, resolved_cfg)

    def schedule(self, dryrun_info: AppDryRunInfo) -> AppHandle:
        """Submit a request produced by :meth:`dryrun`/:meth:`dryrun_component`
        and return its ``scheduler://session/app_id`` handle."""
        scheduler = dryrun_info._scheduler
        if not scheduler:
            raise ValueError(
                "dryrun_info was not produced by Runner.dryrun/submit_dryrun"
            )
        sched = self._scheduler(scheduler)
        app = dryrun_info._app
        with log_event(
            "schedule",
            scheduler,
            app_image=app.roles[0].image if app and app.roles else None,
            session=self._name,
        ) as ev:
            launch_start = time.perf_counter()
            app_id = sched.schedule(dryrun_info)
            obs_metrics.LAUNCH_SECONDS.observe(
                time.perf_counter() - launch_start, scheduler=scheduler
            )
            handle = make_app_handle(scheduler, self._name, app_id)
            ev._event.app_id = app_id
            if app:
                logger.info("launched app %s on %s", app_id, scheduler)
            return handle

    # -- monitor path ------------------------------------------------------

    def attach_reconciler(self, reconciler: Any) -> None:
        """Join this runner to a control-plane reconciler
        (:class:`~torchx_tpu.control.reconciler.Reconciler`): watch events
        refresh this runner's describe cache through its writer path, and
        :meth:`wait` wakes on events instead of sleeping out its poll
        interval (the poll loop stays as the fallback — a dead watch
        stream degrades latency, never correctness)."""
        self._reconciler = reconciler
        reconciler.bind_cache(self._describe_cache)

    def _wait_tick(
        self,
        scheduler: str,
        app_id: str,
        interval: float,
        sleep: Callable[[float], None],
    ) -> None:
        """One wait-loop pause: block on the reconciler's condition
        variable when a reconciler is attached (a watch event — or an
        already-recorded terminal — returns early and the next poll is
        served from the pinned cache entry), else plain sleep."""
        rec = self._reconciler
        if rec is not None:
            try:
                if rec.wait_event(scheduler, app_id, timeout=interval) is not None:
                    obs_metrics.WAITER_WAKEUPS.inc(scheduler=scheduler)
                # a timeout also consumed the full interval blocking on
                # the condition variable — never sleep on top of it
                return
            except Exception:  # noqa: BLE001 - wake path is an optimization
                logger.debug("reconciler wait_event failed", exc_info=True)
        sleep(interval)

    def status(
        self, app_handle: AppHandle, fresh: bool = False
    ) -> Optional[AppStatus]:
        """Current :class:`AppStatus` of the app, or None when the
        scheduler no longer knows the id. Terminal failures carry the
        scheduler's :class:`FailureClass` (``classify_failure`` hook), so
        ``tpx status`` shows ``FAILED (preemption)`` when the backend can
        tell.

        Served through the Runner's describe cache
        (:mod:`~torchx_tpu.runner.describe_cache`): repeat reads within
        the TTL and concurrent reads of the same app share one backend
        call, and terminal states are pinned (never re-fetched).
        ``fresh=True`` (what :meth:`wait` polls use) refreshes through to
        the backend — still coalescing with any in-flight fetch."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        sched = self._scheduler(scheduler)
        with log_event("status", scheduler, app_id, session=self._name):
            desc = self._describe_cache.get(
                scheduler, app_id, lambda: sched.describe(app_id), fresh=fresh
            )
            if desc is None:
                return None
            return AppStatus(
                state=desc.state,
                num_restarts=desc.num_restarts,
                msg=desc.msg,
                structured_error_msg=desc.structured_error_msg,
                ui_url=desc.ui_url,
                roles=desc.roles_statuses,
                failure_class=sched.classify_failure(desc),
            )

    def wait(
        self,
        app_handle: AppHandle,
        wait_interval: float = 10,
        timeout: Optional[float] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        poll_miss_budget: int = 0,
    ) -> Optional[AppStatus]:
        """Block until the app reaches a terminal state.

        Polls with jittered incremental backoff (1s ramping up to
        ``wait_interval``; see :func:`~torchx_tpu.util.times.poll_intervals`)
        so short jobs return fast without hammering the control plane on
        long ones. ``timeout`` (seconds) raises :class:`TimeoutError` if no
        terminal state arrives in time — the app keeps running. ``sleep``
        and ``rng`` are injectable for deterministic tests.

        ``poll_miss_budget`` > 0 absorbs that many *consecutive* status
        polls failing with a transient error (as classified by
        :func:`torchx_tpu.resilience.errors.classify_exception`, AFTER the
        scheduler's own in-call retries are spent): each miss degrades to a
        warning plus a ``poll_degraded`` event instead of surfacing, and a
        successful poll resets the count. Permanent errors always raise —
        a long wait must not hide an auth failure.

        The whole wait is one ``runner.wait`` span (each status poll nests
        under it), with the poll count in attrs and the per-scheduler poll
        counter metric incremented as it goes."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        if self._reconciler is not None:
            # join the backend's watch stream: terminal transitions then
            # wake this wait immediately via _wait_tick
            self._reconciler.track(scheduler, self._scheduler(scheduler), app_id)
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        polls = 0
        misses = 0
        with obs_trace.span(
            "runner.wait", session=self._name, scheduler=scheduler, app_id=app_id
        ) as sp:
            for interval in poll_intervals(
                initial=min(1.0, wait_interval), max_interval=wait_interval, rng=rng
            ):
                try:
                    # fresh=True: wait is the cache WRITER — every tick
                    # refreshes the entry that passive readers share
                    status = self.status(app_handle, fresh=True)
                    misses = 0
                except Exception as e:
                    from torchx_tpu.resilience.errors import (
                        classify_exception,
                        is_transient,
                    )

                    misses += 1
                    kind = classify_exception(e)
                    if not is_transient(kind) or misses > poll_miss_budget:
                        raise
                    self._emit_poll_degraded(
                        scheduler, app_id, e, kind, misses, poll_miss_budget
                    )
                    if deadline is not None and time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"app {app_handle} status unknown after {timeout}s"
                            " (polls failing)"
                        ) from e
                    self._wait_tick(scheduler, app_id, interval, sleep)
                    continue
                polls += 1
                obs_metrics.WAIT_POLLS.inc(scheduler=scheduler)
                if sp is not None:
                    sp.attrs["polls"] = polls
                if status is None or status.is_terminal():
                    if sp is not None and status is not None:
                        sp.attrs["state"] = str(status.state)
                    return status
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"app {app_handle} still {status.state} after"
                            f" {timeout}s"
                        )
                    interval = min(interval, remaining)
                self._wait_tick(scheduler, app_id, interval, sleep)
        raise AssertionError("unreachable: poll_intervals is infinite")

    def _emit_poll_degraded(
        self,
        scheduler: str,
        app_id: str,
        exc: Exception,
        kind: object,
        misses: int,
        budget: int,
    ) -> None:
        """One absorbed status-poll failure: warn + ``poll_degraded``
        TpxEvent (api="supervise" — this is the supervision audit trail
        answering "why did status go quiet for two minutes at 3am")."""
        from torchx_tpu.runner.events import record
        from torchx_tpu.runner.events.api import TpxEvent

        logger.warning(
            "status poll for %s failed (%s: %s); absorbed miss %d/%d",
            app_id,
            kind,
            exc,
            misses,
            budget,
        )
        record(
            TpxEvent(
                session=self._name,
                scheduler=scheduler,
                api="supervise",
                app_id=app_id,
                app_metadata={
                    "transition": "poll_degraded",
                    "kind": str(kind),
                    "error": str(exc)[:500],
                    "miss": misses,
                    "budget": budget,
                },
            )
        )

    def cancel(self, app_handle: AppHandle) -> None:
        """Stop the app but keep it describable (scheduler-side state and
        logs are preserved where the backend allows)."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        with log_event("cancel", scheduler, app_id, session=self._name):
            self._scheduler(scheduler).cancel(app_id)
            self._describe_cache.invalidate(scheduler, app_id)

    def delete(self, app_handle: AppHandle) -> None:
        """Remove the app from the scheduler entirely (cancel + forget)."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        with log_event("delete", scheduler, app_id, session=self._name):
            self._scheduler(scheduler).delete(app_id)
            self._describe_cache.invalidate(scheduler, app_id)

    def resize(
        self, app_handle: AppHandle, role_name: str, num_replicas: int
    ) -> None:
        """Resize a running role's gang (AppDef units: slices for TPU
        roles). The gang restarts with a coherent world and resumes from
        its checkpoint; backends without resize support raise."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        with log_event("resize", scheduler, app_id, session=self._name):
            self._scheduler(scheduler).resize(app_id, role_name, num_replicas)
            self._describe_cache.invalidate(scheduler, app_id)

    def watch_elastic(
        self,
        app_handle: AppHandle,
        poll_interval: float = 10.0,
        timeout: Optional[float] = None,
        max_restarts: int = 3,
    ) -> int:
        """Run the failure-driven elastic controller for an app: observe
        gang failures and auto-shrink roles with a ``min_replicas`` floor
        (the operator-side analog of the local scheduler's elastic
        restart). Blocks until the app terminates, the floor is breached,
        or the restart budget is spent; returns shrink-restarts performed.
        Backends without a watcher raise."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        sched = self._scheduler(scheduler)
        watch = getattr(sched, "watch_elastic", None)
        if watch is None:
            raise ValueError(
                f"the {scheduler} scheduler has no elastic watcher"
                " (local restarts elastically on its own; others need"
                " operator resize)"
            )
        with log_event("watch_elastic", scheduler, app_id, session=self._name):
            return watch(
                app_id,
                poll_interval=poll_interval,
                timeout=timeout,
                max_restarts=max_restarts,
            )

    def supervise(
        self,
        dryrun_info: AppDryRunInfo,
        policy: Optional[Any] = None,
        session: Optional[str] = None,
    ) -> Any:
        """Run a dryrun under the preemption-aware supervisor: submit,
        watch to terminal, classify the failure, and auto-resubmit within
        the policy's per-class retry budgets, resuming from the latest
        checkpoint step when the policy names a checkpoint dir. With
        ``policy.elastic`` each attempt additionally runs the backend's
        elastic watcher (:meth:`watch_elastic`). Blocks until success or
        budget exhaustion; returns a
        :class:`~torchx_tpu.supervisor.api.SupervisorResult`.

        ``policy`` is a :class:`~torchx_tpu.supervisor.policy.SupervisorPolicy`
        (default-constructed when omitted); typed ``Any`` here only to keep
        the supervisor subsystem an optional import at runner load time.
        ``session`` names the durable supervision session (auto-generated
        when omitted); ``tpx supervise --resume <session>`` reattaches to
        it after a client crash."""
        from torchx_tpu.supervisor.api import Supervisor

        scheduler = dryrun_info._scheduler or ""
        app = dryrun_info._app
        with log_event(
            "supervise",
            scheduler,
            app_image=app.roles[0].image if app and app.roles else None,
            session=self._name,
        ) as ev:
            result = Supervisor(self, dryrun_info, policy, session=session).run()
            if result.handle:
                _, _, app_id = parse_app_handle(result.handle)
                ev._event.app_id = app_id
            ev._event.app_metadata = {
                "attempts": result.attempts,
                "succeeded": result.succeeded,
                "budget_exhausted": (
                    str(result.budget_exhausted)
                    if result.budget_exhausted
                    else None
                ),
            }
            return result

    def describe(self, app_handle: AppHandle) -> Optional[AppDef]:
        """Best-effort reconstruction of the AppDef from the backend
        (served through the describe cache, like :meth:`status`)."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        with log_event("describe", scheduler, app_id, session=self._name):
            desc = self._describe_cache.get(
                scheduler, app_id, lambda: self._scheduler(scheduler).describe(app_id)
            )
            if desc is None:
                return None
            return AppDef(name=app_id, roles=desc.roles)

    def list(self, scheduler: str) -> list[ListAppResponse]:
        """All apps the backend knows about (any session)."""
        with log_event("list", scheduler, session=self._name):
            return self._scheduler(scheduler).list()

    def list_all(
        self,
        schedulers: Optional[Iterable[str]] = None,
        max_workers: int = 8,
    ) -> tuple[dict[str, list[ListAppResponse]], dict[str, Exception]]:
        """:meth:`list` fanned out across backends on a bounded thread
        pool, so one slow/unreachable control plane no longer serializes
        the whole listing.

        Returns ``(results, errors)``, each keyed by scheduler name.
        Ordering is deterministic: both dicts iterate in registry order
        (the order of ``scheduler_backends()``), regardless of which
        backend answered first. A backend that raises lands in ``errors``
        and never hides the others' results."""
        names = (
            list(schedulers)
            if schedulers is not None
            else list(self._scheduler_factories)
        )
        for name in names:
            if name not in self._scheduler_factories:
                raise UnknownSchedulerError(name, list(self._scheduler_factories))
        results: dict[str, list[ListAppResponse]] = {}
        errors: dict[str, Exception] = {}
        if not names:
            return results, errors
        from concurrent.futures import ThreadPoolExecutor

        with obs_trace.span(
            "runner.list_all", session=self._name, schedulers=",".join(names)
        ):
            with ThreadPoolExecutor(
                max_workers=min(max_workers, len(names)),
                thread_name_prefix="tpx-list",
            ) as pool:
                futures = {name: pool.submit(self.list, name) for name in names}
            for name in names:
                try:
                    results[name] = futures[name].result()
                except Exception as e:  # noqa: BLE001 - reported per backend
                    errors[name] = e
        return results, errors

    def log_lines(
        self,
        app_handle: AppHandle,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[datetime] = None,
        until: Optional[datetime] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        """Stream one replica's log lines, optionally regex-filtered,
        time-windowed (``since``/``until``), and followed (``should_tail``)
        — the unified log access every backend implements."""
        scheduler, _, app_id = parse_app_handle(app_handle)
        with log_event("log_lines", scheduler, app_id, session=self._name):
            sched = self._scheduler(scheduler)
            if (since or until) and not getattr(
                sched, "supports_log_windows", False
            ):
                logger.warning(
                    "the %s scheduler does not apply --since/--until"
                    " windows (its log files carry no per-line"
                    " timestamps); showing the full log",
                    scheduler,
                )
            return sched.log_iter(
                app_id,
                role_name,
                k,
                regex,
                since.timestamp() if since else None,
                until.timestamp() if until else None,
                should_tail,
                streams,
            )

    def log_lines_multi(
        self,
        app_handle: AppHandle,
        replicas: Mapping[str, Iterable[int]],
        regex: Optional[str] = None,
        since: Optional[datetime] = None,
        until: Optional[datetime] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterator[tuple[str, int, str]]:
        """Merge many replicas' log streams into one iterator of
        ``(role_name, replica_id, line)`` tuples (lines come with their
        trailing newline stripped).

        One pump thread per replica feeds a single bounded FIFO queue, so
        the streams are read concurrently (tailing N replicas costs the
        latency of one) while PER-REPLICA ordering is preserved exactly;
        interleaving across replicas is arrival-order. A stream that fails
        yields one ``<log stream error: ...>`` line for its replica and
        never takes the other streams down. Abandoning the iterator
        (``close()``/GC) releases every pump thread."""
        pairs = [
            (role, int(rid)) for role, ids in replicas.items() for rid in ids
        ]
        if not pairs:
            return
        import queue

        q: "queue.Queue[object]" = queue.Queue(maxsize=1024)
        stop = threading.Event()
        done = object()  # one per-replica end-of-stream sentinel

        def _offer(item: object) -> None:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return
                except queue.Full:
                    continue

        def _pump(role: str, rid: int) -> None:
            try:
                for line in self.log_lines(
                    app_handle,
                    role,
                    rid,
                    regex=regex,
                    since=since,
                    until=until,
                    should_tail=should_tail,
                    streams=streams,
                ):
                    _offer((role, rid, line.rstrip("\n")))
                    if stop.is_set():
                        return
            except Exception as e:  # noqa: BLE001 - isolated per stream
                _offer((role, rid, f"<log stream error: {e}>"))
            finally:
                _offer(done)

        threads = [
            threading.Thread(
                target=_pump,
                args=(role, rid),
                daemon=True,
                name=f"tpx-log-{role}-{rid}",
            )
            for role, rid in pairs
        ]
        for t in threads:
            t.start()
        remaining = len(threads)
        try:
            while remaining:
                item = q.get()
                if item is done:
                    remaining -= 1
                    continue
                yield item  # type: ignore[misc]
        finally:
            stop.set()  # consumer gone: release any blocked pump

    # -- scheduler access --------------------------------------------------

    def scheduler_backends(self) -> list[str]:
        """Names of every registered backend (first = default)."""
        return list(self._scheduler_factories)

    def scheduler_run_opts(self, scheduler: str) -> runopts:
        """The named backend's typed run-config schema."""
        return self._scheduler(scheduler).run_opts()

    def run_opts(self) -> dict[str, runopts]:
        """Run-config schemas for every backend, keyed by name."""
        return {name: self._scheduler(name).run_opts() for name in self._scheduler_factories}

    def _scheduler(self, scheduler: str) -> Scheduler:
        sched = self._scheduler_instances.get(scheduler)
        if sched is not None:
            return sched
        factory = self._scheduler_factories.get(scheduler)
        if factory is None:
            raise UnknownSchedulerError(
                scheduler, list(self._scheduler_factories)
            )
        # per-name creation lock: fan-out worker threads racing on the
        # same backend create exactly one instance; distinct backends
        # still construct (and import) in parallel
        with self._sched_locks_guard:
            lock = self._sched_locks.setdefault(scheduler, threading.Lock())
        with lock:
            sched = self._scheduler_instances.get(scheduler)
            if sched is None:
                params = dict(self._scheduler_params)
                sched = factory(session_name=self._name, **params)
                self._scheduler_instances[scheduler] = sched
        return sched

    # -- tracker env injection (reference runner/api.py:358-391) -----------

    def _inject_tracker_env(self, app: AppDef, parent_run_id: Optional[str]) -> None:
        from torchx_tpu.tracker.api import tracker_config_env_vars

        env = tracker_config_env_vars(parent_run_id)
        if not env:
            return
        for role in app.roles:
            for k, v in env.items():
                role.env.setdefault(k, v)

    def _inject_trace_env(self, app: AppDef) -> None:
        """Propagate the client trace context ($TPX_TRACE_ID /
        $TPX_PARENT_SPAN) into every role's env so in-job spans and
        heartbeats join this trace (see obs/trace.py)."""
        for role in app.roles:
            obs_trace.inject_env(role.env)


def get_runner(
    name: Optional[str] = None,
    component_defaults: Optional[Mapping[str, Mapping[str, str]]] = None,
    **scheduler_params: Any,
) -> Runner:
    """Create a Runner with all registered scheduler factories.

    Scheduler params are also harvested from ``TPX_PARAMS_*`` env vars
    (reference analog: TORCHX_* harvesting, runner/api.py:128-134).
    """
    if not name:
        name = f"tpx_{get_session_id_or_create_new()[:8]}"
    for key, value in os.environ.items():
        if key.startswith(settings.ENV_TPX_PARAMS_PREFIX):
            param = key[len(settings.ENV_TPX_PARAMS_PREFIX) :].lower()
            scheduler_params.setdefault(param, value)
    return Runner(
        name,
        get_scheduler_factories(),
        component_defaults=component_defaults,
        scheduler_params=scheduler_params,
    )
