""".tpxconfig — INI-based layered configuration.

Reference analog: torchx/runner/config.py (556 LoC). Sections:

* ``[<scheduler>]`` — default run-cfg values for that scheduler,
* ``[component:<name>]`` — default component arguments,
* ``[cli:<cmd>]`` — default CLI arguments (e.g. default component for run),
* ``[tracker:<name>]`` — tracker backends to enable (+ ``config = ...``).

Precedence (highest wins): explicit CLI/-cfg values > file named by
$TPXCONFIG > $HOME/.tpxconfig > ./.tpxconfig > code defaults.
"""

from __future__ import annotations

import configparser
import logging
import os
from pathlib import Path
from typing import Mapping, Optional, TextIO

from torchx_tpu import settings
from torchx_tpu.specs.api import CfgVal, runopts

logger = logging.getLogger(__name__)

CONFIG_FILE = ".tpxconfig"
_NONE = "None"


def _config_files(dirs: Optional[list[str]] = None) -> list[str]:
    """Ordered lowest→highest precedence."""
    files: list[str] = []
    search_dirs: list[str] = []
    if dirs is not None:
        search_dirs = dirs
    else:
        # later files override earlier ones: $TPXCONFIG > $HOME > CWD
        search_dirs = [os.getcwd(), str(Path.home())]
    for d in search_dirs:
        f = os.path.join(d, CONFIG_FILE)
        if os.path.isfile(f):
            files.append(f)
    env_file = os.environ.get(settings.ENV_TPXCONFIG)
    if env_file and os.path.isfile(env_file):
        files.append(env_file)
    return files


def _read_all(dirs: Optional[list[str]] = None) -> configparser.ConfigParser:
    cp = configparser.ConfigParser()
    # preserve case of option names (component arg names are case-sensitive)
    cp.optionxform = str  # type: ignore[method-assign,assignment]
    for f in _config_files(dirs):
        try:
            cp.read(f)
        except configparser.Error as e:
            logger.warning("skipping malformed config %s: %s", f, e)
    return cp


# =========================================================================
# Scheduler run-cfg sections
# =========================================================================


def load(scheduler: str, f: TextIO, cfg: dict[str, CfgVal]) -> None:
    """Merge the ``[{scheduler}]`` section of an open file into cfg
    (only keys not already present)."""
    cp = configparser.ConfigParser()
    cp.optionxform = str  # type: ignore[method-assign,assignment]
    cp.read_string(f.read())
    _merge_section(cp, scheduler, cfg)


def _merge_section(
    cp: configparser.ConfigParser, section: str, cfg: dict[str, CfgVal]
) -> None:
    if not cp.has_section(section):
        return
    for key, value in cp.items(section):
        if key not in cfg or cfg[key] is None:
            cfg[key] = None if value == _NONE else value


def apply(
    scheduler: str, cfg: dict[str, CfgVal], dirs: Optional[list[str]] = None
) -> None:
    """Fill missing cfg values from all .tpxconfig files on the lookup path.

    Values already in cfg (from the CLI) always win; within the files, later
    (higher-precedence) files win.
    """
    cp = _read_all(dirs)
    _merge_section(cp, scheduler, cfg)


def get_config(
    prefix: str,
    name: str,
    key: str,
    dirs: Optional[list[str]] = None,
) -> Optional[str]:
    """One value from a ``[prefix:name]`` section (e.g.
    ``get_config("component", "dist.spmd", "j")``), or None."""
    cp = _read_all(dirs)
    section = f"{prefix}:{name}"
    if cp.has_section(section) and cp.has_option(section, key):
        val = cp.get(section, key)
        return None if val == _NONE else val
    return None


def load_sections(
    prefix: str, dirs: Optional[list[str]] = None
) -> dict[str, dict[str, str]]:
    """All ``[prefix:*]`` sections -> {name: {key: value}}."""
    cp = _read_all(dirs)
    out: dict[str, dict[str, str]] = {}
    for section in cp.sections():
        if section.startswith(prefix + ":"):
            name = section[len(prefix) + 1 :]
            out[name] = dict(cp.items(section))
    return out


def load_tracker_sections(
    dirs: Optional[list[str]] = None,
) -> dict[str, Optional[str]]:
    """[tracker:<name>] sections -> {name: config-string-or-None}."""
    return {
        name: body.get("config")
        for name, body in load_sections("tracker", dirs).items()
    }


def dump(
    f: TextIO,
    schedulers: Optional[Mapping[str, runopts]] = None,
    required_only: bool = False,
) -> None:
    """Write a skeleton .tpxconfig with all (or required-only) runopts
    (used by ``tpx configure``; reference config.py dump)."""
    if schedulers is None:
        from torchx_tpu.runner.api import get_runner

        with get_runner() as runner:
            schedulers = runner.run_opts()
    for name, opts in schedulers.items():
        lines = [f"[{name}]"]
        for key, opt in opts:
            if required_only and not opt.is_required:
                continue
            default = "" if opt.default is None else str(opt.default)
            comment = "" if opt.is_required else "#"
            lines.append(f"{comment}{key} = {default or _NONE}")
        lines.append("")
        f.write("\n".join(lines) + "\n")
