"""The single seam every control-plane interaction goes through.

Two entry points, one behavior:

* :func:`resilient_cmd` wraps a backend's raw ``_run_cmd`` subprocess
  seam (gcloud / kubectl / sbatch / squeue ...): applies the default
  control-plane deadline (``TPX_CONTROL_PLANE_TIMEOUT``), classifies
  non-zero exits by stderr and timeouts structurally, and retries
  transient outcomes within the :class:`~torchx_tpu.resilience.policy.CallPolicy`
  budget. Callers keep their ``returncode``-based semantics: when the
  budget is exhausted the last failing ``CompletedProcess`` is returned
  (a timeout synthesizes one with returncode 124), never raised.
* :func:`resilient_call` wraps an arbitrary callable (SDK invocations,
  in-process scheduler methods): exceptions are classified via
  :func:`~torchx_tpu.resilience.errors.classify_exception` and transient
  ones retried; the *original* exception is re-raised when the budget is
  exhausted so existing caller ``except`` clauses keep working.

Both consult the per-backend :class:`~torchx_tpu.resilience.breaker.CircuitBreaker`
(fail fast while a backend is down), thread the deterministic
``TPX_FAULT_PLAN`` injector through the exact same code path real
failures take, and emit the observability surface: ``launcher.retry`` /
``launcher.breaker`` spans plus the ``tpx_control_plane_*`` metrics.
"""

from __future__ import annotations

import logging
import os
import random
import subprocess
import time
from typing import Any, Callable, Optional, TypeVar

from torchx_tpu import settings
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.resilience import faults
from torchx_tpu.resilience.breaker import (
    STATE_VALUES,
    BreakerState,
    CircuitBreaker,
)
from torchx_tpu.resilience.errors import (
    BreakerOpenError,
    FailureKind,
    classify_exception,
    classify_proc,
    is_transient,
)
from torchx_tpu.resilience.policy import CallPolicy

logger = logging.getLogger(__name__)

T = TypeVar("T")

#: the policy used when a call site passes none; tests may swap it for a
#: near-zero-backoff variant to keep retry paths fast.
DEFAULT_POLICY = CallPolicy()

#: synthesized returncode for an exhausted-deadline subprocess call
#: (the shell convention for "killed by timeout(1)").
TIMEOUT_RETURNCODE = 124


def control_plane_timeout() -> Optional[float]:
    """The default per-call deadline in seconds from
    ``TPX_CONTROL_PLANE_TIMEOUT`` (default
    :data:`~torchx_tpu.settings.DEFAULT_CONTROL_PLANE_TIMEOUT`);
    ``0``/``off``/``none`` disables the deadline entirely."""
    raw = os.environ.get(settings.ENV_TPX_CONTROL_PLANE_TIMEOUT)
    if raw is None or not raw.strip():
        return settings.DEFAULT_CONTROL_PLANE_TIMEOUT
    if raw.strip().lower() in ("0", "off", "none", "false"):
        return None
    try:
        value = float(raw)
    except ValueError:
        logger.warning(
            "unparseable %s=%r; using default %ss",
            settings.ENV_TPX_CONTROL_PLANE_TIMEOUT,
            raw,
            settings.DEFAULT_CONTROL_PLANE_TIMEOUT,
        )
        return settings.DEFAULT_CONTROL_PLANE_TIMEOUT
    return value if value > 0 else None


# -- per-backend breakers -------------------------------------------------

_breakers: dict[str, CircuitBreaker] = {}


def breaker_for(backend: str) -> CircuitBreaker:
    """The process-wide circuit breaker guarding one backend
    (get-or-create; all seam calls for a backend share it)."""
    breaker = _breakers.get(backend)
    if breaker is None:
        breaker = _breakers.setdefault(backend, CircuitBreaker(backend))
    return breaker


def reset_breakers() -> None:
    """Drop every breaker (tests)."""
    _breakers.clear()


def _note_breaker_transition(
    breaker: CircuitBreaker, backend: str, before: BreakerState
) -> None:
    after = breaker.state
    if after is before:
        return
    obs_metrics.BREAKER_STATE.set(STATE_VALUES[after], backend=backend)
    with obs_trace.span(
        "launcher.breaker",
        backend=backend,
        state=after.value,
        previous=before.value,
    ):
        pass
    log = logger.warning if after is BreakerState.OPEN else logger.info
    log("%s control plane breaker: %s -> %s", backend, before.value, after.value)


def _check_breaker(backend: str, op: str) -> CircuitBreaker:
    breaker = breaker_for(backend)
    if not breaker.allow():
        obs_metrics.CONTROL_PLANE_CALLS.inc(
            backend=backend, op=op, status="rejected"
        )
        raise BreakerOpenError(
            f"{backend} control plane breaker is open"
            f" (cooling down after repeated transient failures);"
            f" refusing {op}",
            kind=FailureKind.UNAVAILABLE,
            backend=backend,
            op=op,
        )
    return breaker


def _backoff(
    policy: CallPolicy,
    backend: str,
    op: str,
    kind: FailureKind,
    retry_number: int,
    sleep: Callable[[float], None],
    rng: Optional[random.Random],
) -> None:
    """One retry pause: metric + ``launcher.retry`` span around the sleep."""
    delay = policy.backoff_delay(retry_number, rng=rng)
    obs_metrics.CONTROL_PLANE_RETRIES.inc(
        backend=backend, op=op, kind=kind.value
    )
    logger.info(
        "%s.%s failed (%s); retry %d/%d in %.2fs",
        backend,
        op,
        kind.value,
        retry_number,
        policy.retries_for(kind),
        delay,
    )
    with obs_trace.span(
        "launcher.retry",
        backend=backend,
        op=op,
        kind=kind.value,
        retry=retry_number,
        delay_seconds=round(delay, 3),
    ):
        sleep(delay)


def resilient_call(
    fn: Callable[[], T],
    *,
    backend: str,
    op: str,
    policy: Optional[CallPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
) -> T:
    """Invoke ``fn`` under classification, retries, the backend breaker,
    and fault injection.

    Raised exceptions are classified; transient kinds are retried within
    ``policy``'s per-kind budget with capped jittered backoff. On budget
    exhaustion (or any permanent kind) the original exception propagates
    unchanged — callers' existing ``except`` clauses (SDK NotFound
    handling etc.) are preserved. A permanent failure still proves the
    backend reachable, so it records breaker *success*."""
    policy = policy or DEFAULT_POLICY
    breaker = _check_breaker(backend, op)
    injector = faults.active_injector()
    retries_used: dict[FailureKind, int] = {}
    while True:
        before = breaker.state
        try:
            rule = injector.check(backend, op) if injector else None
            result: Any = (
                injector.fire(rule, backend, op)  # type: ignore[union-attr]
                if rule is not None
                else fn()
            )
        except Exception as exc:  # noqa: BLE001 - classified below
            kind = classify_exception(exc)
            if not is_transient(kind):
                breaker.record_success()
                _note_breaker_transition(breaker, backend, before)
                obs_metrics.CONTROL_PLANE_CALLS.inc(
                    backend=backend, op=op, status="error"
                )
                raise
            breaker.record_failure()
            _note_breaker_transition(breaker, backend, before)
            used = retries_used.get(kind, 0)
            if used >= policy.retries_for(kind):
                obs_metrics.CONTROL_PLANE_CALLS.inc(
                    backend=backend, op=op, status="error"
                )
                raise
            retries_used[kind] = used + 1
            _backoff(policy, backend, op, kind, used + 1, sleep, rng)
            continue
        breaker.record_success()
        _note_breaker_transition(breaker, backend, before)
        obs_metrics.CONTROL_PLANE_CALLS.inc(backend=backend, op=op, status="ok")
        return result


def resilient_cmd(
    run: Callable[..., subprocess.CompletedProcess],
    cmd: list[str],
    *,
    backend: str,
    op: str,
    policy: Optional[CallPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    **kwargs: Any,
) -> subprocess.CompletedProcess:
    """Run one control-plane subprocess through the resilient seam.

    ``run`` is the backend's raw ``_run_cmd`` (kept as the monkeypatchable
    test seam). The per-call deadline defaults to
    :func:`control_plane_timeout` unless the caller or policy supplies
    one. Non-zero exits classify by stderr; transient classes retry within
    budget, then the last failing ``CompletedProcess`` is *returned* so
    existing ``returncode != 0`` handling applies. A hung call raises
    ``subprocess.TimeoutExpired`` inside, retries, and finally returns a
    synthesized ``CompletedProcess`` with returncode
    :data:`TIMEOUT_RETURNCODE` — a deadline must degrade like any other
    failed call, not crash a poll loop that predates deadlines."""
    policy = policy or DEFAULT_POLICY
    if "timeout" not in kwargs:
        deadline = (
            policy.timeout if policy.timeout is not None else control_plane_timeout()
        )
        if deadline is not None:
            kwargs["timeout"] = deadline
    breaker = _check_breaker(backend, op)
    injector = faults.active_injector()
    retries_used: dict[FailureKind, int] = {}
    while True:
        before = breaker.state
        failure: Optional[FailureKind] = None
        proc: Optional[subprocess.CompletedProcess] = None
        try:
            rule = injector.check(backend, op) if injector else None
            if rule is not None:
                payload = injector.fire(rule, backend, op)  # may raise
                proc = subprocess.CompletedProcess(
                    args=cmd, returncode=0, stdout=payload, stderr=""
                )
            else:
                proc = run(cmd, **kwargs)
            failure = classify_proc(proc)
        except subprocess.TimeoutExpired as exc:
            failure = FailureKind.TIMEOUT
            proc = subprocess.CompletedProcess(
                args=cmd,
                returncode=TIMEOUT_RETURNCODE,
                stdout="",
                stderr=(
                    f"{backend} {op} timed out after {exc.timeout}s"
                    f" (control-plane deadline; raise"
                    f" ${settings.ENV_TPX_CONTROL_PLANE_TIMEOUT} if the"
                    f" call is legitimately slow)"
                ),
            )
        except Exception as exc:  # noqa: BLE001 - injected / transport errors
            kind = classify_exception(exc)
            if not is_transient(kind):
                breaker.record_success()
                _note_breaker_transition(breaker, backend, before)
                obs_metrics.CONTROL_PLANE_CALLS.inc(
                    backend=backend, op=op, status="error"
                )
                raise
            breaker.record_failure()
            _note_breaker_transition(breaker, backend, before)
            used = retries_used.get(kind, 0)
            if used >= policy.retries_for(kind):
                obs_metrics.CONTROL_PLANE_CALLS.inc(
                    backend=backend, op=op, status="error"
                )
                raise
            retries_used[kind] = used + 1
            _backoff(policy, backend, op, kind, used + 1, sleep, rng)
            continue

        if failure is None:
            breaker.record_success()
            _note_breaker_transition(breaker, backend, before)
            obs_metrics.CONTROL_PLANE_CALLS.inc(
                backend=backend, op=op, status="ok"
            )
            return proc
        if not is_transient(failure):
            # deterministic failure, but the control plane answered:
            # reachability-wise that is a breaker success
            breaker.record_success()
            _note_breaker_transition(breaker, backend, before)
            obs_metrics.CONTROL_PLANE_CALLS.inc(
                backend=backend, op=op, status="error"
            )
            return proc
        breaker.record_failure()
        _note_breaker_transition(breaker, backend, before)
        used = retries_used.get(failure, 0)
        if used >= policy.retries_for(failure):
            obs_metrics.CONTROL_PLANE_CALLS.inc(
                backend=backend, op=op, status="error"
            )
            return proc
        retries_used[failure] = used + 1
        _backoff(policy, backend, op, failure, used + 1, sleep, rng)
