"""Per-call retry policy for the resilient control-plane seam.

The shape mirrors :class:`~torchx_tpu.supervisor.policy.SupervisorPolicy`
one layer down: where the supervisor budgets *resubmissions* per
:class:`~torchx_tpu.specs.api.FailureClass`, a :class:`CallPolicy` budgets
*retries of one control-plane call* per
:class:`~torchx_tpu.resilience.errors.FailureKind`, with the same capped
exponential backoff + jitter scheme. Budgets default to a few quick
retries for throttling/transport blips and zero for everything permanent
— a launcher should shrug off a 429, not mask a revoked credential.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Mapping, Optional

from torchx_tpu.resilience.errors import FailureKind, is_transient


def _default_retries() -> dict[FailureKind, int]:
    """Default per-kind retry budgets (retries, not attempts: 2 means up
    to 3 calls total). Permanent kinds are hard-zeroed in
    :meth:`CallPolicy.retries_for` regardless of this table."""
    return {
        FailureKind.TIMEOUT: 1,
        FailureKind.RATE_LIMIT: 3,
        FailureKind.QUOTA: 2,
        FailureKind.UNAVAILABLE: 2,
        FailureKind.CONNECTION: 2,
    }


@dataclass
class CallPolicy:
    """Knobs governing one resilient control-plane call."""

    #: per-call deadline in seconds, applied as the subprocess timeout by
    #: :func:`~torchx_tpu.resilience.call.resilient_cmd`; None defers to
    #: the ``TPX_CONTROL_PLANE_TIMEOUT`` setting.
    timeout: Optional[float] = None
    #: retry budget per failure kind (missing kind = 0 retries).
    retries: Mapping[FailureKind, int] = field(default_factory=_default_retries)
    #: first retry delay, seconds.
    backoff_seconds: float = 0.5
    #: multiplier per consecutive retry.
    backoff_factor: float = 2.0
    #: ceiling on a single delay, seconds.
    backoff_max_seconds: float = 15.0
    #: ± fraction of random perturbation on every delay.
    jitter: float = 0.1

    def __post_init__(self) -> None:
        if self.timeout is not None and self.timeout <= 0:
            raise ValueError(f"timeout must be > 0, got {self.timeout}")
        if self.backoff_seconds < 0 or self.backoff_max_seconds < 0:
            raise ValueError("backoff delays must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1.0, got {self.backoff_factor}"
            )
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        for kind, budget in self.retries.items():
            if budget < 0:
                raise ValueError(f"retry budget for {kind} must be >= 0")

    def retries_for(self, kind: FailureKind) -> int:
        """Retry budget for one failure kind; permanent kinds always 0."""
        if not is_transient(kind):
            return 0
        return int(self.retries.get(kind, 0))

    def backoff_delay(
        self, retry_number: int, rng: Optional[random.Random] = None
    ) -> float:
        """Jittered delay (seconds) before retry ``retry_number`` (1-based):
        capped exponential, same scheme as
        :meth:`~torchx_tpu.supervisor.policy.SupervisorPolicy.backoff_delay`."""
        if retry_number < 1:
            raise ValueError(f"retry_number must be >= 1, got {retry_number}")
        base = min(
            self.backoff_seconds * self.backoff_factor ** (retry_number - 1),
            self.backoff_max_seconds,
        )
        r = rng or random
        return max(0.0, base * (1.0 + r.uniform(-self.jitter, self.jitter)))


#: policy for non-idempotent calls (submits): deadline + classification
#: still apply, but a call that MAY have reached the control plane is
#: never replayed — a duplicate job is worse than a failed submit.
NON_IDEMPOTENT = CallPolicy(retries={})
