"""Control-plane resilience: deadlines, classified retries, circuit
breakers, and deterministic fault injection.

The launcher's value is babysitting jobs through a flaky control plane,
so the launcher <-> cloud edge gets the same treatment PR 1 gave the
job <-> capacity edge. Every backend control-plane interaction (gcloud /
kubectl / sbatch subprocesses, SDK calls, even the local scheduler's
status path) flows through one seam —
:func:`~torchx_tpu.resilience.call.resilient_call` /
:func:`~torchx_tpu.resilience.call.resilient_cmd` — which:

* applies a per-call deadline (``TPX_CONTROL_PLANE_TIMEOUT``; a hung
  gcloud degrades into a classified failure instead of blocking forever),
* classifies failures into a :class:`~torchx_tpu.resilience.errors.FailureKind`
  (transient 429/quota/deadline/connection vs permanent auth/invalid),
* retries transients under a :class:`~torchx_tpu.resilience.policy.CallPolicy`
  (per-kind budgets, capped exponential backoff + jitter),
* guards each backend with a :class:`~torchx_tpu.resilience.breaker.CircuitBreaker`
  (closed -> open -> half-open; fail fast while the backend is down),
* threads the ``TPX_FAULT_PLAN`` chaos-drill injector
  (:mod:`torchx_tpu.resilience.faults`) through the identical code path,
* and emits ``launcher.retry`` / ``launcher.breaker`` spans plus the
  ``tpx_control_plane_{calls,retries,breaker_state}`` metrics.

:class:`~torchx_tpu.resilience.breaker.FailureLedger` is the durable
cousin of the breaker (trip-after-N-consecutive-failures persisted per
user), generalizing the gcp_batch scope-eviction file into a shared
primitive.
"""

from torchx_tpu.resilience.breaker import (
    BreakerState,
    CircuitBreaker,
    FailureLedger,
)
from torchx_tpu.resilience.call import (
    breaker_for,
    control_plane_timeout,
    resilient_call,
    resilient_cmd,
)
from torchx_tpu.resilience.errors import (
    BreakerOpenError,
    FailureKind,
    PermanentSchedulerError,
    SchedulerCallError,
    TransientSchedulerError,
    classify_exception,
    classify_proc,
    classify_text,
    is_transient,
)
from torchx_tpu.resilience.faults import (
    FaultInjector,
    FaultPlan,
    FaultRule,
    fault_plan_active,
)
from torchx_tpu.resilience.policy import NON_IDEMPOTENT, CallPolicy

__all__ = [
    "BreakerOpenError",
    "BreakerState",
    "CallPolicy",
    "CircuitBreaker",
    "FailureKind",
    "FailureLedger",
    "FaultInjector",
    "FaultPlan",
    "FaultRule",
    "NON_IDEMPOTENT",
    "PermanentSchedulerError",
    "SchedulerCallError",
    "TransientSchedulerError",
    "breaker_for",
    "classify_exception",
    "classify_proc",
    "classify_text",
    "control_plane_timeout",
    "fault_plan_active",
    "is_transient",
    "resilient_call",
    "resilient_cmd",
]
