"""Circuit breakers for control-plane backends, in two durabilities.

:class:`CircuitBreaker` is the classic in-process closed -> open ->
half-open machine: trip after N *consecutive* transient failures, cool
down, then let exactly one probe through; the probe's outcome decides
between closing and re-opening. One breaker guards each backend (see
:func:`~torchx_tpu.resilience.call.breaker_for`) so a dead control plane
fails fast instead of stacking deadlines on every poll.

:class:`FailureLedger` is the same trip-after-N-consecutive-failures idea
made durable and keyed: a per-user file counting unbroken failures per
string key, where a success clears the key and a key at threshold is
"tripped" until something succeeds against it again. It generalizes the
gcp_batch scope-eviction bookkeeping (``.tpxgcpbatchscopefails``) that
previously lived inline in that scheduler — a revoked project's scope
sits out of ``list()`` fan-out, and the same primitive is available to
any backend that needs cross-process failure memory.
"""

from __future__ import annotations

import enum
import os
import threading
import time
from typing import Callable, Optional


class BreakerState(enum.Enum):
    """The three classic circuit-breaker states."""

    #: calls flow; consecutive transient failures are counted.
    CLOSED = "closed"
    #: calls are refused until the cool-down elapses.
    OPEN = "open"
    #: cool-down elapsed; exactly one probe call is allowed through.
    HALF_OPEN = "half_open"


#: numeric encoding for the ``tpx_control_plane_breaker_state`` gauge.
STATE_VALUES = {
    BreakerState.CLOSED: 0,
    BreakerState.HALF_OPEN: 1,
    BreakerState.OPEN: 2,
}


class CircuitBreaker:
    """In-process breaker guarding one backend's control plane.

    Thread-safe; ``clock`` is injectable (monotonic seconds) so tests can
    step time instead of sleeping. Only *transient* outcomes should be
    recorded as failures — a deterministic auth error says nothing about
    backend health and must not trip the breaker."""

    def __init__(
        self,
        name: str,
        trip_after: int = 5,
        cooldown_seconds: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if trip_after < 1:
            raise ValueError(f"trip_after must be >= 1, got {trip_after}")
        if cooldown_seconds < 0:
            raise ValueError(
                f"cooldown_seconds must be >= 0, got {cooldown_seconds}"
            )
        self.name = name
        self.trip_after = trip_after
        self.cooldown_seconds = cooldown_seconds
        self._clock = clock
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_out = False

    @property
    def state(self) -> BreakerState:
        """Current state (OPEN decays to HALF_OPEN once cooled down)."""
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (
            self._state is BreakerState.OPEN
            and self._opened_at is not None
            and self._clock() - self._opened_at >= self.cooldown_seconds
        ):
            self._state = BreakerState.HALF_OPEN

    def allow(self) -> bool:
        """May a call proceed right now? CLOSED always; OPEN never (until
        the cool-down); HALF_OPEN admits one probe then refuses until the
        probe reports back."""
        with self._lock:
            self._maybe_half_open()
            if self._state is BreakerState.CLOSED:
                return True
            if self._state is BreakerState.HALF_OPEN:
                # admit one probe; restart the cool-down so an abandoned
                # probe (caller died) cannot wedge the breaker open forever
                self._state = BreakerState.OPEN
                self._opened_at = self._clock()
                self._probe_out = True
                return True
            return False

    def record_success(self) -> None:
        """A call completed: reset the failure streak and close."""
        with self._lock:
            self._state = BreakerState.CLOSED
            self._consecutive_failures = 0
            self._opened_at = None
            self._probe_out = False

    def record_failure(self) -> None:
        """A call failed transiently: extend the streak; trip to OPEN at
        ``trip_after`` (or immediately when a half-open probe fails)."""
        with self._lock:
            probing = self._probe_out
            self._probe_out = False
            self._consecutive_failures += 1
            if probing or self._consecutive_failures >= self.trip_after:
                self._state = BreakerState.OPEN
                self._opened_at = self._clock()


class FailureLedger:
    """Durable consecutive-failure counter, keyed by string.

    The file is APPEND-ONLY: one ``key`` line per failure, one
    ``key|clear`` tombstone line per success. :meth:`failures` replays
    the lines in order, so a tombstone erases every failure recorded
    before it and none after. Clears used to rewrite the whole file
    (tmp + ``os.replace``), which could silently drop a failure appended
    between the read and the replace; a tombstone is a single O_APPEND
    write, so concurrent writers can no longer undo each other. A key
    with >= ``threshold`` unbroken failures is *tripped* and should sit
    out until a success clears it. Keys must not end with ``|clear``
    (they would parse as tombstones)."""

    CLEAR_SUFFIX = "|clear"

    def __init__(self, path: str, threshold: int = 3) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.path = path
        self.threshold = threshold

    def failures(self) -> dict[str, int]:
        """Unbroken failure count per key (missing file = empty),
        replaying failure lines and ``|clear`` tombstones in order."""
        out: dict[str, int] = {}
        try:
            with open(self.path) as f:
                for line in f:
                    key = line.strip()
                    if not key:
                        continue
                    if key.endswith(self.CLEAR_SUFFIX):
                        out.pop(key[: -len(self.CLEAR_SUFFIX)], None)
                    else:
                        out[key] = out.get(key, 0) + 1
        except OSError:
            pass
        return out

    def note(self, key: str, ok: bool) -> None:
        """Record one observation: a failure appends a ``key`` line; a
        success appends a ``key|clear`` tombstone (only when the key has
        recorded failures, so a success on a clean ledger stays a no-op
        and never creates the file). Each append is one O_APPEND write
        of one line — concurrent notes interleave per-line instead of
        racing a whole-file rewrite."""
        try:
            if ok and key not in self.failures():
                return
            line = f"{key}{self.CLEAR_SUFFIX}\n" if ok else f"{key}\n"
            fd = os.open(
                self.path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644
            )
            try:
                os.write(fd, line.encode())
            finally:
                os.close(fd)
        except OSError:
            pass

    def tripped(self) -> set[str]:
        """Keys whose unbroken failure count reached the threshold."""
        return {
            key
            for key, count in self.failures().items()
            if count >= self.threshold
        }
