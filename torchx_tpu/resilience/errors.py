"""Failure taxonomy for the launcher <-> control-plane edge.

Every remote backend talks to its control plane through fallible channels
(gcloud/kubectl subprocesses, HTTP SDKs). Failures split into two classes
with opposite correct reactions:

* **transient** — 429s, quota exhaustion, deadline overruns, connection
  resets, 5xx: the call may well succeed if repeated, so the resilient
  seam retries it under a :class:`~torchx_tpu.resilience.policy.CallPolicy`;
* **permanent** — auth errors, malformed requests, missing resources:
  deterministic, retrying burns time and quota, fail immediately.

The classifier maps the three observable shapes of a failed control-plane
call — a subprocess timeout, a non-zero exit with stderr text, a raised
SDK exception — onto one :class:`FailureKind`, and :func:`is_transient`
decides which side of the line each kind falls on. Patterns follow the
wording gcloud / kubectl / googleapis actually emit (``RESOURCE_EXHAUSTED``,
``Quota exceeded``, ``DEADLINE_EXCEEDED``, ``connection reset by peer``).
"""

from __future__ import annotations

import enum
import re
import subprocess
from typing import Optional


class FailureKind(enum.Enum):
    """What went wrong with one control-plane call (the classifier's
    output and the retry-budget key of
    :class:`~torchx_tpu.resilience.policy.CallPolicy`)."""

    #: the call overran its deadline (subprocess timeout, DEADLINE_EXCEEDED).
    TIMEOUT = "TIMEOUT"
    #: the control plane throttled us (429 / too many requests).
    RATE_LIMIT = "RATE_LIMIT"
    #: quota / RESOURCE_EXHAUSTED — capacity may free up.
    QUOTA = "QUOTA"
    #: 5xx / "service unavailable" / "internal error" — their side, not ours.
    UNAVAILABLE = "UNAVAILABLE"
    #: transport-level failure (connection reset/refused, broken pipe, DNS).
    CONNECTION = "CONNECTION"
    #: authentication / authorization failure — deterministic until fixed.
    AUTH = "AUTH"
    #: the named resource does not exist — retrying cannot create it.
    NOT_FOUND = "NOT_FOUND"
    #: the request itself is malformed — a launcher bug, never retried.
    INVALID = "INVALID"
    #: unrecognized failure; classified permanent so unknown errors
    #: surface immediately instead of burning a retry budget.
    UNKNOWN = "UNKNOWN"


#: kinds the resilient seam may retry.
TRANSIENT_KINDS = frozenset(
    {
        FailureKind.TIMEOUT,
        FailureKind.RATE_LIMIT,
        FailureKind.QUOTA,
        FailureKind.UNAVAILABLE,
        FailureKind.CONNECTION,
    }
)


def is_transient(kind: FailureKind) -> bool:
    """True when ``kind`` is worth retrying (see :data:`TRANSIENT_KINDS`)."""
    return kind in TRANSIENT_KINDS


class SchedulerCallError(RuntimeError):
    """Base of the taxonomy: one failed control-plane call, annotated with
    the backend, the logical operation, and the classified kind."""

    def __init__(
        self,
        message: str,
        *,
        kind: FailureKind = FailureKind.UNKNOWN,
        backend: str = "",
        op: str = "",
    ) -> None:
        super().__init__(message)
        self.kind = kind
        self.backend = backend
        self.op = op


class TransientSchedulerError(SchedulerCallError):
    """A control-plane failure that may succeed if repeated (throttling,
    quota, deadline, connection loss). The resilient seam retries these
    within budget; :meth:`~torchx_tpu.runner.api.Runner.wait` absorbs them
    up to its poll-miss budget instead of aborting a supervised run."""


class PermanentSchedulerError(SchedulerCallError):
    """A deterministic control-plane failure (auth, malformed request,
    missing resource). Never retried."""


class BreakerOpenError(TransientSchedulerError):
    """Raised without attempting the call when the backend's circuit
    breaker is open (the backend failed too many consecutive times and is
    cooling down). Transient by definition (kind defaults to UNAVAILABLE):
    the breaker re-probes after its cool-down."""

    def __init__(
        self,
        message: str,
        *,
        kind: FailureKind = FailureKind.UNAVAILABLE,
        backend: str = "",
        op: str = "",
    ) -> None:
        super().__init__(message, kind=kind, backend=backend, op=op)


# -- stderr / message pattern table ---------------------------------------
# Ordered: the first matching pattern wins, so throttling text that also
# mentions a 403 ("rate limit exceeded for project") classifies RATE_LIMIT
# (transient), not AUTH.
_PATTERNS: tuple[tuple[FailureKind, "re.Pattern[str]"], ...] = (
    (
        FailureKind.RATE_LIMIT,
        re.compile(r"\b429\b|too many requests|rate.?limit", re.I),
    ),
    (
        FailureKind.QUOTA,
        re.compile(r"resource.?exhausted|quota", re.I),
    ),
    (
        FailureKind.TIMEOUT,
        re.compile(r"deadline.?exceeded|timed?.?out", re.I),
    ),
    (
        FailureKind.CONNECTION,
        re.compile(
            r"connection (reset|refused|aborted|closed)|broken pipe"
            r"|network is unreachable|remote end closed|name resolution"
            r"|temporary failure in name",
            re.I,
        ),
    ),
    (
        FailureKind.UNAVAILABLE,
        re.compile(
            r"\b50[023]\b|unavailable|internal error|backend error"
            r"|server error|try again later",
            re.I,
        ),
    ),
    (
        FailureKind.AUTH,
        re.compile(
            r"\b40[13]\b|permission denied|unauthenticated|unauthorized"
            r"|forbidden|credential",
            re.I,
        ),
    ),
    (
        FailureKind.NOT_FOUND,
        re.compile(r"\b404\b|not.?found|does not exist|no such", re.I),
    ),
    (
        FailureKind.INVALID,
        re.compile(r"\b400\b|invalid.?argument|bad request|malformed", re.I),
    ),
)


def classify_text(text: str) -> FailureKind:
    """Classify an error message (typically gcloud/kubectl stderr) by the
    pattern table; :data:`FailureKind.UNKNOWN` when nothing matches."""
    for kind, pattern in _PATTERNS:
        if pattern.search(text or ""):
            return kind
    return FailureKind.UNKNOWN


def classify_proc(proc: subprocess.CompletedProcess) -> Optional[FailureKind]:
    """Classify a finished subprocess: None for success (returncode 0),
    otherwise the kind derived from its stderr (falling back to stdout —
    some gcloud verbs print errors there)."""
    if proc.returncode == 0:
        return None
    text = (getattr(proc, "stderr", "") or "") + "\n" + (
        getattr(proc, "stdout", "") or ""
    )
    return classify_text(text)


# HTTP status -> kind, for SDK exceptions that carry one (kubernetes
# ApiException.status, google.api_core errors' .code).
_STATUS_KINDS = {
    408: FailureKind.TIMEOUT,
    429: FailureKind.RATE_LIMIT,
    500: FailureKind.UNAVAILABLE,
    502: FailureKind.UNAVAILABLE,
    503: FailureKind.UNAVAILABLE,
    504: FailureKind.TIMEOUT,
    401: FailureKind.AUTH,
    403: FailureKind.AUTH,
    404: FailureKind.NOT_FOUND,
    400: FailureKind.INVALID,
}

# Exception type names -> kind, so google/kubernetes/docker errors classify
# without importing their (optional) packages.
_TYPENAME_KINDS = {
    "DeadlineExceeded": FailureKind.TIMEOUT,
    "GatewayTimeout": FailureKind.TIMEOUT,
    "TooManyRequests": FailureKind.RATE_LIMIT,
    "ResourceExhausted": FailureKind.QUOTA,
    "ServiceUnavailable": FailureKind.UNAVAILABLE,
    "InternalServerError": FailureKind.UNAVAILABLE,
    "ServerError": FailureKind.UNAVAILABLE,
    "RetryError": FailureKind.UNAVAILABLE,
    "Unauthenticated": FailureKind.AUTH,
    "Unauthorized": FailureKind.AUTH,
    "PermissionDenied": FailureKind.AUTH,
    "Forbidden": FailureKind.AUTH,
    "NotFound": FailureKind.NOT_FOUND,
    "InvalidArgument": FailureKind.INVALID,
    "BadRequest": FailureKind.INVALID,
}


def classify_exception(exc: BaseException) -> FailureKind:
    """Classify a raised exception from any control-plane channel.

    Resolution order: the taxonomy's own errors carry their kind;
    ``subprocess.TimeoutExpired`` and stdlib connection errors classify
    structurally; SDK exceptions classify by HTTP status attribute
    (``status``/``code``) then by type name (no optional imports needed);
    anything else falls back to the stderr pattern table over ``str(exc)``.
    """
    if isinstance(exc, SchedulerCallError):
        return exc.kind
    if isinstance(exc, subprocess.TimeoutExpired):
        return FailureKind.TIMEOUT
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return (
            FailureKind.TIMEOUT
            if isinstance(exc, TimeoutError)
            else FailureKind.CONNECTION
        )
    for attr in ("status", "code"):
        value = getattr(exc, attr, None)
        if isinstance(value, int) and value in _STATUS_KINDS:
            return _STATUS_KINDS[value]
    for cls in type(exc).__mro__:
        kind = _TYPENAME_KINDS.get(cls.__name__)
        if kind is not None:
            return kind
    return classify_text(str(exc))
