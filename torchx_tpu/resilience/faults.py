"""Deterministic fault injection for the resilient control-plane seam.

``TPX_FAULT_PLAN`` (inline JSON or a path to a JSON file) arms a plan of
:class:`FaultRule` entries that the seam consults before every real call
— the chaos-drill counterpart of the local scheduler's
``TPX_SIMULATE_PREEMPTION_EXIT`` knob, one layer down: where that drills
*job* failure handling, a fault plan drills *control-plane* failure
handling (retries, breakers, poll-miss budgets) without a flaky cloud.

A plan is a JSON list of rules (or ``{"rules": [...]}``)::

    [{"backend": "local", "op": "describe", "nth": 2, "times": 2,
      "mode": "transient", "message": "injected 429"}]

Rule fields: ``backend``/``op`` are fnmatch patterns against the seam's
call coordinates; ``nth`` (1-based, per matching backend+op counter)
pins the first call to fire on, ``times`` how many consecutive calls
fire (``nth`` omitted = fire on the first ``times`` matching calls);
``mode`` is one of:

* ``transient`` — raise :class:`~torchx_tpu.resilience.errors.TransientSchedulerError`
  (kind UNAVAILABLE): exercises retry/backoff/poll-miss paths;
* ``permanent`` — raise :class:`~torchx_tpu.resilience.errors.PermanentSchedulerError`;
* ``timeout`` — raise ``subprocess.TimeoutExpired``: exercises the
  deadline path exactly as a hung gcloud would;
* ``garbage`` — the call "succeeds" but returns garbage stdout
  (subprocess seams get a fake zero-exit ``CompletedProcess``): exercises
  downstream parse hardening.

Determinism: counters are plain per-``(backend, op)`` call counts in
process memory, so the same plan against the same call sequence always
fires on the same calls. :func:`reset` clears counters and the plan
cache (tests; the env var is re-read after a reset).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
from dataclasses import dataclass, field
from fnmatch import fnmatch
from typing import Any, Optional

from torchx_tpu import settings
from torchx_tpu.resilience.errors import (
    FailureKind,
    PermanentSchedulerError,
    TransientSchedulerError,
)

#: the ``mode`` values a rule may carry.
FAULT_MODES = ("transient", "permanent", "timeout", "garbage")

#: stdout payload of ``garbage`` faults — deliberately unparseable as
#: JSON/ids so downstream parsing must cope.
GARBAGE_PAYLOAD = "\x00<<injected-garbage>>\x00 not json } ]"


@dataclass
class FaultRule:
    """One entry of a fault plan (see the module docstring for semantics)."""

    #: fnmatch pattern against the backend name ("local", "gcp_batch", ...).
    backend: str = "*"
    #: fnmatch pattern against the seam op ("describe", "submit", ...).
    op: str = "*"
    #: 1-based index (per backend+op call counter) of the first call to
    #: fire on; None = fire from the first matching call.
    nth: Optional[int] = None
    #: how many consecutive matching calls fire.
    times: int = 1
    #: failure mode, one of :data:`FAULT_MODES`.
    mode: str = "transient"
    #: message carried by the injected error.
    message: str = "injected fault"

    def __post_init__(self) -> None:
        if self.mode not in FAULT_MODES:
            raise ValueError(
                f"fault mode must be one of {FAULT_MODES}, got {self.mode!r}"
            )
        if self.nth is not None and self.nth < 1:
            raise ValueError(f"nth is 1-based, got {self.nth}")
        if self.times < 1:
            raise ValueError(f"times must be >= 1, got {self.times}")

    def matches(self, backend: str, op: str, count: int) -> bool:
        """Does this rule fire on call number ``count`` (1-based) of
        ``backend``/``op``?"""
        if not fnmatch(backend, self.backend) or not fnmatch(op, self.op):
            return False
        first = self.nth if self.nth is not None else 1
        return first <= count < first + self.times


@dataclass
class FaultPlan:
    """A parsed ``TPX_FAULT_PLAN``: an ordered list of rules (first match
    wins per call)."""

    rules: list[FaultRule] = field(default_factory=list)

    @classmethod
    def parse(cls, raw: str) -> "FaultPlan":
        """Parse inline JSON, or read the file at ``raw`` when it names
        one. Raises ``ValueError`` on malformed plans — a typo'd chaos
        drill must fail loudly, not silently not inject."""
        text = raw
        if not raw.lstrip().startswith(("[", "{")) and os.path.exists(raw):
            with open(raw) as f:
                text = f.read()
        try:
            data = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"unparseable TPX_FAULT_PLAN: {e}") from e
        if isinstance(data, dict):
            data = data.get("rules", [])
        if not isinstance(data, list):
            raise ValueError("TPX_FAULT_PLAN must be a list of rules")
        rules = []
        for entry in data:
            if not isinstance(entry, dict):
                raise ValueError(f"fault rule must be an object, got {entry!r}")
            known = {f for f in FaultRule.__dataclass_fields__}
            unknown = set(entry) - known
            if unknown:
                raise ValueError(
                    f"unknown fault rule keys {sorted(unknown)};"
                    f" valid keys: {sorted(known)}"
                )
            rules.append(FaultRule(**entry))
        return cls(rules=rules)


class FaultInjector:
    """Stateful executor of one :class:`FaultPlan`: counts calls per
    ``(backend, op)`` and applies the first matching rule."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self._counts: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()

    def check(self, backend: str, op: str) -> Optional[FaultRule]:
        """Advance the call counter for ``backend``/``op`` and return the
        rule that fires on this call, if any."""
        with self._lock:
            key = (backend, op)
            self._counts[key] = self._counts.get(key, 0) + 1
            count = self._counts[key]
        for rule in self.plan.rules:
            if rule.matches(backend, op, count):
                return rule
        return None

    def fire(self, rule: FaultRule, backend: str, op: str) -> Any:
        """Apply one rule: raise for ``transient``/``permanent``/``timeout``
        modes, return the garbage payload for ``garbage`` (subprocess
        seams wrap it into a fake ``CompletedProcess``)."""
        msg = f"{rule.message} [fault-plan {backend}.{op}]"
        if rule.mode == "transient":
            raise TransientSchedulerError(
                msg, kind=FailureKind.UNAVAILABLE, backend=backend, op=op
            )
        if rule.mode == "permanent":
            raise PermanentSchedulerError(
                msg, kind=FailureKind.UNKNOWN, backend=backend, op=op
            )
        if rule.mode == "timeout":
            raise subprocess.TimeoutExpired(cmd=f"{backend}.{op}", timeout=0.0)
        return GARBAGE_PAYLOAD


_lock = threading.Lock()
_cached_raw: Optional[str] = None
_cached_injector: Optional[FaultInjector] = None


def active_injector() -> Optional[FaultInjector]:
    """The process-wide injector for the current ``TPX_FAULT_PLAN``, or
    None when no plan is armed. The injector (and its deterministic
    counters) persists while the env value is unchanged; changing or
    unsetting the variable swaps in a fresh one."""
    global _cached_raw, _cached_injector
    raw = os.environ.get(settings.ENV_TPX_FAULT_PLAN)
    with _lock:
        if raw != _cached_raw:
            _cached_raw = raw
            _cached_injector = (
                FaultInjector(FaultPlan.parse(raw)) if raw else None
            )
        return _cached_injector


def fault_plan_active() -> bool:
    """True when ``TPX_FAULT_PLAN`` is set and non-empty (the preflight
    analyzer's TPX502 gate against chaos-drilling real submits)."""
    return bool(os.environ.get(settings.ENV_TPX_FAULT_PLAN))


def reset() -> None:
    """Drop the cached injector and its counters (tests)."""
    global _cached_raw, _cached_injector
    with _lock:
        _cached_raw = None
        _cached_injector = None
