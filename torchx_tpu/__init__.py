"""torchx_tpu — a TPU-native universal job launcher.

Define distributed applications as typed specs (AppDef / Role / Resource
with TPU slice topology), materialize them from parameterized component
functions, package local code via workspaces, gang-schedule onto local
processes / Docker / Slurm / GKE TPU pod slices, then monitor, log-tail,
cancel and track.

Built from scratch against the capability surface of meta-pytorch/torchx
(see SURVEY.md); the execution model is JAX SPMD over TPU slices instead of
torchrun/NCCL gangs.
"""

from torchx_tpu.version import __version__  # noqa: F401
