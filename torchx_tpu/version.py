"""Version of the torchx_tpu package."""

__version__ = "0.1.0"

# The image used by components when none is given. For the local scheduler the
# image is a directory; remote schedulers expect a container image tag.
TORCHX_TPU_IMAGE = f"ghcr.io/torchx-tpu/torchx-tpu:{__version__}"
