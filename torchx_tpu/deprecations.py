"""Deprecation helpers (reference analog: torchx/deprecations.py)."""

from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, TypeVar

F = TypeVar("F", bound=Callable[..., Any])


def deprecated(replacement: str = "", since: str = "") -> Callable[[F], F]:
    """Mark a function deprecated; calling it emits a UserWarning once."""

    def deco(fn: F) -> F:
        msg = f"{fn.__module__}.{fn.__qualname__} is deprecated"
        if since:
            msg += f" since {since}"
        if replacement:
            msg += f"; use {replacement} instead"

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            warnings.warn(msg, UserWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper  # type: ignore[return-value]

    return deco


def deprecated_module(name: str, replacement: str) -> None:
    """Call at module import time to warn the whole module is deprecated."""
    warnings.warn(
        f"module {name} is deprecated; use {replacement} instead",
        UserWarning,
        stacklevel=3,
    )
