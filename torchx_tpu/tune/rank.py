"""Predicted step cost: how tune orders survivors before measuring.

The ranking is deliberately coarse — it only has to order candidates,
not predict wall clock — but it is built from the same terms the explain
report shows: per-axis collective bytes over the generation's ICI/DCN
bandwidth, a roofline compute floor, and an HBM-pressure penalty (a plan
that fits at 99% of budget thrashes the allocator and forfeits fusion
headroom; prefer slack). The per-generation ``step_time_scale`` from the
calibration table owns the whole measured time residual and multiplies
the total, so every measured run tightens future rankings (the byte-level
``collective_scale`` stays with the explain report — applying both here
would double-count one correction).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from torchx_tpu.analyze import costmodel
from torchx_tpu.analyze.plan import ParallelPlan


@dataclasses.dataclass(frozen=True)
class GenerationPerf:
    """Roofline constants for one accelerator generation (per chip)."""

    flops: float  # peak bf16 FLOP/s
    ici_bytes_per_s: float  # per-link ICI bandwidth
    dcn_bytes_per_s: float  # effective cross-slice bandwidth


#: Public-spec-order-of-magnitude constants; the calibration table owns
#: the residual error, so these only need to be relatively sane.
GENERATION_PERF: dict[str, GenerationPerf] = {
    "v2": GenerationPerf(46e12, 70e9, 10e9),
    "v3": GenerationPerf(123e12, 112e9, 10e9),
    "v4": GenerationPerf(275e12, 300e9, 25e9),
    "v5e": GenerationPerf(197e12, 200e9, 25e9),
    "v5p": GenerationPerf(459e12, 450e9, 25e9),
    "v6e": GenerationPerf(918e12, 450e9, 50e9),
    "v7x": GenerationPerf(2300e12, 900e9, 100e9),
}

#: CPU-sim fallback: arbitrary but consistent, keeps rankings meaningful
#: on the forced-host-device backend.
_DEFAULT_PERF = GenerationPerf(1e12, 10e9, 1e9)

#: MFU the compute floor assumes — a constant factor, so it cannot
#: reorder candidates, only keep the seconds plausible.
ASSUMED_MFU = 0.5

#: HBM pressure (total / usable) above which the penalty ramps in.
PRESSURE_KNEE = 0.85


def perf_for(generation: str) -> GenerationPerf:
    from torchx_tpu.tune.calibrate import generation_key

    return GENERATION_PERF.get(generation_key(generation), _DEFAULT_PERF)


@dataclasses.dataclass(frozen=True)
class StepCost:
    """Predicted per-step cost of one candidate plan."""

    step_s: float
    compute_s: float
    collective_s: float
    collective_bytes: int
    hbm_pressure: float  # total / usable (under the calibrated fit)
    penalty: float  # multiplicative HBM-pressure factor (>= 1)

    def to_dict(self) -> dict:
        return {
            "step_s": self.step_s,
            "compute_s": self.compute_s,
            "collective_s": self.collective_s,
            "collective_bytes": self.collective_bytes,
            "hbm_pressure": self.hbm_pressure,
            "penalty": self.penalty,
        }


def predicted_step_cost(
    plan: ParallelPlan,
    *,
    generation: str = "",
    calibration: Optional[object] = None,
    headroom: float = costmodel.DEFAULT_HEADROOM,
) -> StepCost:
    """Rank key for one plan: compute floor + collective time, scaled by
    the HBM-pressure penalty and the generation's calibration."""
    perf = perf_for(generation or plan.accelerator)
    m = plan.model

    # roofline compute floor: 6 * active params * tokens per chip
    tokens_per_chip = plan.batch * plan.seq / max(1, plan.devices)
    flops_per_chip = 6.0 * m.active_param_count() * tokens_per_chip
    compute_s = flops_per_chip / (perf.flops * ASSUMED_MFU)

    # collective bytes are deliberately UNCALIBRATED here: observe()
    # folds the step-time residual into step_time_scale AND (for the
    # explain report) collective_scale, so applying both to the same
    # prediction would double-count the correction and oscillate
    traffic = costmodel.collective_traffic(plan)
    collective_s = 0.0
    collective_bytes = 0
    for t in traffic:
        bw = perf.dcn_bytes_per_s if t.network in ("dcn", "mixed") else (
            perf.ici_bytes_per_s
        )
        collective_s += t.bytes_per_step / bw
        collective_bytes += t.bytes_per_step

    fit = costmodel.hbm_fit(plan, headroom=headroom, calibration=calibration)
    usable = max(1, int(fit.budget_bytes * fit.headroom))
    pressure = fit.total_bytes / usable
    # fits-at-the-brink plans lose allocator/fusion headroom: ramp a
    # penalty from the knee; an exceeding plan should already be pruned,
    # but rank it last if one slips through (headroom override races)
    penalty = 1.0 + 2.0 * max(0.0, pressure - PRESSURE_KNEE)

    scale = float(getattr(calibration, "step_time_scale", 1.0) or 1.0)
    # charge only the EXPOSED share of collective time: profiled runs
    # measure how much comm the schedule hides behind compute (bucketed
    # grad sync, async collectives) and the calibration carries it as
    # overlap_frac; uncalibrated -> discount 1.0, identical to before
    exposed_collective_s = collective_s * costmodel.overlap_discount(
        calibration
    )
    step_s = (compute_s + exposed_collective_s) * penalty * scale
    return StepCost(
        step_s=step_s,
        compute_s=compute_s,
        collective_s=collective_s,
        collective_bytes=collective_bytes,
        hbm_pressure=pressure,
        penalty=penalty,
    )
