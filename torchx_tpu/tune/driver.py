"""The tune search driver: enumerate -> prune -> rank -> measure -> emit.

Orchestrates one ``tpx tune`` run (see the package docstring for the
funnel). The driver itself never imports jax: the AOT memory probe and
the measured trials run as subprocesses (``parallel/aot_fit`` /
``tune/measure``), each importing jax exactly once for its whole batch
of work. Every decision — enumeration, each pruned candidate with the
verdict that killed it, each measured trial — lands in the fsync'd
journal, so a killed run resumes: completed trials replay from the
journal and only the remainder touches a device again.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
from typing import Any, Optional

from torchx_tpu import settings
from torchx_tpu.analyze.diagnostics import Severity
from torchx_tpu.specs.api import Role
from torchx_tpu.tune import rank as tune_rank
from torchx_tpu.tune.artifact import PlanArtifact
from torchx_tpu.tune.calibrate import CalibrationTable, tune_dir
from torchx_tpu.tune.journal import TuneJournal
from torchx_tpu.tune.space import Candidate, SearchSpace

ARTIFACT_FILE = "plan_artifact.json"
JOURNAL_FILE = "journal.jsonl"

#: how many ranked survivors the AOT stage probes (the next-best slides
#: in when a probe kills one of the top-k).
AOT_PROBE_FACTOR = 2


class TuneError(RuntimeError):
    """The tune run cannot proceed (empty space, no survivors, ...)."""


def role_for_candidate(cand: Candidate, devices: int) -> Role:
    """The synthetic single-slice role a candidate would submit as —
    what :func:`~torchx_tpu.analyze.explain.deep_preflight` analyzes.

    The CPU-sim device-count env makes the plan resolve onto ``devices``
    chips of ONE slice (tune searches within a slice; cross-slice specs
    still classify DCN through their explicit axis sizes)."""
    args = [
        "-m",
        "torchx_tpu.examples.train_llama",
        "--config",
        cand.config,
        "--mesh",
        cand.mesh_spec,
        "--batch",
        str(cand.batch),
        "--seq",
        str(cand.seq),
        "--remat-policy",
        cand.remat_policy,
    ]
    if cand.int8:
        args.append("--int8")
    return Role(
        name="tune",
        entrypoint="python",
        args=args,
        env={
            settings.ENV_XLA_FLAGS: (
                f"--xla_force_host_platform_device_count={devices}"
            )
        },
    )


@dataclasses.dataclass
class Trial:
    """One candidate's journey through the funnel."""

    candidate: Candidate
    status: str  # pruned_static | pruned_aot | measured | measure_failed
    #             | ranked_out (survived, outside top-k) | selected
    code: str = ""  # the TPX verdict / AOT verdict that decided it
    message: str = ""
    predicted: dict[str, Any] = dataclasses.field(default_factory=dict)
    metrics: dict[str, Any] = dataclasses.field(default_factory=dict)
    replayed: bool = False  # metrics came from the resume journal

    def to_dict(self) -> dict[str, Any]:
        return {
            "cid": self.candidate.cid,
            "candidate": self.candidate.to_dict(),
            "status": self.status,
            "code": self.code,
            "message": self.message,
            "predicted": self.predicted,
            "metrics": self.metrics,
            "replayed": self.replayed,
        }


@dataclasses.dataclass
class TuneResult:
    """What one ``run_tune`` call produced."""

    space: SearchSpace
    trials: list[Trial]
    winner: Optional[Trial]
    artifact_path: str
    report: dict[str, Any]
    calibration: dict[str, Any]

    def to_dict(self) -> dict[str, Any]:
        return {
            "space": self.space.to_dict(),
            "trials": [t.to_dict() for t in self.trials],
            "winner": self.winner.to_dict() if self.winner else None,
            "artifact": self.artifact_path,
            "report": self.report,
            "calibration": self.calibration,
        }


def _last_json(stdout: str, prefix: str = "") -> Optional[Any]:
    """The last parseable JSON line of a subprocess's stdout (the jax
    runtime chats on stdout/stderr around the payload)."""
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if prefix:
            if not line.startswith(prefix):
                continue
            line = line[len(prefix):]
        if not line.startswith(("{", "[")):
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def run_tune(
    space: SearchSpace,
    *,
    devices: int,
    hbm_bytes: Optional[int] = None,
    generation: str = "",
    out_dir: Optional[str] = None,
    top_k: int = 3,
    aot: bool = True,
    measure: bool = True,
    data_path: Optional[str] = None,
    measure_cmd: Optional[list[str]] = None,
    aot_cmd: Optional[list[str]] = None,
    subprocess_env: Optional[dict[str, str]] = None,
    measure_timeout: float = 1800.0,
    session: str = "",
) -> TuneResult:
    """Run the full funnel over ``space`` (see module docstring).

    ``out_dir`` (default ``$TPX_TUNE_DIR/<space digest>``) holds the
    journal and the emitted artifact; re-running with the same space and
    out_dir resumes. ``measure_cmd`` / ``aot_cmd`` override the
    subprocess argv prefixes (tests inject stubs; the spec/requests JSON
    arrives on stdin either way). ``subprocess_env`` entries overlay
    ``os.environ`` for both subprocess kinds (e.g. ``JAX_PLATFORMS`` /
    ``XLA_FLAGS`` for CPU-sim runs).
    """
    from torchx_tpu.analyze import costmodel
    from torchx_tpu.analyze.explain import deep_preflight
    from torchx_tpu.obs import metrics as obs_metrics
    from torchx_tpu.obs import trace as obs_trace

    if devices < 1:
        raise TuneError(f"devices must be >= 1, got {devices}")
    cands = space.candidates()
    if not cands:
        raise TuneError("search space enumerated zero candidates")

    out_dir = out_dir or os.path.join(tune_dir(), space.digest())
    journal = TuneJournal(os.path.join(out_dir, JOURNAL_FILE))
    prior_digest = journal.space_digest()
    if prior_digest is not None and prior_digest != space.digest():
        # the journal belongs to a different space: resuming would lie
        journal.reset()
    seen = {
        (e.get("event"), e.get("cid")): e for e in journal.replay()
    }

    def journal_once(event: dict[str, Any]) -> None:
        key = (event.get("event"), event.get("cid"))
        if key in seen:
            return
        seen[key] = event
        journal.append(event)

    table = CalibrationTable.load(
        os.path.join(tune_dir(), "calibration.json")
    )
    scales = table.scales_for(generation)
    env = {**os.environ, **(subprocess_env or {})}

    trials: list[Trial] = []
    with obs_trace.span(
        "launcher.tune",
        session=session,
        config=space.config,
        candidates=len(cands),
        devices=devices,
    ) as sp:
        obs_metrics.TUNE_CANDIDATES.inc(len(cands), config=space.config)
        journal_once(
            {
                "event": "enumerated",
                "space_digest": space.digest(),
                "total": len(cands),
                "space": space.to_dict(),
            }
        )

        # -- stage 1: static prune (deep preflight, zero device seconds)
        survivors: list[tuple[Candidate, Any, tune_rank.StepCost]] = []
        with obs_trace.span("tune.static_prune", session=session):
            for cand in cands:
                role = role_for_candidate(cand, devices)
                plan, diags = deep_preflight(
                    role,
                    devices=devices,
                    hbm_bytes=hbm_bytes,
                    calibration=scales,
                )
                errors = [d for d in diags if d.severity is Severity.ERROR]
                if errors:
                    worst = errors[0]
                    trials.append(
                        Trial(
                            candidate=cand,
                            status="pruned_static",
                            code=worst.code,
                            message=worst.message,
                        )
                    )
                    obs_metrics.TUNE_PRUNED.inc(
                        stage="static", code=worst.code
                    )
                    journal_once(
                        {
                            "event": "pruned",
                            "cid": cand.cid,
                            "stage": "static",
                            "code": worst.code,
                            "message": worst.message,
                        }
                    )
                    continue
                if plan is None:  # not plan-shaped: cannot happen for our
                    raise TuneError(  # synthetic role — fail loudly if it does
                        f"candidate {cand.cid} resolved no plan"
                    )
                # the trainer shards batch over dp*fsdp and seq over sp
                # exactly (no padding): indivisible candidates would only
                # fail later, on the device — prune them here for free
                if (
                    plan.batch % plan.data_shards
                    or plan.seq % plan.axis("sp")
                ):
                    msg = (
                        f"batch {plan.batch} / seq {plan.seq} not divisible"
                        f" by data shards {plan.data_shards} / sp"
                        f" {plan.axis('sp')}"
                    )
                    trials.append(
                        Trial(
                            candidate=cand,
                            status="pruned_static",
                            code="SHARD_INDIVISIBLE",
                            message=msg,
                        )
                    )
                    obs_metrics.TUNE_PRUNED.inc(
                        stage="static", code="SHARD_INDIVISIBLE"
                    )
                    journal_once(
                        {
                            "event": "pruned",
                            "cid": cand.cid,
                            "stage": "static",
                            "code": "SHARD_INDIVISIBLE",
                            "message": msg,
                        }
                    )
                    continue
                cost = tune_rank.predicted_step_cost(
                    plan,
                    generation=generation,
                    calibration=scales,
                )
                survivors.append((cand, plan, cost))

        # -- stage 2: rank by predicted step cost
        survivors.sort(key=lambda t: t[2].step_s)

        # -- stage 3: AOT memory-fit probe over the ranked head (one jax
        #    subprocess for the whole batch; still zero device seconds)
        aot_pruned: set[str] = set()
        aot_results: dict[str, dict[str, Any]] = {}
        if aot and survivors:
            probe = survivors[: max(top_k * AOT_PROBE_FACTOR, top_k)]
            requests = [
                {
                    "config": c.config,
                    "mesh_spec": c.mesh_spec,
                    "batch": c.batch,
                    "seq": c.seq,
                    "remat_policy": plan.remat_policy,
                    "int8_scope": c.int8_scope,
                    "hbm_bytes": plan.hbm_bytes_per_chip,
                }
                for c, plan, _cost in probe
            ]
            cmd = aot_cmd or [
                sys.executable,
                "-m",
                "torchx_tpu.parallel.aot_fit",
            ]
            with obs_trace.span(
                "tune.aot_probe", session=session, probes=len(requests)
            ):
                try:
                    proc = subprocess.run(
                        cmd,
                        input=json.dumps(requests),
                        capture_output=True,
                        text=True,
                        env=env,
                        timeout=measure_timeout,
                    )
                    results = _last_json(proc.stdout)
                except (subprocess.SubprocessError, OSError) as e:
                    results = None
                    journal_once(
                        {"event": "aot_error", "message": str(e), "cid": None}
                    )
            if isinstance(results, list) and len(results) == len(probe):
                for (c, _plan, _cost), r in zip(probe, results):
                    aot_results[c.cid] = r
                    if r.get("error"):
                        continue  # advisory: keep the candidate
                    if r.get("fits") is False:
                        aot_pruned.add(c.cid)
                        trials.append(
                            Trial(
                                candidate=c,
                                status="pruned_aot",
                                code="AOT_EXCEEDS",
                                message=(
                                    f"XLA AOT peak {r.get('peak_bytes', 0)}"
                                    f" bytes exceeds the per-chip budget"
                                ),
                                predicted={"aot": r},
                            )
                        )
                        obs_metrics.TUNE_PRUNED.inc(
                            stage="aot", code="AOT_EXCEEDS"
                        )
                        journal_once(
                            {
                                "event": "pruned",
                                "cid": c.cid,
                                "stage": "aot",
                                "code": "AOT_EXCEEDS",
                                "message": "XLA AOT memory fit exceeded",
                            }
                        )

        ranked = [
            (c, plan, cost)
            for c, plan, cost in survivors
            if c.cid not in aot_pruned
        ]
        if not ranked:
            raise TuneError(
                "static + AOT pruning killed every candidate; widen the"
                " space or raise the HBM budget"
            )

        # -- stage 4: measure the top-k via short seeded bench trials
        prior_measured = journal.measured()
        measured: list[Trial] = []
        to_measure = ranked[:top_k] if measure else []
        for c, plan, cost in to_measure:
            predicted = {
                "step_cost": cost.to_dict(),
                "aot": aot_results.get(c.cid),
            }
            if c.cid in prior_measured:
                t = Trial(
                    candidate=c,
                    status="measured",
                    predicted=predicted,
                    metrics=prior_measured[c.cid],
                    replayed=True,
                )
                trials.append(t)
                measured.append(t)
                continue
            journal.append({"event": "measure_start", "cid": c.cid})
            spec = {
                "candidate": c.to_dict(),
                "steps": space.measure_steps,
                "data_path": data_path,
            }
            cmd = measure_cmd or [
                sys.executable,
                "-m",
                "torchx_tpu.tune.measure",
            ]
            with obs_trace.span(
                "tune.measure", session=session, cid=c.cid
            ):
                try:
                    proc = subprocess.run(
                        cmd,
                        input=json.dumps(spec),
                        capture_output=True,
                        text=True,
                        env=env,
                        timeout=measure_timeout,
                    )
                    from torchx_tpu.tune.measure import RESULT_PREFIX

                    metrics = (
                        _last_json(proc.stdout, prefix=RESULT_PREFIX)
                        if proc.returncode == 0
                        else None
                    )
                except (subprocess.SubprocessError, OSError) as e:
                    proc, metrics = None, None
                    err = str(e)
            if isinstance(metrics, dict) and "step_time_s" in metrics:
                t = Trial(
                    candidate=c,
                    status="measured",
                    predicted=predicted,
                    metrics=metrics,
                )
                journal.append(
                    {"event": "measured", "cid": c.cid, "metrics": metrics}
                )
                obs_metrics.TUNE_MEASURED.inc(status="ok")
                trials.append(t)
                measured.append(t)
            else:
                err = (
                    err
                    if proc is None
                    else (proc.stderr or proc.stdout or "")[-2000:]
                )
                journal.append(
                    {"event": "measure_failed", "cid": c.cid, "message": err}
                )
                obs_metrics.TUNE_MEASURED.inc(status="failed")
                trials.append(
                    Trial(
                        candidate=c,
                        status="measure_failed",
                        code="MEASURE_FAILED",
                        message=err,
                        predicted=predicted,
                    )
                )

        # survivors outside the measured head
        decided = {t.candidate.cid for t in trials}
        for c, plan, cost in ranked:
            if c.cid not in decided:
                trials.append(
                    Trial(
                        candidate=c,
                        status="ranked_out",
                        predicted={"step_cost": cost.to_dict()},
                    )
                )

        # -- stage 5: winner + calibration + artifact
        winner: Optional[Trial] = None
        good = [t for t in measured if t.metrics.get("tokens_per_sec_per_chip")]
        if good:
            winner = max(
                good, key=lambda t: t.metrics["tokens_per_sec_per_chip"]
            )
        elif not measure and ranked:
            c, plan, cost = ranked[0]
            winner = Trial(
                candidate=c,
                status="selected",
                predicted={"step_cost": cost.to_dict()},
            )
            trials = [
                t if t.candidate.cid != c.cid else winner for t in trials
            ]

        calibration_obs: dict[str, Any] = {}
        if winner is not None and winner.metrics.get("step_time_s"):
            cost_dict = winner.predicted.get("step_cost", {})
            pred_step = float(cost_dict.get("step_s") or 0.0)
            if pred_step > 0:
                calibration_obs = table.observe(
                    generation,
                    predicted_step_s=pred_step,
                    measured_step_s=float(winner.metrics["step_time_s"]),
                    predicted_collective_s=float(
                        cost_dict.get("collective_s") or 0.0
                    ),
                )
                table.save()

        pruned_static = sum(1 for t in trials if t.status == "pruned_static")
        pruned_aot = sum(1 for t in trials if t.status == "pruned_aot")
        by_code: dict[str, int] = {}
        for t in trials:
            if t.status.startswith("pruned"):
                by_code[t.code] = by_code.get(t.code, 0) + 1
        report = {
            "candidates": len(cands),
            "pruned_static": pruned_static,
            "pruned_aot": pruned_aot,
            "measured": len(measured),
            "measure_failed": sum(
                1 for t in trials if t.status == "measure_failed"
            ),
            "prune_rate": (pruned_static + pruned_aot) / len(cands),
            "pruned_by_code": dict(sorted(by_code.items())),
            "device_seconds_pruning": 0.0,
        }

        artifact_path = ""
        if winner is not None:
            wrole = role_for_candidate(winner.candidate, devices)
            wplan, _ = deep_preflight(
                wrole, devices=devices, hbm_bytes=hbm_bytes,
                calibration=scales,
            )
            fit = costmodel.hbm_fit(wplan, calibration=scales)
            traffic = costmodel.collective_traffic(wplan, calibration=scales)
            artifact = PlanArtifact(
                space=space.to_dict(),
                candidate=winner.candidate.to_dict(),
                plan=wplan.to_dict(),
                predictions={
                    **winner.predicted,
                    "hbm": fit.to_dict(),
                    "collective_bytes_per_step": {
                        t.axis: t.bytes_per_step for t in traffic
                    },
                },
                measurements=winner.metrics,
                calibration=calibration_obs,
                report=report,
            )
            artifact_path = artifact.save(
                os.path.join(out_dir, ARTIFACT_FILE)
            )
            journal_once(
                {
                    "event": "winner",
                    "cid": winner.candidate.cid,
                    "digest": artifact.digest,
                }
            )
        if sp is not None:
            sp.attrs["pruned"] = report["pruned_static"] + report["pruned_aot"]
            sp.attrs["measured"] = report["measured"]
            sp.attrs["winner"] = winner.candidate.cid if winner else ""

    return TuneResult(
        space=space,
        trials=trials,
        winner=winner,
        artifact_path=artifact_path,
        report=report,
        calibration=calibration_obs,
    )
