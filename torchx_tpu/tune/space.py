"""Declarative search space for the config autotuner.

A :class:`SearchSpace` is the cross product of the launcher-visible
training knobs the cost model can reason about: mesh spec x remat policy
x per-device batch x prefetch depth x int8 scope. Enumeration order is
deterministic (itertools.product over the declared tuples), so candidate
ids are stable across runs — the resumable journal and the plan-artifact
digest both key off them.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json

#: int8 scopes the trainer accepts ("none" = bf16 baseline; see
#: models/llama.py int8_scope).
INT8_SCOPES = ("none", "ffn", "all")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space (all launcher-side knobs resolved)."""

    config: str
    mesh_spec: str
    remat_policy: str
    batch: int
    seq: int
    prefetch: int = 2
    int8_scope: str = "none"

    @property
    def int8(self) -> bool:
        """True when any int8 scope is enabled (the ``--int8`` flag)."""
        return self.int8_scope != "none"

    @property
    def cid(self) -> str:
        """Stable, human-readable candidate id (journal / artifact key)."""
        return (
            f"{self.config}|{self.mesh_spec}|{self.remat_policy}"
            f"|b{self.batch}|s{self.seq}|pf{self.prefetch}"
            f"|i8={self.int8_scope}"
        )

    def to_dict(self) -> dict:
        """JSON-ready form (journal events, the plan artifact)."""
        return {
            "config": self.config,
            "mesh_spec": self.mesh_spec,
            "remat_policy": self.remat_policy,
            "batch": self.batch,
            "seq": self.seq,
            "prefetch": self.prefetch,
            "int8_scope": self.int8_scope,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Candidate":
        """Inverse of :meth:`to_dict`."""
        return cls(
            config=str(d["config"]),
            mesh_spec=str(d["mesh_spec"]),
            remat_policy=str(d["remat_policy"]),
            batch=int(d["batch"]),
            seq=int(d["seq"]),
            prefetch=int(d.get("prefetch", 2)),
            int8_scope=str(d.get("int8_scope", "none")),
        )


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    """The declarative config space one ``tpx tune`` run explores."""

    config: str
    mesh_specs: tuple[str, ...]
    remat_policies: tuple[str, ...]
    batches: tuple[int, ...]
    seq: int
    prefetch_depths: tuple[int, ...] = (2,)
    int8_scopes: tuple[str, ...] = ("none",)
    #: steps per measured trial (short seeded runs; step 1 is warmup)
    measure_steps: int = 8

    def __post_init__(self) -> None:
        for s in self.int8_scopes:
            if s not in INT8_SCOPES:
                raise ValueError(
                    f"int8_scope must be one of {INT8_SCOPES}, got {s!r}"
                )
        if not (self.mesh_specs and self.remat_policies and self.batches):
            raise ValueError("search space has an empty axis")

    def candidates(self) -> list[Candidate]:
        """Deterministic enumeration (the declared tuple order)."""
        return [
            Candidate(
                config=self.config,
                mesh_spec=mesh,
                remat_policy=policy,
                batch=batch,
                seq=self.seq,
                prefetch=pf,
                int8_scope=scope,
            )
            for mesh, policy, batch, pf, scope in itertools.product(
                self.mesh_specs,
                self.remat_policies,
                self.batches,
                self.prefetch_depths,
                self.int8_scopes,
            )
        ]

    def digest(self) -> str:
        """Content digest — a resumed journal must match it."""
        blob = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def to_dict(self) -> dict:
        """JSON-ready form — also the digest's canonical content."""
        return {
            "config": self.config,
            "mesh_specs": list(self.mesh_specs),
            "remat_policies": list(self.remat_policies),
            "batches": list(self.batches),
            "seq": self.seq,
            "prefetch_depths": list(self.prefetch_depths),
            "int8_scopes": list(self.int8_scopes),
            "measure_steps": self.measure_steps,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SearchSpace":
        """Inverse of :meth:`to_dict` (CLI ``--space file.json`` entry)."""
        return cls(
            config=str(d["config"]),
            mesh_specs=tuple(str(m) for m in d["mesh_specs"]),
            remat_policies=tuple(str(p) for p in d["remat_policies"]),
            batches=tuple(int(b) for b in d["batches"]),
            seq=int(d["seq"]),
            prefetch_depths=tuple(
                int(p) for p in d.get("prefetch_depths", (2,))
            ),
            int8_scopes=tuple(str(s) for s in d.get("int8_scopes", ("none",))),
            measure_steps=int(d.get("measure_steps", 8)),
        )


def bench_1b_space() -> SearchSpace:
    """The 1B bench space: every knob bench.py hand-picks today.

    The static funnel is expected to kill most of it — llama3_1b at
    seq 2048 overruns a 16 GiB chip for most of the batch x remat grid
    (TPX701), and the tp/sp specs cannot resolve on single-chip hosts
    (TPX703) — which is exactly the point: zero device seconds spent
    discovering what arithmetic already knows.
    """
    return SearchSpace(
        config="llama3_1b",
        mesh_specs=("fsdp=-1", "dp=-1", "fsdp=-1,tp=2", "fsdp=-1,sp=2"),
        remat_policies=("dots", "dots_attn", "full"),
        batches=(1, 2, 4, 8),
        seq=2048,
        prefetch_depths=(2, 4),
        int8_scopes=("none", "ffn"),
        measure_steps=12,
    )


def tiny_smoke_space() -> SearchSpace:
    """<= 6 candidates for the tier-1 TUNE_SMOKE / CPU bench fallback.

    ``tp=3`` cannot resolve onto a power-of-two device count, so static
    pruning deterministically kills half the space with TPX703.
    """
    return SearchSpace(
        config="tiny",
        mesh_specs=("fsdp=-1", "tp=3"),
        remat_policies=("full", "dots"),
        batches=(8,),
        seq=128,
        measure_steps=2,
    )


#: Builtin spaces addressable by name from the CLI (`tpx tune --space`).
BUILTIN_SPACES = {
    "bench-1b": bench_1b_space,
    "tiny-smoke": tiny_smoke_space,
}
