"""Closed-loop config autotuner over the deep-preflight cost model.

``tpx tune`` searches the training-config space (mesh spec x remat
policy x prefetch depth x per-device batch x int8 scope) without
spending device time on configs the static analyzer can already kill:

1. **Enumerate** — a declarative :class:`~torchx_tpu.tune.space.SearchSpace`
   expands into deterministic candidates.
2. **Prune statically** — every candidate runs through
   :func:`~torchx_tpu.analyze.explain.deep_preflight` (TPX700/701/703
   verdicts) and, optionally, the XLA AOT memory fit
   (``parallel/aot_fit.compile_fit`` in a batch subprocess). Zero device
   seconds; every kill is journaled with the verdict that caused it.
3. **Measure top-k** — survivors are ranked by predicted step cost
   (:mod:`~torchx_tpu.tune.rank`: collective bytes over ICI/DCN
   bandwidth + an HBM-pressure penalty) and only the top-k run short
   seeded bench trials (``tune/measure.py`` subprocess reusing the
   ``train_llama`` harness).
4. **Emit + recalibrate** — the winner becomes a content-digested
   **plan artifact** (:mod:`~torchx_tpu.tune.artifact`) the submit gate
   can pin (``$TPX_PLAN_ARTIFACT``, TPX706/707) and ``tpx explain`` can
   diff against; each measured run's prediction-vs-actual error updates
   the persisted per-generation calibration table
   (:mod:`~torchx_tpu.tune.calibrate`) that rescales ``costmodel.py``
   and feeds the fleet placer's ``hbm_refusal`` oracle.

The whole package is jax-free at module level (enforced by
``scripts/lint_internal.py``); only the measure / AOT-probe
*subprocesses* import jax.
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "Candidate",
    "SearchSpace",
    "CalibrationTable",
    "PlanArtifact",
    "TuneJournal",
    "run_tune",
]

_LAZY = {
    "Candidate": ("torchx_tpu.tune.space", "Candidate"),
    "SearchSpace": ("torchx_tpu.tune.space", "SearchSpace"),
    "CalibrationTable": ("torchx_tpu.tune.calibrate", "CalibrationTable"),
    "PlanArtifact": ("torchx_tpu.tune.artifact", "PlanArtifact"),
    "TuneJournal": ("torchx_tpu.tune.journal", "TuneJournal"),
    "run_tune": ("torchx_tpu.tune.driver", "run_tune"),
}


def __getattr__(name: str) -> Any:
    # lazy re-exports keep `import torchx_tpu.tune` free of the driver's
    # analyze/obs imports (and break the analyze <-> tune import cycle:
    # explain.py lazily imports tune.artifact for `--artifact` diffs)
    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(module), attr)
