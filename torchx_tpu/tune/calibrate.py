"""Persisted per-generation calibration of the static cost model.

The deep-preflight cost model (``analyze/costmodel.py``) is first-order
arithmetic; its activation and collective terms carry generation-specific
error (XLA fusion, padding, kernel choice). Every measured tune/bench run
closes the loop: the observed ``measured / predicted`` ratio nudges a
per-generation scale via an EMA with gain ``alpha`` in (0, 1), so

    err_after = |1 - alpha| * err_before  <  err_before

whenever prediction != measurement — the model provably gets closer with
every observation. ``costmodel.hbm_fit`` / ``collective_traffic`` accept
the scales as an optional ``calibration`` argument (default None keeps
the uncalibrated behavior bit-identical), and the fleet placer's
``hbm_refusal`` oracle loads the same table per pool generation.

The table is one JSON file under ``$TPX_TUNE_DIR`` (default
``~/.torchx_tpu/tune``), written atomically (tmp + fsync + ``os.replace``)
so concurrent readers never see a torn file.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Optional

from torchx_tpu import settings

#: EMA gain: one observation moves a scale halfway to the measured ratio.
DEFAULT_ALPHA = 0.5

CALIBRATION_FILE = "calibration.json"


def tune_dir() -> str:
    """State root for tune journals + the calibration table."""
    return os.environ.get(settings.ENV_TPX_TUNE_DIR) or os.path.join(
        os.path.expanduser("~"), ".torchx_tpu", "tune"
    )


def generation_key(name: str) -> str:
    """Normalize an accelerator string to a calibration key.

    ``"TPU v5e"`` / ``"v5litepod-8"`` / ``"v5e"`` -> ``"v5e"``; anything
    without a recognizable generation (CPU sim, empty) -> ``"cpu-sim"``.
    """
    m = re.search(r"v\d+[a-z]*", str(name).lower())
    return m.group(0) if m else "cpu-sim"


@dataclasses.dataclass
class CalibrationScales:
    """Multiplicative corrections for one accelerator generation.

    ``activation_scale`` rescales the activation-HBM term,
    ``collective_scale`` the per-axis collective bytes, and
    ``step_time_scale`` the end-to-end predicted step time (what the
    tune ranking and the bench error tracking consume).
    ``overlap_frac`` is the measured comm/compute overlap fraction (the
    step profiler's ``1 - exposed/modeled``): the ranking discounts the
    collective term by it instead of charging exposed comm at 100%.
    """

    activation_scale: float = 1.0
    collective_scale: float = 1.0
    step_time_scale: float = 1.0
    overlap_frac: float = 0.0
    samples: int = 0

    def to_dict(self) -> dict:
        return {
            "activation_scale": self.activation_scale,
            "collective_scale": self.collective_scale,
            "step_time_scale": self.step_time_scale,
            "overlap_frac": self.overlap_frac,
            "samples": self.samples,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CalibrationScales":
        return cls(
            activation_scale=float(d.get("activation_scale", 1.0)),
            collective_scale=float(d.get("collective_scale", 1.0)),
            step_time_scale=float(d.get("step_time_scale", 1.0)),
            overlap_frac=float(d.get("overlap_frac", 0.0)),
            samples=int(d.get("samples", 0)),
        )


class CalibrationTable:
    """The on-disk generation -> :class:`CalibrationScales` map."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._scales: dict[str, CalibrationScales] = {}

    # -- persistence -------------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "CalibrationTable":
        """Load a table (missing/corrupt file = identity scales)."""
        table = cls(path)
        try:
            with open(path) as f:
                raw = json.load(f)
            for gen, d in raw.get("generations", {}).items():
                table._scales[str(gen)] = CalibrationScales.from_dict(d)
        except (OSError, json.JSONDecodeError, AttributeError, TypeError):
            pass  # missing/corrupt table = identity scales
        return table

    @classmethod
    def load_default(cls) -> "CalibrationTable":
        """Load the shared table under ``$TPX_TUNE_DIR``."""
        return cls.load(os.path.join(tune_dir(), CALIBRATION_FILE))

    def save(self) -> None:
        """Atomically persist (tmp + fsync + ``os.replace``)."""
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def to_dict(self) -> dict:
        """The persisted JSON form."""
        return {
            "version": 1,
            "generations": {
                g: s.to_dict() for g, s in sorted(self._scales.items())
            },
        }

    # -- lookup / update ---------------------------------------------------

    def scales_for(self, generation: str) -> CalibrationScales:
        """Scales for one generation (identity when never observed)."""
        return self._scales.get(
            generation_key(generation), CalibrationScales()
        )

    def observe(
        self,
        generation: str,
        *,
        predicted_step_s: Optional[float] = None,
        measured_step_s: Optional[float] = None,
        predicted_collective_s: Optional[float] = None,
        predicted_hbm_bytes: Optional[float] = None,
        measured_hbm_bytes: Optional[float] = None,
        activation_bytes: Optional[float] = None,
        alpha: float = DEFAULT_ALPHA,
    ) -> dict[str, Any]:
        """Fold one prediction-vs-measurement pair into the table.

        The predictions must be the CALIBRATED ones (what the current
        scales produce), so the EMA converges on the residual error:
        with ``scale' = scale * (1 + alpha * (m/p - 1))`` the new
        calibrated prediction is ``p' = p * (1 + alpha * (m/p - 1))``
        and ``|p' - m| = (1 - alpha) * |p - m|`` — strictly smaller for
        ``alpha`` in (0, 1). Returns the before/after relative errors.
        """
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        gen = generation_key(generation)
        cur = self._scales.get(gen, CalibrationScales())
        out: dict[str, Any] = {"generation": gen, "alpha": alpha}

        def _fold(scale: float, pred: float, meas: float) -> tuple[float, dict]:
            err_before = abs(pred - meas) / meas
            new_scale = scale * (1.0 + alpha * (meas / pred - 1.0))
            err_after = abs(pred * (new_scale / scale) - meas) / meas
            return new_scale, {
                "predicted": pred,
                "measured": meas,
                "err_before": err_before,
                "err_after": err_after,
            }

        act, coll, step = (
            cur.activation_scale,
            cur.collective_scale,
            cur.step_time_scale,
        )
        if predicted_step_s and measured_step_s:
            step, out["step_time"] = _fold(
                step, predicted_step_s, measured_step_s
            )
            if predicted_collective_s:
                # attribute the same relative residual to the collective
                # term (the step-level measurement cannot split compute
                # from collectives; the shared ratio keeps both honest —
                # profiled runs refine it via observe_collectives, whose
                # measurement CAN split them)
                coll = coll * (1.0 + alpha * (
                    measured_step_s / predicted_step_s - 1.0
                ))
        if predicted_hbm_bytes and measured_hbm_bytes:
            # only the activation term is calibrated (params/optimizer
            # are exact arithmetic), so the scale update solves for the
            # activation share of the total-HBM residual:
            #   total' = total + act*(s'/s - 1) = total + alpha*(m - total)
            p, m = predicted_hbm_bytes, measured_hbm_bytes
            err_before = abs(p - m) / m
            act_share = float(activation_bytes or 0.0)
            if act_share > 0:
                new_act = max(0.05, act * (1.0 + alpha * (m - p) / act_share))
                total_after = p + act_share * (new_act / act - 1.0)
                act = new_act
            else:
                total_after = p
            out["hbm"] = {
                "predicted": p,
                "measured": m,
                "err_before": err_before,
                "err_after": abs(total_after - m) / m,
            }
        self._scales[gen] = CalibrationScales(
            activation_scale=act,
            collective_scale=coll,
            step_time_scale=step,
            overlap_frac=cur.overlap_frac,
            samples=cur.samples + 1,
        )
        out["scales"] = self._scales[gen].to_dict()
        return out

    def observe_collectives(
        self,
        generation: str,
        *,
        predicted_collective_s: float,
        measured_collective_s: float,
        alpha: float = DEFAULT_ALPHA,
    ) -> dict[str, Any]:
        """Fold a directly MEASURED collective-seconds observation into
        ``collective_scale``.

        :meth:`observe`'s step-level measurement cannot split compute
        from collectives, so it only shares the whole-step residual with
        the collective term. The step profiler (``obs/profile.py``)
        removes that limit: its per-phase attribution yields measured
        exposed-collective seconds per step, and this fold gives
        ``collective_scale`` its own EMA on the same contraction math as
        :meth:`observe` (``predicted_collective_s`` must be the
        CALIBRATED prediction, so the residual strictly shrinks).
        Returns the before/after relative errors and the new scales.
        """
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        if predicted_collective_s <= 0.0 or measured_collective_s <= 0.0:
            raise ValueError(
                "predicted_collective_s and measured_collective_s must be"
                f" > 0, got {predicted_collective_s} / {measured_collective_s}"
            )
        gen = generation_key(generation)
        cur = self._scales.get(gen, CalibrationScales())
        p, m = float(predicted_collective_s), float(measured_collective_s)
        new_scale = cur.collective_scale * (1.0 + alpha * (m / p - 1.0))
        self._scales[gen] = CalibrationScales(
            activation_scale=cur.activation_scale,
            collective_scale=new_scale,
            step_time_scale=cur.step_time_scale,
            overlap_frac=cur.overlap_frac,
            samples=cur.samples + 1,
        )
        return {
            "generation": gen,
            "alpha": alpha,
            "collectives": {
                "predicted": p,
                "measured": m,
                "err_before": abs(p - m) / m,
                "err_after": abs(p * (new_scale / cur.collective_scale) - m) / m,
            },
            "scales": self._scales[gen].to_dict(),
        }

    def observe_overlap(
        self,
        generation: str,
        *,
        measured_overlap_frac: float,
        alpha: float = DEFAULT_ALPHA,
    ) -> dict[str, Any]:
        """Fold a measured comm/compute overlap fraction into the table.

        The step profiler's summary reports ``overlap_frac = 1 -
        exposed/modeled`` per run; the EMA here (``new = old + alpha *
        (measured - old)``) converges on the schedule's steady overlap,
        and the ranking (:func:`torchx_tpu.tune.rank.predicted_step_cost`)
        charges only ``collective_s * (1 - overlap_frac)`` instead of the
        fully-serialized collective time. Clamped to [0, 0.95]: some
        collective time is always exposed (the last bucket has no
        compute left to hide behind), and a runaway 1.0 would make every
        collective free and un-rank mesh choices entirely.
        """
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        m = min(max(float(measured_overlap_frac), 0.0), 0.95)
        gen = generation_key(generation)
        cur = self._scales.get(gen, CalibrationScales())
        new_frac = min(
            max(cur.overlap_frac + alpha * (m - cur.overlap_frac), 0.0), 0.95
        )
        self._scales[gen] = CalibrationScales(
            activation_scale=cur.activation_scale,
            collective_scale=cur.collective_scale,
            step_time_scale=cur.step_time_scale,
            overlap_frac=new_frac,
            samples=cur.samples + 1,
        )
        return {
            "generation": gen,
            "alpha": alpha,
            "overlap": {
                "measured": m,
                "before": cur.overlap_frac,
                "after": new_frac,
            },
            "scales": self._scales[gen].to_dict(),
        }
