"""The tune plan artifact: a content-digested, pin-able winner record.

One JSON file carrying the winning candidate, the plan it resolves to,
the cost-model predictions, the measured trial metrics, the calibration
observation, and the prune-funnel report. The ``digest`` field is the
sha256 of the canonical JSON of everything else, so

* ``tpx run`` can PIN it: ``$TPX_PLAN_ARTIFACT=<path>`` makes the submit
  gate (``rules.check_plan_artifact``) diff every plan-shaped role
  against the artifact — divergence is TPX706, a corrupt/tampered file
  is TPX707;
* ``tpx explain --artifact <path>`` shows the same diff inline.

No timestamps: the artifact of a deterministic space + measurements is
itself deterministic, which keeps digests reproducible in CI.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Optional

ARTIFACT_VERSION = 1

#: plan fields the pin actually compares — the knobs a tune run chose.
#: Topology fields (devices, hbm) deliberately excluded: the same tuned
#: config is valid on any pool the preflight HBM fit accepts.
PINNED_PLAN_FIELDS = (
    "config",
    "mesh",
    "batch",
    "seq",
    "remat_policy",
    "int8",
)


class ArtifactError(ValueError):
    """The artifact file is unreadable, malformed, or fails its digest."""


def _canonical(core: dict[str, Any]) -> bytes:
    return json.dumps(core, sort_keys=True, separators=(",", ":")).encode()


@dataclasses.dataclass
class PlanArtifact:
    """The winner of one tune run (see module docstring)."""

    space: dict[str, Any]
    candidate: dict[str, Any]
    plan: dict[str, Any]
    predictions: dict[str, Any] = dataclasses.field(default_factory=dict)
    measurements: dict[str, Any] = dataclasses.field(default_factory=dict)
    calibration: dict[str, Any] = dataclasses.field(default_factory=dict)
    report: dict[str, Any] = dataclasses.field(default_factory=dict)
    version: int = ARTIFACT_VERSION

    def core_dict(self) -> dict[str, Any]:
        """Everything the digest covers (all fields but the digest)."""
        return {
            "version": self.version,
            "space": self.space,
            "candidate": self.candidate,
            "plan": self.plan,
            "predictions": self.predictions,
            "measurements": self.measurements,
            "calibration": self.calibration,
            "report": self.report,
        }

    @property
    def digest(self) -> str:
        """sha256 of the canonical JSON of :meth:`core_dict`."""
        return hashlib.sha256(_canonical(self.core_dict())).hexdigest()

    def to_dict(self) -> dict[str, Any]:
        """The saved JSON form: the core plus its digest."""
        return {**self.core_dict(), "digest": self.digest}

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "PlanArtifact":
        """Parse + digest-verify (raises :class:`ArtifactError`)."""
        try:
            art = cls(
                space=dict(raw["space"]),
                candidate=dict(raw["candidate"]),
                plan=dict(raw["plan"]),
                predictions=dict(raw.get("predictions", {})),
                measurements=dict(raw.get("measurements", {})),
                calibration=dict(raw.get("calibration", {})),
                report=dict(raw.get("report", {})),
                version=int(raw.get("version", ARTIFACT_VERSION)),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise ArtifactError(f"malformed plan artifact: {e}") from e
        recorded = raw.get("digest")
        if recorded is not None and recorded != art.digest:
            raise ArtifactError(
                f"plan artifact digest mismatch: recorded {recorded[:12]}…"
                f" != computed {art.digest[:12]}… (edited by hand?)"
            )
        return art

    def save(self, path: str) -> str:
        """Atomically write the artifact (tmp + fsync + replace)."""
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def diff_plan(self, plan_dict: dict[str, Any]) -> list[str]:
        """Field-level differences between a role's resolved plan and the
        pinned winner, restricted to :data:`PINNED_PLAN_FIELDS`. The mesh
        compares only axes either side sets > 1 (wildcard resolution may
        differ in trivial axes)."""
        diffs: list[str] = []
        for key in PINNED_PLAN_FIELDS:
            want, got = self.plan.get(key), plan_dict.get(key)
            if key == "mesh":
                want = {
                    a: v for a, v in (want or {}).items() if int(v) != 1
                }
                got = {a: v for a, v in (got or {}).items() if int(v) != 1}
            if want != got:
                diffs.append(f"{key}: artifact={want!r} plan={got!r}")
        return diffs


def load_artifact(path: str) -> PlanArtifact:
    """Load + digest-verify an artifact file (raises ArtifactError)."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ArtifactError(f"cannot read plan artifact {path!r}: {e}") from e
    if not isinstance(raw, dict):
        raise ArtifactError(f"plan artifact {path!r} is not a JSON object")
    return PlanArtifact.from_dict(raw)


def pinned_artifact_path() -> Optional[str]:
    """The ``$TPX_PLAN_ARTIFACT`` pin, if set (submit-gate entry)."""
    from torchx_tpu import settings

    path = os.environ.get(settings.ENV_TPX_PLAN_ARTIFACT, "").strip()
    return path or None
