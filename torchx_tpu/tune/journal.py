"""Fsync'd JSONL trial journal — a killed tune resumes, not restarts.

One event per line, fsync'd after every append (a tune run is low-rate:
tens of events, each potentially minutes apart — durability beats
throughput here). Replay skips torn trailing lines (a kill mid-write
leaves at most one), so resume sees exactly the completed events.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator, Optional


class TuneJournal:
    """Append-only event journal for one tune run."""

    def __init__(self, path: str) -> None:
        self.path = path

    def append(self, event: dict[str, Any]) -> None:
        """Durably append one event (mkdir + O_APPEND + flush + fsync)."""
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(event, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def replay(self) -> list[dict[str, Any]]:
        """Every durably-written event, in order; torn lines skipped."""
        out: list[dict[str, Any]] = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        # a kill mid-append leaves one torn line; anything
                        # after it was never acknowledged, so stop here
                        break
        except OSError:
            pass
        return out

    def events(self, kind: str) -> Iterator[dict[str, Any]]:
        """Replayed events of one kind."""
        for e in self.replay():
            if e.get("event") == kind:
                yield e

    def space_digest(self) -> Optional[str]:
        """The space digest of the run this journal belongs to, if any."""
        for e in self.events("enumerated"):
            return str(e.get("space_digest", "")) or None
        return None

    def measured(self) -> dict[str, dict[str, Any]]:
        """cid -> metrics for every trial with a durable ``measured``
        event (the resume unit: a trial with only ``measure_start`` was
        killed mid-flight and re-measures)."""
        return {
            str(e["cid"]): dict(e.get("metrics", {}))
            for e in self.events("measured")
        }

    def reset(self) -> None:
        """Discard the journal (space changed: a resume would lie)."""
        try:
            os.remove(self.path)
        except OSError:
            pass
