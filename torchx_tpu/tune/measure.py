"""Measured-trial subprocess for the tune driver.

``python -m torchx_tpu.tune.measure`` reads one trial spec (JSON) on
stdin, runs a short seeded training trial through the real
``examples/train_llama.train`` harness (the same code path bench.py
measures), and prints ONE JSON result line prefixed ``TUNE_METRICS ``
on stdout. All jax imports live inside function bodies: the module
itself stays importable under the package's jax-free lint, and only
this *subprocess* ever initializes a backend — the driver never does.

Spec fields: ``candidate`` (tune/space.Candidate dict), optional
``steps``, ``data_path``, ``seed``.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Optional

RESULT_PREFIX = "TUNE_METRICS "

#: metrics keys copied from the trainer's result into the trial record.
_KEEP = (
    "tokens_per_sec_per_chip",
    "mfu",
    "step_time_s",
    "loss",
    "remat_policy",
    "launch_to_first_step_s",
    "data_wait_frac",
)


def measure(spec: dict[str, Any]) -> dict[str, Any]:
    """Run one trial and return the trimmed metrics dict."""
    from torchx_tpu.examples.train_llama import all_configs, train
    from torchx_tpu.parallel.mesh_config import MeshConfig, parse_mesh_spec
    from torchx_tpu.tune.space import Candidate

    cand = Candidate.from_dict(spec["candidate"])
    overrides: dict[str, Any] = {"remat_policy": cand.remat_policy}
    if cand.int8:
        overrides["int8_matmuls"] = True
        overrides["int8_scope"] = cand.int8_scope
    cfg = all_configs()[cand.config](**overrides)

    mesh_cfg = (
        parse_mesh_spec(cand.mesh_spec) if cand.mesh_spec else MeshConfig()
    )
    steps = int(spec.get("steps", 4))
    metrics = train(
        cfg,
        mesh_cfg,
        batch=cand.batch,
        seq=cand.seq,
        steps=steps,
        log_every=max(1, steps // 2),
        prefetch=cand.prefetch,
        data_path=spec.get("data_path"),
    )
    out = {k: metrics[k] for k in _KEEP if k in metrics}
    out["steps"] = steps
    out["cid"] = cand.cid
    return out


def main(argv: Optional[list[str]] = None) -> int:
    args = sys.argv[1:] if argv is None else argv
    if args and args[0] not in ("-",):
        with open(args[0]) as f:
            spec = json.load(f)
    else:
        spec = json.load(sys.stdin)
    result = measure(spec)
    print(RESULT_PREFIX + json.dumps(result, sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
