"""Docker workspaces: patch the role image with local code.

Reference analog: torchx/workspace/docker_workspace.py (274 LoC):
tar a build context from the workspace (auto-generating
``Dockerfile.tpx`` = ``FROM $image\\nCOPY . .`` when absent), docker-build a
patched image labeled with the launcher version, re-point ``role.image`` at
the built sha, and push ``sha256:`` images to ``image_repo`` before remote
submission.

The docker SDK import is deferred and injectable so dryrun-level tests run
without a docker daemon.
"""

from __future__ import annotations

import io
import logging
import os
import tarfile
from typing import Any, Mapping, Optional, TYPE_CHECKING

from torchx_tpu.specs.api import AppDef, CfgVal, Role, Workspace, runopts
from torchx_tpu.version import __version__
from torchx_tpu.workspace.api import WorkspaceMixin, walk_workspace

if TYPE_CHECKING:
    from docker import DockerClient

logger = logging.getLogger(__name__)

TPX_DOCKERFILE = "Dockerfile.tpx"
_DEFAULT_DOCKERFILE = b"""ARG IMAGE
FROM $IMAGE

COPY . .
"""

LABEL_VERSION = "sh.tpx.version"


class DockerWorkspaceMixin(WorkspaceMixin["dict[str, tuple[str, str]]"]):
    """Builds patched images; tracks sha-images that need pushing."""

    def __init__(
        self,
        *args: Any,
        docker_client: Optional["DockerClient"] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(*args, **kwargs)
        self.__docker_client = docker_client

    @property
    def _docker_client(self) -> "DockerClient":
        if self.__docker_client is None:
            import docker

            self.__docker_client = docker.from_env()
        return self.__docker_client

    def workspace_opts(self) -> runopts:
        opts = runopts()
        opts.add(
            "image_repo",
            type_=str,
            default=None,
            help="remote repo to push patched images to (e.g."
            " us-docker.pkg.dev/proj/repo/app); required for remote schedulers"
            " when a workspace is used",
        )
        return opts

    def build_workspace_and_update_role(
        self, role: Role, workspace: Workspace, cfg: Mapping[str, CfgVal]
    ) -> None:
        context = build_context(role.image, workspace)
        try:
            image, _ = self._docker_client.images.build(
                fileobj=context,
                custom_context=True,
                pull=False,
                rm=True,
                labels={LABEL_VERSION: __version__},
                buildargs={"IMAGE": role.image},
            )
        finally:
            context.close()
        role.image = image.id  # sha256:... until pushed

    # -- push contract (reference docker_workspace.py:146-189) -------------

    def dryrun_push_images(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> dict[str, tuple[str, str]]:
        """Rewrite any ``sha256:`` role images to ``{image_repo}:{hash}``
        tags and return {old_image: (repo, tag)} for :meth:`push_images`."""
        images_to_push: dict[str, tuple[str, str]] = {}
        image_repo = cfg.get("image_repo")
        for role in app.roles:
            if role.image.startswith("sha256:"):
                if not image_repo:
                    raise KeyError(
                        f"role {role.name} has a locally-built image"
                        f" ({role.image[:19]}...); configure image_repo to"
                        " push it for remote execution"
                    )
                tag = role.image.removeprefix("sha256:")[:12]
                images_to_push[role.image] = (str(image_repo), tag)
                role.image = f"{image_repo}:{tag}"
        return images_to_push

    def push_images(self, images_to_push: dict[str, tuple[str, str]]) -> None:
        if not images_to_push:
            return
        client = self._docker_client
        for local_image, (repo, tag) in images_to_push.items():
            img = client.images.get(local_image)
            img.tag(repo, tag=tag)
            logger.info("pushing %s:%s ...", repo, tag)
            for line in client.images.push(repo, tag=tag, stream=True, decode=True):
                if "error" in line:
                    raise RuntimeError(f"failed to push {repo}:{tag}: {line['error']}")


def build_context(image: str, workspace: Workspace) -> io.BytesIO:
    """In-memory tar build context: workspace files + Dockerfile.

    A user-provided ``Dockerfile.tpx`` in the workspace root wins over the
    generated ``COPY . .`` one (reference docker_workspace.py:30-37).
    """
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        has_custom_dockerfile = False
        for src_dir, dst_sub in workspace.projects.items():
            for abs_path, rel_path in walk_workspace(src_dir):
                arcname = os.path.join(dst_sub, rel_path) if dst_sub else rel_path
                if arcname == TPX_DOCKERFILE:
                    has_custom_dockerfile = True
                    tar.add(abs_path, arcname="Dockerfile")
                    continue
                tar.add(abs_path, arcname=arcname)
        if not has_custom_dockerfile:
            info = tarfile.TarInfo("Dockerfile")
            info.size = len(_DEFAULT_DOCKERFILE)
            tar.addfile(info, io.BytesIO(_DEFAULT_DOCKERFILE))
    buf.seek(0)
    return buf
