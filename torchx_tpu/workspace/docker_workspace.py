"""Docker workspaces: patch the role image with local code.

Reference analog: torchx/workspace/docker_workspace.py (274 LoC):
tar a build context from the workspace (auto-generating
``Dockerfile.tpx`` = ``FROM $image\\nCOPY . .`` when absent), docker-build a
patched image labeled with the launcher version, re-point ``role.image`` at
the built sha, and push ``sha256:`` images to ``image_repo`` before remote
submission.

The docker SDK import is deferred and injectable so dryrun-level tests run
without a docker daemon.
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import tarfile
from typing import Any, Mapping, Optional, TYPE_CHECKING

from torchx_tpu.specs.api import AppDef, CfgVal, Role, Workspace, runopts
from torchx_tpu.version import __version__
from torchx_tpu.workspace.api import WorkspaceMixin, walk_workspace

if TYPE_CHECKING:
    from docker import DockerClient

logger = logging.getLogger(__name__)

TPX_DOCKERFILE = "Dockerfile.tpx"
_DEFAULT_DOCKERFILE = b"""ARG IMAGE
FROM $IMAGE

COPY . .
"""

LABEL_VERSION = "sh.tpx.version"
LABEL_CONTENT_HASH = "sh.tpx.content-hash"


class DockerWorkspaceMixin(WorkspaceMixin["dict[str, tuple[str, str]]"]):
    """Builds patched images; tracks sha-images that need pushing."""

    def __init__(
        self,
        *args: Any,
        docker_client: Optional["DockerClient"] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(*args, **kwargs)
        self.__docker_client = docker_client

    @property
    def _docker_client(self) -> "DockerClient":
        if self.__docker_client is None:
            import docker

            self.__docker_client = docker.from_env()
        return self.__docker_client

    def workspace_opts(self) -> runopts:
        """Adds ``image_repo`` (remote repo for patched images)."""
        opts = runopts()
        opts.add(
            "image_repo",
            type_=str,
            default=None,
            help="remote repo to push patched images to (e.g."
            " us-docker.pkg.dev/proj/repo/app); required for remote schedulers"
            " when a workspace is used",
        )
        return opts

    def build_workspace_and_update_role(
        self, role: Role, workspace: Workspace, cfg: Mapping[str, CfgVal]
    ) -> None:
        # skip-if-unchanged: an image labeled with the same content digest
        # already has this exact workspace baked in — re-point and return
        # without a build (reference analog: torchx/workspace/api.py:97-154
        # build caching + docker_workspace.py:92-144 image re-point).
        # The digest keys on the RESOLVED base image id (not just the tag)
        # so a re-pulled/moved tag invalidates the cache.
        context, digest = build_context_with_digest(
            f"{role.image}@{self._resolve_image_id(role.image)}", workspace
        )
        cached = self._find_cached_image(digest)
        if cached is not None:
            logger.info("workspace unchanged (digest %s); reusing %s",
                        digest[:12], cached[:19])
            role.image = cached
            context.close()
            return
        try:
            image, _ = self._docker_client.images.build(
                fileobj=context,
                custom_context=True,
                pull=False,
                rm=True,
                labels={LABEL_VERSION: __version__, LABEL_CONTENT_HASH: digest},
                buildargs={"IMAGE": role.image},
            )
        finally:
            context.close()
        role.image = image.id  # sha256:... until pushed

    def _resolve_image_id(self, image: str) -> str:
        try:
            return str(self._docker_client.images.get(image).id)
        except Exception:  # noqa: BLE001 - unknown local image: tag alone keys the digest
            return ""

    def _find_cached_image(self, digest: str) -> Optional[str]:
        try:
            images = self._docker_client.images.list(
                filters={"label": f"{LABEL_CONTENT_HASH}={digest}"}
            )
        except Exception as e:  # noqa: BLE001 - cache probe must never block a build
            logger.debug("image-cache lookup failed (%s); building", e)
            return None
        return images[0].id if images else None

    # -- push contract (reference docker_workspace.py:146-189) -------------

    def dryrun_push_images(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> dict[str, tuple[str, str]]:
        """Rewrite any ``sha256:`` role images to ``{image_repo}:{hash}``
        tags and return {old_image: (repo, tag)} for :meth:`push_images`."""
        images_to_push: dict[str, tuple[str, str]] = {}
        image_repo = cfg.get("image_repo")
        for role in app.roles:
            if role.image.startswith("sha256:"):
                if not image_repo:
                    raise KeyError(
                        f"role {role.name} has a locally-built image"
                        f" ({role.image[:19]}...); configure image_repo to"
                        " push it for remote execution"
                    )
                tag = role.image.removeprefix("sha256:")[:12]
                images_to_push[role.image] = (str(image_repo), tag)
                role.image = f"{image_repo}:{tag}"
        return images_to_push

    def push_images(self, images_to_push: dict[str, tuple[str, str]]) -> None:
        """Tag + push each locally-built image to its planned repo:tag."""
        if not images_to_push:
            return
        client = self._docker_client
        for local_image, (repo, tag) in images_to_push.items():
            img = client.images.get(local_image)
            img.tag(repo, tag=tag)
            logger.info("pushing %s:%s ...", repo, tag)
            for line in client.images.push(repo, tag=tag, stream=True, decode=True):
                if "error" in line:
                    raise RuntimeError(f"failed to push {repo}:{tag}: {line['error']}")


def build_context_with_digest(
    image: str, workspace: Workspace
) -> tuple[io.BytesIO, str]:
    """One walk over the workspace tree -> (tar build context, content digest).

    The digest covers everything the build recipe depends on — base image
    key, generated Dockerfile, builder version, and each entry's path,
    permission bits, and bytes (symlinks hash their target; non-regular
    files like FIFOs hash a type tag and are never opened) — so any edit
    forces a rebuild while an untouched tree reuses the cached image. Each
    file is read ONCE, feeding the hash and the tar together.

    A user-provided ``Dockerfile.tpx`` in the workspace root wins over the
    generated ``COPY . .`` one (reference docker_workspace.py:30-37).
    """
    h = hashlib.sha256()
    h.update(image.encode())
    h.update(_DEFAULT_DOCKERFILE)
    h.update(__version__.encode())
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        has_custom_dockerfile = False
        for src_dir, dst_sub in sorted(workspace.projects.items()):
            entries = sorted(walk_workspace(src_dir), key=lambda e: e[1])
            for abs_path, rel_path in entries:
                arcname = os.path.join(dst_sub, rel_path) if dst_sub else rel_path
                if arcname == TPX_DOCKERFILE:
                    has_custom_dockerfile = True
                    arcname = "Dockerfile"
                info = tar.gettarinfo(abs_path, arcname=arcname)
                h.update(f"\x00{arcname}\x00{info.mode & 0o777:o}\x00".encode())
                if info.issym():
                    h.update(b"link:" + info.linkname.encode())
                    tar.addfile(info)
                elif info.isreg():
                    with open(abs_path, "rb") as f:
                        data = f.read()
                    h.update(data)
                    tar.addfile(info, io.BytesIO(data))
                else:  # FIFO/device/etc: archive the entry, never open it
                    h.update(b"special:" + str(info.type).encode())
                    tar.addfile(info)
        if not has_custom_dockerfile:
            info = tarfile.TarInfo("Dockerfile")
            info.size = len(_DEFAULT_DOCKERFILE)
            tar.addfile(info, io.BytesIO(_DEFAULT_DOCKERFILE))
    buf.seek(0)
    return buf, h.hexdigest()


def workspace_digest(image: str, workspace: Workspace) -> str:
    """Deterministic content hash of (base image key, workspace tree)."""
    context, digest = build_context_with_digest(image, workspace)
    context.close()
    return digest


def build_context(image: str, workspace: Workspace) -> io.BytesIO:
    """In-memory tar build context: workspace files + Dockerfile."""
    return build_context_with_digest(image, workspace)[0]
