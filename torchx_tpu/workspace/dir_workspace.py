"""Directory workspaces: snapshot local code into a job dir.

Reference analog: torchx/workspace/dir_workspace.py (66 LoC). Used by the
Slurm / TPU-VM path where the "image" is a shared-filesystem directory.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Mapping

from torchx_tpu.specs.api import CfgVal, Role, Workspace, runopts
from torchx_tpu.workspace.api import WorkspaceMixin, walk_workspace


def copy_workspace(workspace: Workspace, dst_root: str) -> int:
    """Copy every non-ignored file of every project into dst_root;
    returns the file count."""
    count = 0
    for src_dir, dst_sub in workspace.projects.items():
        dst_dir = os.path.join(dst_root, dst_sub) if dst_sub else dst_root
        for abs_path, rel_path in walk_workspace(src_dir):
            dst = os.path.join(dst_dir, rel_path)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copy2(abs_path, dst)
            count += 1
    return count


class DirWorkspaceMixin(WorkspaceMixin[None]):
    """Copies the workspace into ``{job_dir}/workspace`` and points
    role.image there."""

    def workspace_opts(self) -> runopts:
        """Adds ``job_dir`` (shared directory the workspace copies into)."""
        opts = runopts()
        opts.add(
            "job_dir",
            type_=str,
            default=None,
            help="shared-filesystem directory to snapshot the workspace into"
            " (e.g. an NFS/Lustre path visible on all hosts)",
        )
        return opts

    def build_workspace_and_update_role(
        self, role: Role, workspace: Workspace, cfg: Mapping[str, CfgVal]
    ) -> None:
        job_dir = cfg.get("job_dir")
        if job_dir is None:
            return  # no job dir configured: run from the original image/dir
        dst = os.path.join(str(job_dir), "workspace")
        os.makedirs(dst, exist_ok=True)
        copy_workspace(workspace, dst)
        role.image = dst


class TmpDirWorkspaceMixin(DirWorkspaceMixin):
    """Like DirWorkspaceMixin but snapshots into a fresh temp dir — the
    local-scheduler workspace mode."""

    def build_workspace_and_update_role(
        self, role: Role, workspace: Workspace, cfg: Mapping[str, CfgVal]
    ) -> None:
        dst = tempfile.mkdtemp(prefix="tpx_workspace_")
        copy_workspace(workspace, dst)
        role.image = dst
