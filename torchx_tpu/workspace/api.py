"""Workspace layer: package local code into the job's image.

Reference analog: torchx/workspace/api.py (247 LoC). ``WorkspaceMixin`` is
mixed into scheduler classes; ``build_workspaces`` re-points ``role.image``
at the built artifact (a patched docker image, or a snapshot directory).
Includes the ``.tpxignore``/``.dockerignore`` walker with ``!`` negation.
"""

from __future__ import annotations

import fnmatch
import os
import posixpath
from abc import abstractmethod
from typing import Any, Generic, Iterable, Mapping, TypeVar

from torchx_tpu.specs.api import CfgVal, Role, Workspace, runopts

T = TypeVar("T")  # workspace build artifact type

IGNORE_FILES = (".tpxignore", ".torchxignore", ".dockerignore")


class WorkspaceMixin(Generic[T]):
    """Adds workspace building to a Scheduler.

    ``build_workspaces(roles, cfg)`` builds each distinct (image, workspace)
    pair once (build cache) and mutates ``role.image`` to the result
    (reference api.py:97-154).
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)

    def workspace_opts(self) -> runopts:
        """Extra runopts this workspace type contributes to the
        scheduler's schema (empty by default)."""
        return runopts()

    @abstractmethod
    def build_workspace_and_update_role(
        self, role: Role, workspace: Workspace, cfg: Mapping[str, CfgVal]
    ) -> None:
        """Build the workspace for one role and mutate role.image in place."""
        ...

    def build_workspaces(
        self, roles: list[Role], cfg: Mapping[str, CfgVal],
        max_workers: int = 4,
    ) -> None:
        """Build each role's workspace (once per distinct (image,
        projects) pair — results are cached) and mutate ``role.image`` to
        the built artifact.

        Distinct pairs build CONCURRENTLY on a bounded thread pool (each
        build is mostly subprocess/IO: docker build, snapshot copy), so a
        multi-role app pays the wall-clock of its slowest build rather
        than the sum. Role mutation order stays deterministic: the first
        role carrying each key is the one whose build runs; the rest take
        the cached image afterwards, in role order."""
        # capture keys BEFORE building: builds mutate role.image in place
        role_keys = [
            (role, (role.image, tuple(sorted(role.workspace.projects.items()))))
            for role in roles
            if role.workspace
        ]
        keyed: dict[tuple[str, tuple[tuple[str, str], ...]], Role] = {}
        for role, key in role_keys:
            keyed.setdefault(key, role)
        if not keyed:
            return

        import logging

        log = logging.getLogger(__name__)

        def _build(role: Role) -> str:
            old_image = role.image
            self.build_workspace_and_update_role(role, role.workspace, cfg)
            if role.image != old_image:
                log.info(
                    "built workspace for role %s: %s -> %s",
                    role.name,
                    old_image,
                    role.image,
                )
            return role.image

        cache: dict[tuple[str, tuple[tuple[str, str], ...]], str] = {}
        if len(keyed) == 1:
            ((key, role),) = keyed.items()
            cache[key] = _build(role)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(max_workers, len(keyed)),
                thread_name_prefix="tpx-ws-build",
            ) as pool:
                futures = {
                    key: pool.submit(_build, role) for key, role in keyed.items()
                }
            for key in futures:
                cache[key] = futures[key].result()  # re-raises build errors
        for role, key in role_keys:
            role.image = cache[key]

    # push contract for docker-ish backends (reference api.py:169-179)
    def dryrun_push_images(self, app: Any, cfg: Mapping[str, CfgVal]) -> Any:
        """Plan remote-image pushes for locally-built images; returns an
        opaque plan for :meth:`push_images` (None = nothing to push)."""
        return None

    def push_images(self, images_to_push: Any) -> None:
        """Execute the push plan from :meth:`dryrun_push_images`."""
        pass


# =========================================================================
# Ignore-file walker
# =========================================================================


def _load_ignore_patterns(root: str) -> list[str]:
    patterns: list[str] = []
    for name in IGNORE_FILES:
        path = os.path.join(root, name)
        if os.path.isfile(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        patterns.append(line)
    return patterns


def _is_ignored(rel_path: str, patterns: list[str]) -> bool:
    """dockerignore-style matching with ``!`` negation; last match wins."""
    ignored = False
    for pat in patterns:
        negate = pat.startswith("!")
        if negate:
            pat = pat[1:]
        pat = pat.rstrip("/")
        # a pattern matches the path itself or any parent directory
        hit = fnmatch.fnmatch(rel_path, pat) or fnmatch.fnmatch(
            rel_path, pat + "/*"
        )
        if not hit:
            parts = rel_path.split("/")
            hit = any(
                fnmatch.fnmatch("/".join(parts[: i + 1]), pat)
                for i in range(len(parts))
            )
        if hit:
            ignored = not negate
    return ignored


def walk_workspace(root: str) -> Iterable[tuple[str, str]]:
    """Yield (abs_path, rel_path) for every non-ignored file under root,
    honoring .tpxignore/.dockerignore (reference api.py:182-247)."""
    root = os.path.abspath(root)
    patterns = _load_ignore_patterns(root)
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        rel_dir = "" if rel_dir == "." else rel_dir.replace(os.sep, "/")
        # prune ignored directories in place so we never descend
        dirnames[:] = [
            d
            for d in dirnames
            if not _is_ignored(posixpath.join(rel_dir, d) if rel_dir else d, patterns)
        ]
        for fname in filenames:
            rel = posixpath.join(rel_dir, fname) if rel_dir else fname
            if fname in IGNORE_FILES:
                continue
            if _is_ignored(rel, patterns):
                continue
            yield os.path.join(dirpath, fname), rel
