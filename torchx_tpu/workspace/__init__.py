from torchx_tpu.workspace.api import WorkspaceMixin, walk_workspace  # noqa: F401
