"""``tpx sim`` — run the fleet control plane on virtual time.

Two verbs over :mod:`torchx_tpu.sim`:

* ``tpx sim scenarios`` lists the bundled scenarios;
* ``tpx sim run --scenario <name|file.json>`` wires the **production**
  scheduler/reconciler/SLO/pipeline stack onto the virtual clock and
  replays the scenario, printing a run report and the journal path. The
  journal bytes are a pure function of ``(scenario, seed)`` — diff two
  journals to regression-test a control-plane change at fleet scale.

Module level stays jax-free (``tpx sim --help`` must not import jax):
the whole sim subsystem is on the lint gate's JAX_FREE list, and the
harness only pulls in jax-free control-plane modules.

Exit codes: 0 run completed, 1 scenario/run errors, 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from torchx_tpu.cli.cmd_base import SubCommand


class CmdSim(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        sub = subparser.add_subparsers(dest="action", required=True)

        run = sub.add_parser(
            "run", help="run one scenario on the virtual clock"
        )
        run.add_argument(
            "--scenario",
            type=str,
            default="smoke-tiny",
            help="bundled scenario name (see `tpx sim scenarios`) or a"
            " scenario JSON file path",
        )
        run.add_argument(
            "--seed",
            type=int,
            default=None,
            help="override the scenario's seed (same seed ="
            " byte-identical journal)",
        )
        run.add_argument(
            "--journal",
            type=str,
            default=None,
            help="where to write the run journal (default:"
            " <state-dir>/sim_journal.jsonl)",
        )
        run.add_argument(
            "--out",
            type=str,
            default=None,
            help="state directory for component journals and artifacts"
            " (default: a throwaway temp dir)",
        )
        run.add_argument(
            "--json",
            action="store_true",
            help="emit the full run report as JSON",
        )

        sub.add_parser("scenarios", help="list the bundled scenarios")

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.sim import BUNDLED_SCENARIOS, get_scenario

        if args.action == "scenarios":
            for name in sorted(BUNDLED_SCENARIOS):
                sc = BUNDLED_SCENARIOS[name]
                print(
                    f"{name}: fleet={sc['fleet']}"
                    f" hours={sc.get('hours', 0)}"
                    f" faults={len(sc.get('faults', []))}"
                    f" pipelines={len(sc.get('pipelines', []))}"
                )
            return

        try:
            scenario = get_scenario(args.scenario)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(2)

        # a scenario with a "cells" list is a federation scenario and
        # runs on the federation harness (TPX605 checks its shape); all
        # others run the single-cell fleet harness (TPX604)
        federated = bool(scenario.get("cells"))
        if federated:
            from torchx_tpu.analyze.rules import check_federation_config

            diags = check_federation_config(scenario)
        else:
            from torchx_tpu.analyze.rules import check_sim_scenario

            diags = check_sim_scenario(scenario)
        for diag in diags:
            print(
                f"{diag.severity.value}[{diag.code}]: {diag.message}"
                + (f"\n  hint: {diag.hint}" if diag.hint else ""),
                file=sys.stderr,
            )

        if federated:
            from torchx_tpu.federation.sim import FederationSimHarness

            harness_cls = FederationSimHarness
        else:
            from torchx_tpu.sim import SimHarness

            harness_cls = SimHarness
        try:
            report = harness_cls(
                scenario,
                seed=args.seed,
                state_dir=args.out,
                journal_path=args.journal,
            ).run()
        except (ValueError, OSError) as e:
            print(f"error: sim run failed: {e}", file=sys.stderr)
            sys.exit(1)

        if args.json:
            print(json.dumps(report.to_dict(), indent=2))
        elif federated:
            print(self._render_fed(report))
        else:
            print(self._render(report))

    @staticmethod
    def _render_fed(report) -> str:  # noqa: ANN001 - SimReport
        s = report.stats
        per_cell = s.get("per_cell") or {}
        lines = [
            f"fed sim: {report.scenario} seed={report.seed} —"
            f" {report.virtual_s / 3600.0:.2f} virtual hours in"
            f" {report.wall_s:.2f}s wall ({report.speedup:,.0f}x)",
            f"  requests: {s.get('requests', 0)} served,"
            f" {s.get('dropped', 0)} dropped,"
            f" {s.get('spillovers', 0)} spilled cross-cell",
            f"  ttft p99: {s.get('ttft_p99_s', 0.0):.3f}s overall"
            f" (pre {s.get('ttft_p99_pre_s', 0.0):.3f}s,"
            f" failover {s.get('ttft_p99_during_s', 0.0):.3f}s,"
            f" post {s.get('ttft_p99_post_s', 0.0):.3f}s)",
            "  per cell: "
            + ", ".join(f"{c}={n}" for c, n in sorted(per_cell.items())),
        ]
        lines.append(f"journal: {report.journal_path}")
        lines.append(f"sha256:  {report.journal_sha256}")
        return "\n".join(lines)

    @staticmethod
    def _render(report) -> str:  # noqa: ANN001 - SimReport
        s = report.stats
        lines = [
            f"sim: {report.scenario} seed={report.seed} —"
            f" {report.virtual_s / 3600.0:.2f} virtual hours in"
            f" {report.wall_s:.2f}s wall ({report.speedup:,.0f}x)",
            f"  gangs: {s.get('submitted', 0)} submitted,"
            f" {s.get('completed', 0)} completed,"
            f" {s.get('resubmitted', 0)} resubmitted,"
            f" {s.get('infeasible', 0)} infeasible,"
            f" {s.get('queued_end', 0)} queued at end",
            f"  market: {s.get('kills', 0)} kills,"
            f" {s.get('reshapes', 0)} reshapes, {s.get('grows', 0)} grows;"
            f" utilization {s.get('utilization', 0.0):.1%}",
            f"  faults: {s.get('faults', 0)} injected,"
            f" slo alerts: {s.get('slo_alerts', 0)},"
            f" autoscales: {s.get('autoscales', 0)}",
        ]
        pipelines = s.get("pipelines") or {}
        if pipelines:
            lines.append(
                "  pipelines: "
                + ", ".join(f"{p}={st}" for p, st in sorted(pipelines.items()))
            )
        lines.append(f"journal: {report.journal_path}")
        lines.append(f"sha256:  {report.journal_sha256}")
        return "\n".join(lines)
