"""Thin Runner-wrapper subcommands: status / describe / list / cancel /
delete / runopts / builtins / configure.

Reference analog: torchx/cli/cmd_*.py (~400 LoC combined).
"""

from __future__ import annotations

import argparse
import json
import sys

from torchx_tpu.cli.cmd_base import SubCommand, control_client
from torchx_tpu.runner import config as tpx_config
from torchx_tpu.runner.api import get_runner


class CmdStatus(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("app_handle", help="scheduler://session/app_id")

    def run(self, args: argparse.Namespace) -> None:
        client = control_client()
        if client is not None:
            self._run_proxied(client, args)
            return
        from torchx_tpu.util.colors import supports_color

        with get_runner() as runner:
            status = runner.status(args.app_handle)
            if status is None:
                print(f"app not found: {args.app_handle}", file=sys.stderr)
                sys.exit(1)
            print(status.format(colored=supports_color()))

    def _run_proxied(self, client, args: argparse.Namespace) -> None:  # noqa: ANN001
        from torchx_tpu.control.client import ControlClientError

        try:
            st = client.status(args.app_handle)
        except ControlClientError as e:
            if e.code == 404:
                print(f"app not found: {args.app_handle}", file=sys.stderr)
            else:
                print(f"control: {e.message}", file=sys.stderr)
            sys.exit(1)
        line = f"{st.get('handle', args.app_handle)}: {st.get('state')}"
        if st.get("failure_class"):
            line += f" ({st['failure_class']})"
        print(line)
        if st.get("msg"):
            print(st["msg"])


class CmdDescribe(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("app_handle", help="scheduler://session/app_id")

    def run(self, args: argparse.Namespace) -> None:
        with get_runner() as runner:
            app = runner.describe(args.app_handle)
            if app is None:
                print(f"app not found: {args.app_handle}", file=sys.stderr)
                sys.exit(1)
            print(json.dumps({"name": app.name, "roles": [r.name for r in app.roles]}, indent=2))


class CmdList(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "-s",
            "--scheduler",
            default=None,
            help="scheduler backend to list (default: every backend,"
            " queried concurrently)",
        )

    def run(self, args: argparse.Namespace) -> None:
        client = control_client()
        if client is not None:
            from torchx_tpu.control.client import ControlClientError

            try:
                if args.scheduler:
                    for app in client.list(args.scheduler):
                        print(f"{app.get('app_id')}\t{app.get('state')}")
                else:
                    # fleet view straight from the daemon's journal — no
                    # backend round-trips at all
                    for app in client.list():
                        print(
                            f"{app.get('scheduler')}\t{app.get('app_id')}"
                            f"\t{app.get('state')}"
                        )
            except ControlClientError as e:
                print(f"control: {e.message}", file=sys.stderr)
                sys.exit(1)
            return
        with get_runner() as runner:
            if args.scheduler:
                for app in runner.list(args.scheduler):
                    print(f"{app.app_id}\t{app.state}\t{app.name}")
                return
            # no -s: fan out across every backend; results print in
            # registry order, one line per app prefixed by the backend,
            # and an unreachable backend degrades to a stderr note
            results, errors = runner.list_all()
            for name, apps in results.items():
                for app in apps:
                    print(f"{name}\t{app.app_id}\t{app.state}\t{app.name}")
            for name, err in errors.items():
                print(f"{name}: <unavailable: {err}>", file=sys.stderr)


class CmdCancel(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("app_handle")

    def run(self, args: argparse.Namespace) -> None:
        client = control_client()
        if client is not None:
            from torchx_tpu.control.client import ControlClientError

            try:
                client.cancel(args.app_handle)
            except ControlClientError as e:
                print(f"control: {e.message}", file=sys.stderr)
                sys.exit(1)
            print(f"cancelled {args.app_handle}")
            return
        with get_runner() as runner:
            runner.cancel(args.app_handle)
            print(f"cancelled {args.app_handle}")


class CmdDelete(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("app_handle")

    def run(self, args: argparse.Namespace) -> None:
        with get_runner() as runner:
            runner.delete(args.app_handle)
            print(f"deleted {args.app_handle}")


class CmdResize(SubCommand):
    """Resize a running role's gang: `tpx resize <handle> <role> <n>`
    (n in AppDef units — slices for TPU roles). The gang restarts with a
    coherent world size and resumes from its checkpoint."""

    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("app_handle")
        subparser.add_argument("role_name")
        subparser.add_argument("num_replicas", type=int)

    def run(self, args: argparse.Namespace) -> None:
        with get_runner() as runner:
            try:
                runner.resize(args.app_handle, args.role_name, args.num_replicas)
            except (ValueError, NotImplementedError) as e:
                # terminal app, unknown role, or a backend without resize:
                # an operator mistake, not a stack trace
                print(f"error: {e}", file=sys.stderr)
                sys.exit(1)
            print(
                f"resized {args.app_handle}/{args.role_name}"
                f" to {args.num_replicas}"
            )


class CmdWatch(SubCommand):
    """Failure-driven elastic controller: `tpx watch <handle>` observes a
    running app and auto-shrinks roles with a min_replicas floor when
    slices fail (the operator-side analog of the local scheduler's elastic
    restart). Blocks until the app terminates or the budget is spent."""

    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("app_handle")
        subparser.add_argument(
            "--interval", type=float, default=10.0, help="poll seconds"
        )
        subparser.add_argument(
            "--timeout", type=float, default=None, help="give up after seconds"
        )
        subparser.add_argument(
            "--max-restarts", type=int, default=3, help="shrink budget"
        )

    def run(self, args: argparse.Namespace) -> None:
        with get_runner() as runner:
            n = runner.watch_elastic(
                args.app_handle,
                poll_interval=args.interval,
                timeout=args.timeout,
                max_restarts=args.max_restarts,
            )
            print(f"watch done: {n} elastic shrink-restart(s)")


class CmdRunopts(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "scheduler", nargs="?", default=None, help="show only this scheduler"
        )

    def run(self, args: argparse.Namespace) -> None:
        with get_runner() as runner:
            names = [args.scheduler] if args.scheduler else runner.scheduler_backends()
            for name in names:
                print(f"{name}:")
                try:
                    print(runner.scheduler_run_opts(name))
                except Exception as e:  # noqa: BLE001 - optional backend deps
                    print(f"    <unavailable: {e}>")
                print()


class CmdBuiltins(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "--print", dest="print_component", default=None, help="print component source"
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.specs.finder import get_builtin_source, get_components

        if args.print_component:
            print(get_builtin_source(args.print_component))
            return
        components = get_components()
        print(f"Found {len(components)} builtin components:")
        for name, c in sorted(components.items()):
            print(f"  {name} - {c.description}")


class CmdConfigure(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "-s",
            "--schedulers",
            default=None,
            help="comma list of schedulers to emit sections for (default: all)",
        )
        subparser.add_argument(
            "--required_only", action="store_true", help="only required options"
        )

    def run(self, args: argparse.Namespace) -> None:
        with get_runner() as runner:
            names = (
                args.schedulers.split(",")
                if args.schedulers
                else runner.scheduler_backends()
            )
            opts = {}
            for name in names:
                try:
                    opts[name] = runner.scheduler_run_opts(name)
                except Exception:  # noqa: BLE001
                    continue
            with open(tpx_config.CONFIG_FILE, "w") as f:
                tpx_config.dump(f, opts, required_only=args.required_only)
            print(f"wrote {tpx_config.CONFIG_FILE}")
