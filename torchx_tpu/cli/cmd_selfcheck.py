"""``tpx selfcheck`` — the whole-program invariant analyzer, as a CLI.

Runs the :mod:`torchx_tpu.analyze.selfcheck` passes over the package's
own source tree and reports TPX9xx diagnostics on the standard lint
report model. The checked-in triaged baseline
(``selfcheck_baseline.json`` at the repo root) suppresses findings a
human has reviewed; anything unsuppressed fails the run.

* ``--json`` — the stable machine-readable report (plus the suppressed
  count), for CI consumers;
* ``--changed-only`` — keep only findings anchored in files changed in
  the working tree (vs ``HEAD``, plus untracked) — the import graph is
  still whole-program, so transitive proofs don't weaken;
* ``--update-baseline`` — retriage: rewrite the baseline from the
  current raw findings (review the diff like any other change);
* ``--passes`` — comma-separated subset (default: all).

Exit codes: 0 clean, 1 any unsuppressed finding (selfcheck findings are
invariant violations — warnings gate too), 2 usage errors.

This module must stay import-light: ``tpx selfcheck --help`` never
imports jax (tier-1 asserts it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from torchx_tpu.cli.cmd_base import SubCommand


class CmdSelfcheck(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "--json",
            action="store_true",
            help="emit the stable JSON report instead of text",
        )
        subparser.add_argument(
            "--changed-only",
            action="store_true",
            help="only report findings in files changed vs HEAD"
            " (graph/proofs stay whole-program)",
        )
        subparser.add_argument(
            "--update-baseline",
            action="store_true",
            help="rewrite the triaged baseline from the current findings",
        )
        subparser.add_argument(
            "--baseline",
            type=str,
            default=None,
            help="baseline file (default: selfcheck_baseline.json next to"
            " the package)",
        )
        subparser.add_argument(
            "--passes",
            type=str,
            default=None,
            help="comma-separated pass subset (default: all); see"
            " `tpx selfcheck --list-passes`",
        )
        subparser.add_argument(
            "--list-passes",
            action="store_true",
            help="print the registered pass names and exit",
        )
        subparser.add_argument(
            "--root",
            type=str,
            default=None,
            help="repo root to scan (default: the checkout this package"
            " is imported from)",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.analyze.selfcheck import (
            BASELINE_FILENAME,
            Baseline,
            PASSES,
            SelfCheckConfig,
            run_selfcheck,
        )

        if args.list_passes:
            for name in PASSES:
                print(name)
            sys.exit(0)

        passes = None
        if args.passes:
            passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
            unknown = set(passes) - set(PASSES)
            if unknown:
                print(
                    f"error: unknown pass(es) {sorted(unknown)};"
                    f" available: {list(PASSES)}",
                    file=sys.stderr,
                )
                sys.exit(2)

        config = SelfCheckConfig.for_repo(args.root)
        if not os.path.isdir(config.pkg_root):
            print(
                f"error: no package tree at {config.pkg_root!r}",
                file=sys.stderr,
            )
            sys.exit(2)

        only_files = None
        if args.changed_only:
            only_files = self._changed_files(config.repo_root)

        raw = run_selfcheck(config, passes=passes, only_files=only_files)

        baseline_path = args.baseline or os.path.join(
            config.repo_root, BASELINE_FILENAME
        )
        if args.update_baseline:
            Baseline.from_report(raw).save(baseline_path)
            print(
                f"selfcheck: baseline rewritten with"
                f" {len(raw.diagnostics)} finding(s) -> {baseline_path}"
            )
            sys.exit(0)

        try:
            baseline = Baseline.load(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"error: bad baseline {baseline_path!r}: {e}", file=sys.stderr)
            sys.exit(2)
        kept, suppressed = baseline.apply(raw)

        if args.json:
            doc = kept.to_dict()
            doc["suppressed"] = suppressed
            print(json.dumps(doc, indent=2))
        else:
            print(kept.render())
            if suppressed:
                print(f"({suppressed} baselined finding(s) suppressed)")
        sys.exit(1 if kept.diagnostics else 0)

    @staticmethod
    def _changed_files(repo_root: str) -> set[str]:
        """Repo-relative paths changed vs HEAD, plus untracked files."""
        import subprocess

        files: set[str] = set()
        for cmd in (
            ["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            try:
                out = subprocess.run(
                    cmd,
                    cwd=repo_root,
                    capture_output=True,
                    text=True,
                    check=True,
                    timeout=30,
                ).stdout
            except (OSError, subprocess.SubprocessError) as e:
                print(
                    f"error: --changed-only needs git in {repo_root}: {e}",
                    file=sys.stderr,
                )
                sys.exit(2)
            files.update(line.strip() for line in out.splitlines() if line.strip())
        return files
