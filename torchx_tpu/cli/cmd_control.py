"""``tpx control`` — run the control-plane daemon (foreground).

Starts the multi-tenant daemon (:mod:`torchx_tpu.control.daemon`): one
process owning the Runner, every watch stream, and the sharded job-state
store, serving submit/status/list/cancel/wait/log over localhost JSON.
Point other shells at it with::

    export TPX_CONTROL_ADDR=<printed addr>

(the bearer token is read from the daemon's 0600 discovery file, or set
``TPX_CONTROL_TOKEN`` explicitly) and every ``tpx`` verb proxies through
the daemon instead of driving schedulers directly.
"""

from __future__ import annotations

import argparse
import sys

from torchx_tpu.cli.cmd_base import SubCommand


class CmdControl(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "--host", default="127.0.0.1", help="bind address (loopback only)"
        )
        subparser.add_argument(
            "--port", type=int, default=0, help="bind port (0 = OS-assigned)"
        )
        subparser.add_argument(
            "--state-dir",
            default=None,
            help="discovery file + job-state store root"
            " (default $TPX_CONTROL_DIR, else ~/.torchx_tpu/control)",
        )
        subparser.add_argument(
            "--tenant-cap",
            type=int,
            default=None,
            help="max concurrently active jobs per tenant (429 past it)",
        )
        subparser.add_argument(
            "--print-token",
            action="store_true",
            help="also print the root token (it is always in the 0600"
            " discovery file; printing it puts it in scrollback)",
        )
        subparser.add_argument(
            "--fleet",
            default=None,
            metavar="SPEC",
            help="enable the fleet scheduler on this modeled fleet, e.g."
            " 'default:v5e-4x8,big:v5p-8x2' (name:gen-CHIPSxCOUNT,...);"
            " submits then queue/place/preempt instead of 429ing",
        )
        subparser.add_argument(
            "--fleet-quota",
            action="append",
            default=None,
            metavar="TENANT=CHIPS",
            help="per-tenant chip quota for the fleet scheduler"
            " (repeatable; tenants without one are unlimited)",
        )
        subparser.add_argument(
            "--slo",
            action="append",
            default=None,
            metavar="SPEC",
            help="SLO spec the telemetry plane evaluates as burn rates"
            " (repeatable): name:metric<thresh@obj,"
            " name:metric{k=v}/metric@obj, or a preset"
            " (p99-ttft, goodput, step-time, gang-wait)",
        )
        subparser.add_argument(
            "--scrape-interval",
            type=float,
            default=None,
            metavar="SECONDS",
            help="telemetry collector cycle"
            " (default $TPX_TELEMETRY_INTERVAL or 5s)",
        )
        subparser.add_argument(
            "--cell",
            default=None,
            metavar="NAME",
            help="federation cell name this daemon answers as"
            " (default $TPX_CELL or 'default'); register it with"
            " `tpx cell add` to route through the federation layer",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.control.daemon import ControlDaemon, control_dir

        fleet = None
        if args.fleet:
            from torchx_tpu.fleet.api import FleetScheduler, parse_quotas
            from torchx_tpu.fleet.model import FleetModel

            fleet = FleetScheduler(
                FleetModel.from_spec(args.fleet),
                state_dir=args.state_dir or control_dir(),
                quotas=parse_quotas(args.fleet_quota),
            )
        daemon = ControlDaemon(
            host=args.host,
            port=args.port,
            state_dir=args.state_dir,
            tenant_cap=args.tenant_cap,
            fleet=fleet,
            slos=args.slo,
            scrape_interval=args.scrape_interval,
            cell=args.cell,
        )
        recovered = len(daemon.store)
        print(
            f"tpx control: serving on {daemon.addr}"
            f" (cell {daemon.cell}, state {daemon.state_dir},"
            f" {recovered} jobs rehydrated)",
            flush=True,
        )
        if fleet is not None:
            snap = fleet.queue_snapshot()
            print(
                f"  fleet: {snap['fleet']['chips_total']} chips in"
                f" {len(snap['fleet']['pools'])} pool(s),"
                f" {len(snap['queue'])} queued /"
                f" {len(snap['running'])} running rehydrated",
                flush=True,
            )
        if daemon.slo_engine is not None and daemon.slo_engine.specs:
            print(
                "  slo: "
                + ", ".join(s.name for s in daemon.slo_engine.specs),
                flush=True,
            )
        print(f"  export TPX_CONTROL_ADDR={daemon.addr}", flush=True)
        if args.print_token:
            print(f"  export TPX_CONTROL_TOKEN={daemon.root_token}", flush=True)
        try:
            daemon.serve_forever()
        except KeyboardInterrupt:
            print("tpx control: shutting down", file=sys.stderr)
            daemon.close()
