"""``tpx lint`` — run the preflight analyzer without submitting anything.

Targets:

* a builtin component name (``dist.spmd``) or custom ``file.py:fn`` —
  lints the component source (TPX00x) and, when the component can be
  materialized with the given args, the resulting AppDef;
* an AppDef JSON file (``job.json``, the ``torchx_tpu.specs.serialize``
  shape) or ``-`` for the same JSON on stdin.

``--scheduler`` specializes the analysis for one backend (capability
rules), ``--policy`` feeds a supervisor policy JSON for retry-coherence
rules, and ``--json`` emits the stable machine-readable report.

Exit codes: 0 clean (warnings allowed), 1 error-severity diagnostics,
2 usage errors (unknown scheduler, unreadable target).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from torchx_tpu.analyze import (
    Diagnostic,
    LintReport,
    Severity,
    analyze,
    analyze_component,
)
from torchx_tpu.cli.cmd_base import SubCommand

logger = logging.getLogger(__name__)


class CmdLint(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "-s",
            "--scheduler",
            type=str,
            default=None,
            help="specialize the analysis for one scheduler backend",
        )
        subparser.add_argument(
            "--json",
            action="store_true",
            help="emit the report as stable JSON instead of text",
        )
        subparser.add_argument(
            "--policy",
            type=str,
            default=None,
            help="supervisor policy JSON file for retry-coherence rules",
        )
        subparser.add_argument(
            "conf_args",
            nargs=argparse.REMAINDER,
            help="component name / file.py:fn / appdef.json / '-' (stdin),"
            " optionally followed by component arguments",
        )

    def run(self, args: argparse.Namespace) -> None:
        conf_args = args.conf_args
        if conf_args and conf_args[0] == "--":
            conf_args = conf_args[1:]
        if not conf_args:
            print(
                "error: lint needs a target: a component name, file.py:fn,"
                " an AppDef JSON file, or '-' for stdin",
                file=sys.stderr,
            )
            sys.exit(2)
        target, rest = conf_args[0], conf_args[1:]

        scheduler = args.scheduler
        if scheduler is not None:
            from torchx_tpu.schedulers import get_scheduler_factories

            available = sorted(get_scheduler_factories())
            if scheduler not in available:
                print(
                    f"error: unknown scheduler {scheduler!r};"
                    f" available: {available}",
                    file=sys.stderr,
                )
                sys.exit(2)

        policy = None
        if args.policy:
            policy = self._load_policy(args.policy)

        report = self._lint_target(target, rest, scheduler, policy)
        if args.json:
            print(json.dumps(report.to_dict(), indent=2))
        else:
            print(report.render())
        sys.exit(1 if report.has_errors else 0)

    def _load_policy(self, path: str):  # noqa: ANN001 - SupervisorPolicy
        from torchx_tpu.specs.serialize import supervisor_policy_from_dict

        try:
            with open(path) as f:
                return supervisor_policy_from_dict(json.load(f))
        except (OSError, json.JSONDecodeError, ValueError, TypeError, KeyError) as e:
            print(f"error: cannot load policy {path!r}: {e}", file=sys.stderr)
            sys.exit(2)

    def _lint_target(self, target: str, rest, scheduler, policy) -> LintReport:  # noqa: ANN001
        from torchx_tpu.specs.serialize import appdef_from_dict

        if target == "-" or target.endswith(".json"):
            try:
                if target == "-":
                    raw = json.load(sys.stdin)
                else:
                    with open(target) as f:
                        raw = json.load(f)
                app = appdef_from_dict(raw)
            except (
                OSError,
                json.JSONDecodeError,
                ValueError,
                KeyError,
                TypeError,
                AttributeError,
            ) as e:
                print(f"error: invalid job spec {target!r}: {e}", file=sys.stderr)
                sys.exit(2)
            report = analyze(app, scheduler=scheduler, policy=policy, gate="cli")
            report.target = target if target != "-" else app.name
            return report

        # component target: source lint first, then AppDef lint if it
        # materializes with the given args
        report = analyze_component(target, gate="cli")
        if report.has_errors:
            return report
        from torchx_tpu.specs.builders import materialize_appdef
        from torchx_tpu.specs.finder import get_component

        try:
            component_def = get_component(target)
            app = materialize_appdef(component_def.fn, rest)
        except Exception as e:  # noqa: BLE001 - missing required args etc.
            report.extend(
                [
                    Diagnostic(
                        code="TPX007",
                        severity=Severity.INFO,
                        message=(
                            f"component not materialized ({e}); AppDef-level"
                            " rules skipped"
                        ),
                        hint=(
                            "pass the component's arguments after the name"
                            " to lint the resulting AppDef"
                        ),
                    )
                ]
            )
            return report
        app_report = analyze(app, scheduler=scheduler, policy=policy, gate="cli")
        report.scheduler = scheduler
        report.extend(app_report)
        return report
