"""``tpx`` CLI entry point (reference analog: torchx/cli/main.py).

Subcommands can be overridden/extended via the ``tpx.cli.cmds`` entry-point
group (reference cli/main.py:51-71).
"""

from __future__ import annotations

import argparse
import logging
import sys
from typing import Optional

from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.cli.cmd_lint import CmdLint
from torchx_tpu.cli.cmd_log import CmdLog
from torchx_tpu.cli.cmd_run import CmdRun
from torchx_tpu.cli.cmd_simple import (
    CmdBuiltins,
    CmdCancel,
    CmdConfigure,
    CmdDelete,
    CmdDescribe,
    CmdList,
    CmdResize,
    CmdRunopts,
    CmdStatus,
    CmdWatch,
)
from torchx_tpu.cli.cmd_supervise import CmdSupervise
from torchx_tpu.cli.cmd_trace import CmdTrace
from torchx_tpu.version import __version__

CMDS_ENTRYPOINT_GROUP = "tpx.cli.cmds"


def get_sub_cmds() -> dict[str, SubCommand]:
    cmds: dict[str, SubCommand] = {
        "run": CmdRun(),
        "lint": CmdLint(),
        "supervise": CmdSupervise(),
        "status": CmdStatus(),
        "describe": CmdDescribe(),
        "list": CmdList(),
        "log": CmdLog(),
        "trace": CmdTrace(),
        "cancel": CmdCancel(),
        "delete": CmdDelete(),
        "resize": CmdResize(),
        "watch": CmdWatch(),
        "runopts": CmdRunopts(),
        "builtins": CmdBuiltins(),
        "configure": CmdConfigure(),
    }
    from torchx_tpu.util.entrypoints import load_group

    for name, loader in load_group(CMDS_ENTRYPOINT_GROUP).items():
        try:
            cmds[name] = loader()()
        except Exception:  # noqa: BLE001 - a broken plugin must not kill the CLI
            pass
    try:
        from torchx_tpu.cli.cmd_tracker import CmdTracker

        cmds["tracker"] = CmdTracker()
    except ImportError:
        pass
    return cmds


def create_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpx", description="tpx — TPU-native universal job launcher"
    )
    parser.add_argument("--version", action="version", version=f"tpx {__version__}")
    parser.add_argument("--log_level", default="INFO", help="client log level")
    subparsers = parser.add_subparsers(title="sub-commands", dest="cmd")
    for name, cmd in get_sub_cmds().items():
        sub = subparsers.add_parser(name)
        cmd.add_arguments(sub)
        sub.set_defaults(func=cmd.run)
    return parser


def main(argv: Optional[list[str]] = None) -> None:
    parser = create_parser()
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, str(args.log_level).upper(), logging.INFO),
        format="%(levelname)s %(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    if not hasattr(args, "func"):
        parser.print_help()
        sys.exit(1)
    from torchx_tpu.runner.api import UnknownSchedulerError

    try:
        args.func(args)
    except UnknownSchedulerError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(1)
    except BrokenPipeError:
        # `tpx ... | head` closed the pipe; not an error
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        sys.exit(0)


if __name__ == "__main__":
    main()
