"""``tpx`` CLI entry point (reference analog: torchx/cli/main.py).

Subcommands can be overridden/extended via the ``tpx.cli.cmds`` entry-point
group (reference cli/main.py:51-71).

Dispatch is LAZY: ``main`` peeks at argv for the command name and imports
only that subcommand's module, so ``tpx list`` never pays for the run
path's deps (jax, docker SDKs, analyzers) and ``tpx --help`` renders from
name-only stubs without importing any subcommand at all. ``get_sub_cmds``
/ ``create_parser()`` (no ``only``) remain the eager full-registry views.
"""

from __future__ import annotations

import argparse
import importlib
import logging
import sys
from typing import Optional

from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.version import __version__

CMDS_ENTRYPOINT_GROUP = "tpx.cli.cmds"

# name -> (module, class): the static dispatch table. Kept as strings so
# `tpx <cmd>` imports exactly one of these modules; order is the help /
# registry order. "tracker" is optional (extra deps) — see _load_cmd.
BUILTIN_CMDS: dict[str, tuple[str, str]] = {
    "run": ("torchx_tpu.cli.cmd_run", "CmdRun"),
    "lint": ("torchx_tpu.cli.cmd_lint", "CmdLint"),
    "explain": ("torchx_tpu.cli.cmd_explain", "CmdExplain"),
    "tune": ("torchx_tpu.cli.cmd_tune", "CmdTune"),
    "supervise": ("torchx_tpu.cli.cmd_supervise", "CmdSupervise"),
    "status": ("torchx_tpu.cli.cmd_simple", "CmdStatus"),
    "describe": ("torchx_tpu.cli.cmd_simple", "CmdDescribe"),
    "list": ("torchx_tpu.cli.cmd_simple", "CmdList"),
    "log": ("torchx_tpu.cli.cmd_log", "CmdLog"),
    "trace": ("torchx_tpu.cli.cmd_trace", "CmdTrace"),
    "profile": ("torchx_tpu.cli.cmd_profile", "CmdProfile"),
    "cancel": ("torchx_tpu.cli.cmd_simple", "CmdCancel"),
    "delete": ("torchx_tpu.cli.cmd_simple", "CmdDelete"),
    "resize": ("torchx_tpu.cli.cmd_simple", "CmdResize"),
    "watch": ("torchx_tpu.cli.cmd_simple", "CmdWatch"),
    "runopts": ("torchx_tpu.cli.cmd_simple", "CmdRunopts"),
    "builtins": ("torchx_tpu.cli.cmd_simple", "CmdBuiltins"),
    "configure": ("torchx_tpu.cli.cmd_simple", "CmdConfigure"),
    "tracker": ("torchx_tpu.cli.cmd_tracker", "CmdTracker"),
    "serve-pool": ("torchx_tpu.cli.cmd_serve_pool", "CmdServePool"),
    "control": ("torchx_tpu.cli.cmd_control", "CmdControl"),
    "cell": ("torchx_tpu.cli.cmd_cell", "CmdCell"),
    "queue": ("torchx_tpu.cli.cmd_queue", "CmdQueue"),
    "top": ("torchx_tpu.cli.cmd_top", "CmdTop"),
    "pipeline": ("torchx_tpu.cli.cmd_pipeline", "CmdPipeline"),
    "sim": ("torchx_tpu.cli.cmd_sim", "CmdSim"),
    "selfcheck": ("torchx_tpu.cli.cmd_selfcheck", "CmdSelfcheck"),
}


def _load_builtin(name: str) -> SubCommand:
    module, cls = BUILTIN_CMDS[name]
    return getattr(importlib.import_module(module), cls)()


def _load_cmd(name: str) -> Optional[SubCommand]:
    """Load ONE command by name, or None when unknown/unloadable.

    Precedence matches the eager registry: the builtin tracker shadows a
    plugin of the same name; every other plugin shadows its builtin; a
    broken plugin falls back to the builtin it shadowed (or None)."""
    if name == "tracker":
        try:
            return _load_builtin("tracker")
        except ImportError:
            pass  # optional deps missing: fall through to a plugin, if any
    from torchx_tpu.util.entrypoints import load_group

    loader = load_group(CMDS_ENTRYPOINT_GROUP).get(name)
    if loader is not None:
        try:
            return loader()()
        except Exception:  # noqa: BLE001 - a broken plugin must not kill the CLI
            pass
    if name in BUILTIN_CMDS and name != "tracker":
        return _load_builtin(name)
    return None


def _known_cmds() -> list[str]:
    """Every dispatchable command name, WITHOUT importing any command
    module ("tracker" is listed optimistically; its import is validated
    on load). Metadata-only entry-point scan for plugins."""
    names = list(BUILTIN_CMDS)
    from torchx_tpu.util.entrypoints import load_group

    names += [n for n in load_group(CMDS_ENTRYPOINT_GROUP) if n not in names]
    return names


def _peek_cmd(argv: list[str]) -> Optional[str]:
    """First positional token of argv = the subcommand name (skipping the
    global options and, for ``--log_level``, its value)."""
    it = iter(argv)
    for tok in it:
        if tok in ("--log_level", "--log-level"):
            next(it, None)  # skip the level value
            continue
        if tok.startswith("-"):
            continue  # --version / --help / --log_level=X
        return tok
    return None


def get_sub_cmds() -> dict[str, SubCommand]:
    """The full eager registry (imports every command module): builtins,
    then entry-point plugins (which may override builtins), then the
    optional tracker command."""
    cmds: dict[str, SubCommand] = {
        name: _load_builtin(name) for name in BUILTIN_CMDS if name != "tracker"
    }
    from torchx_tpu.util.entrypoints import load_group

    for name, loader in load_group(CMDS_ENTRYPOINT_GROUP).items():
        try:
            cmds[name] = loader()()
        except Exception:  # noqa: BLE001 - a broken plugin must not kill the CLI
            pass
    try:
        cmds["tracker"] = _load_builtin("tracker")
    except ImportError:
        pass
    return cmds


def _base_parser() -> tuple[argparse.ArgumentParser, argparse._SubParsersAction]:
    parser = argparse.ArgumentParser(
        prog="tpx", description="tpx — TPU-native universal job launcher"
    )
    parser.add_argument("--version", action="version", version=f"tpx {__version__}")
    parser.add_argument("--log_level", default="INFO", help="client log level")
    subparsers = parser.add_subparsers(title="sub-commands", dest="cmd")
    return parser, subparsers


def create_parser(only: Optional[str] = None) -> argparse.ArgumentParser:
    """The ``tpx`` argument parser.

    With ``only=<cmd>`` (the lazy dispatch path) just that command's
    module is imported and registered; unknown/unloadable names register
    nothing, so parsing then yields argparse's invalid-choice error.
    Without ``only``, the full eager registry is registered."""
    parser, subparsers = _base_parser()
    if only is None:
        for name, cmd in get_sub_cmds().items():
            sub = subparsers.add_parser(name)
            cmd.add_arguments(sub)
            sub.set_defaults(func=cmd.run)
        return parser
    cmd = _load_cmd(only)
    if cmd is not None:
        sub = subparsers.add_parser(only)
        cmd.add_arguments(sub)
        sub.set_defaults(func=cmd.run)
    return parser


def _stub_parser() -> argparse.ArgumentParser:
    """A parser whose subcommands are name-only stubs: renders the full
    help listing and argparse's invalid-choice diagnostics without
    importing a single command module."""
    parser, subparsers = _base_parser()
    for name in _known_cmds():
        subparsers.add_parser(name)
    return parser


def main(argv: Optional[list[str]] = None) -> None:
    args_list = sys.argv[1:] if argv is None else list(argv)
    cmd_name = _peek_cmd(args_list)
    if cmd_name is not None and cmd_name in _known_cmds():
        parser = create_parser(only=cmd_name)
    else:
        # no command / --help / --version / unknown command
        parser = _stub_parser()
    args = parser.parse_args(args_list)
    logging.basicConfig(
        level=getattr(logging, str(args.log_level).upper(), logging.INFO),
        format="%(levelname)s %(asctime)s %(name)s: %(message)s",
        stream=sys.stderr,
    )
    if not hasattr(args, "func"):
        parser.print_help()
        sys.exit(1)
    from torchx_tpu.runner.api import UnknownSchedulerError

    try:
        args.func(args)
    except UnknownSchedulerError as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(1)
    except BrokenPipeError:
        # `tpx ... | head` closed the pipe; not an error
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        sys.exit(0)


if __name__ == "__main__":
    main()
