"""``tpx explain`` — deep preflight: static sharding / HBM / collective
analysis of a job's parallelism plan, without submitting anything.

Targets (same grammar as ``tpx lint``):

* a builtin component name (``dist.spmd``) or custom ``file.py:fn``,
  followed by the component's arguments — the component is materialized
  and every plan-shaped role analyzed;
* an AppDef JSON file (``job.json``) or ``-`` for the same on stdin.

The analysis itself is jax-free (enforced by ``scripts/lint_internal.py``
and the tier1 EXPLAIN_SMOKE step): sharding propagation, the static HBM
fit and ICI-vs-DCN collective classification all run on launcher-side
arithmetic. ``--aot`` additionally AOT-compiles the train step through
``parallel/aot_fit.compile_fit`` (imports jax) and prints the XLA memory
analysis next to the static prediction.

Exit codes: 0 clean (warnings allowed), 1 error-severity diagnostics
(TPX700/701/703), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from torchx_tpu.cli.cmd_base import SubCommand

logger = logging.getLogger(__name__)


class CmdExplain(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "-s",
            "--scheduler",
            type=str,
            default=None,
            help="scheduler name stamped on the report/span (informational)",
        )
        subparser.add_argument(
            "--json",
            action="store_true",
            help="emit the report as stable JSON (schema version 1)",
        )
        subparser.add_argument(
            "--aot",
            action="store_true",
            help="cross-check the static HBM fit against the XLA AOT"
            " memory analysis (imports jax)",
        )
        subparser.add_argument(
            "--devices",
            type=int,
            default=None,
            help="override the device count the plan resolves onto",
        )
        subparser.add_argument(
            "--hbm-gb",
            type=float,
            default=None,
            help="override the per-chip HBM budget in GiB",
        )
        subparser.add_argument(
            "--headroom",
            type=float,
            default=None,
            help="fraction of HBM the fit may use (default 0.9)",
        )
        subparser.add_argument(
            "--artifact",
            type=str,
            default=None,
            help="diff each plan-shaped role against a pinned `tpx tune`"
            " plan artifact (TPX706 on divergence, TPX707 if untrusted)",
        )
        subparser.add_argument(
            "--calibrated",
            type=str,
            default=None,
            metavar="GENERATION",
            help="apply the persisted cost-model calibration for an"
            " accelerator generation (e.g. v5p; see `tpx tune`)",
        )
        subparser.add_argument(
            "conf_args",
            nargs=argparse.REMAINDER,
            help="component name / file.py:fn / appdef.json / '-' (stdin),"
            " optionally followed by component arguments",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.analyze.costmodel import DEFAULT_HEADROOM

        conf_args = args.conf_args
        if conf_args and conf_args[0] == "--":
            conf_args = conf_args[1:]
        if not conf_args:
            print(
                "error: explain needs a target: a component name, file.py:fn,"
                " an AppDef JSON file, or '-' for stdin",
                file=sys.stderr,
            )
            sys.exit(2)
        target, rest = conf_args[0], conf_args[1:]

        scheduler = args.scheduler
        if scheduler is not None:
            from torchx_tpu.schedulers import get_scheduler_factories

            available = sorted(get_scheduler_factories())
            if scheduler not in available:
                print(
                    f"error: unknown scheduler {scheduler!r};"
                    f" available: {available}",
                    file=sys.stderr,
                )
                sys.exit(2)

        app = self._load_app(target, rest)
        from torchx_tpu.analyze.explain import explain

        calibration = None
        if args.calibrated:
            from torchx_tpu.tune.calibrate import CalibrationTable

            calibration = CalibrationTable.load_default().scales_for(
                args.calibrated
            )
        report = explain(
            app,
            scheduler=scheduler,
            devices=args.devices,
            hbm_bytes=(
                int(args.hbm_gb * 1024**3) if args.hbm_gb is not None else None
            ),
            headroom=(
                args.headroom if args.headroom is not None else DEFAULT_HEADROOM
            ),
            aot=args.aot,
            artifact=args.artifact,
            calibration=calibration,
            gate="cli",
        )
        if target not in ("-",):
            report.target = target
        if args.json:
            print(json.dumps(report.to_dict(), indent=2))
        else:
            print(report.render())
        sys.exit(1 if report.has_errors else 0)

    def _load_app(self, target: str, rest):  # noqa: ANN001 - AppDef
        from torchx_tpu.specs.serialize import appdef_from_dict

        if target == "-" or target.endswith(".json"):
            try:
                if target == "-":
                    raw = json.load(sys.stdin)
                else:
                    with open(target) as f:
                        raw = json.load(f)
                return appdef_from_dict(raw)
            except (
                OSError,
                json.JSONDecodeError,
                ValueError,
                KeyError,
                TypeError,
                AttributeError,
            ) as e:
                print(f"error: invalid job spec {target!r}: {e}", file=sys.stderr)
                sys.exit(2)
        from torchx_tpu.specs.builders import materialize_appdef
        from torchx_tpu.specs.finder import get_component

        try:
            component_def = get_component(target)
            return materialize_appdef(component_def.fn, rest)
        except Exception as e:  # noqa: BLE001 - unknown component, bad args
            print(f"error: cannot materialize {target!r}: {e}", file=sys.stderr)
            sys.exit(2)
