"""``tpx supervise`` — run a component under the preemption-aware supervisor.

``tpx run`` submits and walks away; ``tpx supervise`` submits and stays:
it watches the app to a terminal state, classifies the failure
(preemption / infra / app), and auto-resubmits within per-class retry
budgets with capped exponential backoff, injecting the latest checkpoint
step (``--checkpoint-dir``) so each attempt resumes instead of restarting
from scratch. This is the intended way to train on spot TPU capacity::

    tpx supervise -s tpu_vm -cfg project=p,zone=z,spot=True \\
        --checkpoint-dir gs://bkt/run1/ckpt --max-preemptions 16 \\
        dist.spmd -j 2x4 --script train.py

Policy comes from ``--policy policy.json``
(:func:`~torchx_tpu.specs.serialize.supervisor_policy_from_dict`) with
individual flags overriding file values.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.cli.cmd_run import CmdRun
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.runner import config as tpx_config
from torchx_tpu.runner.api import Runner, get_runner
from torchx_tpu.specs.finder import (
    ComponentNotFoundException,
    ComponentValidationException,
)

logger = logging.getLogger(__name__)


class CmdSupervise(SubCommand):
    """Submit a component and babysit it to success (see module docstring)."""

    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "-s",
            "--scheduler",
            type=str,
            default=None,
            help="scheduler backend to submit to (default: first registered)",
        )
        subparser.add_argument(
            "-cfg",
            "--scheduler_args",
            type=str,
            default="",
            help="scheduler run config as comma-separated k=v pairs",
        )
        subparser.add_argument(
            "--workspace",
            type=str,
            default=None,
            help="local workspace to package into the job image",
        )
        subparser.add_argument(
            "--parent_run_id", type=str, default=None, help="tracker parent run id"
        )
        subparser.add_argument(
            "--policy",
            type=str,
            default=None,
            help="JSON file with SupervisorPolicy fields; flags below"
            " override file values",
        )
        subparser.add_argument(
            "--max-preemptions",
            type=int,
            default=None,
            help="resubmits allowed after spot reclaims (default 8)",
        )
        subparser.add_argument(
            "--max-infra-retries",
            type=int,
            default=None,
            help="resubmits allowed after infra failures (default 3)",
        )
        subparser.add_argument(
            "--max-retries",
            type=int,
            default=None,
            help="resubmits allowed after application failures (default 0:"
            " app bugs fail deterministically)",
        )
        subparser.add_argument(
            "--backoff",
            type=float,
            default=None,
            help="initial resubmit backoff in seconds (default 5; doubles"
            " per consecutive retry, capped at --backoff-max)",
        )
        subparser.add_argument(
            "--backoff-max",
            type=float,
            default=None,
            help="ceiling on a single backoff delay in seconds (default 300)",
        )
        subparser.add_argument(
            "--poll-interval",
            type=float,
            default=None,
            help="cap on the jittered status poll interval (default 10s)",
        )
        subparser.add_argument(
            "--checkpoint-dir",
            type=str,
            default=None,
            help="checkpoint dir to read the latest step from; injected as"
            " TPX_RESUME_STEP on every resubmit",
        )
        subparser.add_argument(
            "--elastic",
            action="store_true",
            default=None,
            help="run the backend's elastic watcher during each attempt",
        )
        subparser.add_argument(
            "--poll-miss-budget",
            type=int,
            default=None,
            help="consecutive transient status-poll failures absorbed"
            " (as poll_degraded warnings) before surfacing (default 3)",
        )
        subparser.add_argument(
            "--hang-deadline",
            type=float,
            default=None,
            help="seconds without heartbeats/leases before the gang counts"
            " as hung (kill + classify HANG + resubmit; default 0: off)",
        )
        subparser.add_argument(
            "--gang-check-interval",
            type=float,
            default=None,
            help="seconds between gang-health checks while an attempt runs"
            " (default 5)",
        )
        subparser.add_argument(
            "--lease-ttl",
            type=float,
            default=None,
            help="liveness-lease TTL in seconds (default: the hang deadline)",
        )
        subparser.add_argument(
            "--straggler-step-lag",
            type=int,
            default=None,
            help="warn when replicas drift more than this many steps apart"
            " (default 0: off)",
        )
        subparser.add_argument(
            "--max-hang-retries",
            type=int,
            default=None,
            help="resubmits allowed after gang hangs (default 2)",
        )
        subparser.add_argument(
            "--elastic-reshape",
            action="store_true",
            default=None,
            help="after PREEMPTION/HANG, shrink the mesh's data axes to the"
            " surviving capacity and resubmit with $TPX_MESH (needs --mesh)",
        )
        subparser.add_argument(
            "--mesh",
            type=str,
            default=None,
            help="the job's launch mesh spec (pp/dp/fsdp/ep/tp/sp, e.g."
            " dp=2,fsdp=-1); the basis --elastic-reshape degrades from",
        )
        subparser.add_argument(
            "--devices-per-replica",
            type=int,
            default=None,
            help="accelerator devices each replica contributes to the mesh"
            " (default 1)",
        )
        subparser.add_argument(
            "--session",
            type=str,
            default=None,
            help="name for the durable supervision session (default:"
            " auto-generated; shown on start for --resume)",
        )
        subparser.add_argument(
            "--resume",
            type=str,
            default=None,
            metavar="SESSION",
            help="reattach to a crashed supervise session: restore its"
            " attempt/retry state from the on-disk ledger and keep"
            " watching the live attempt instead of resubmitting",
        )
        subparser.add_argument(
            "conf_args",
            nargs=argparse.REMAINDER,
            help="component name followed by its arguments"
            " (e.g. dist.spmd -j 1x4 --script train.py)",
        )

    def _build_policy(self, args: argparse.Namespace):  # noqa: ANN202
        from torchx_tpu.specs.serialize import supervisor_policy_from_dict
        from torchx_tpu.supervisor.policy import SupervisorPolicy

        if args.policy:
            with open(args.policy) as f:
                policy = supervisor_policy_from_dict(json.load(f))
        else:
            policy = SupervisorPolicy()
        overrides = {
            "max_preemptions": args.max_preemptions,
            "max_infra_retries": args.max_infra_retries,
            "max_app_retries": args.max_retries,
            "backoff_seconds": args.backoff,
            "backoff_max_seconds": args.backoff_max,
            "poll_interval": args.poll_interval,
            "checkpoint_dir": args.checkpoint_dir,
            "elastic": args.elastic,
            "poll_miss_budget": args.poll_miss_budget,
            "hang_deadline_seconds": args.hang_deadline,
            "gang_check_interval": args.gang_check_interval,
            "lease_ttl_seconds": args.lease_ttl,
            "straggler_step_lag": args.straggler_step_lag,
            "max_hang_retries": args.max_hang_retries,
            "elastic_reshape": args.elastic_reshape,
            "mesh": args.mesh,
            "devices_per_replica": args.devices_per_replica,
        }
        for name, value in overrides.items():
            if value is not None:
                setattr(policy, name, value)
        policy.__post_init__()  # re-validate after overrides
        return policy

    def run(self, args: argparse.Namespace) -> None:
        with get_runner(
            component_defaults=tpx_config.load_sections("component")
        ) as runner:
            self._run(runner, args)

    def _run(self, runner: Runner, args: argparse.Namespace) -> None:
        # one root span over dryrun + supervise: every attempt, backoff,
        # and in-job heartbeat lands in a single trace for `tpx trace`
        with obs_trace.span("tpx.supervise", session=runner._name):
            self._run_traced(runner, args)

    def _run_traced(self, runner: Runner, args: argparse.Namespace) -> None:
        if args.resume:
            self._run_resume(runner, args)
            return
        scheduler = args.scheduler
        if scheduler is None:
            from torchx_tpu.schedulers import get_default_scheduler_name

            scheduler = (
                tpx_config.get_config("cli", "run", "scheduler")
                or get_default_scheduler_name()
            )
        cfg = runner.scheduler_run_opts(scheduler).cfg_from_str(args.scheduler_args)
        tpx_config.apply(scheduler, cfg)

        component, component_args = CmdRun()._parse_component(args.conf_args)
        try:
            policy = self._build_policy(args)
            dryrun_info = runner.dryrun_component(
                component,
                component_args,
                scheduler,
                cfg,
                workspace=args.workspace,
                parent_run_id=args.parent_run_id,
            )
        except (
            ComponentValidationException,
            ComponentNotFoundException,
            OSError,
            json.JSONDecodeError,
        ) as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)

        try:
            result = runner.supervise(dryrun_info, policy, session=args.session)
        except KeyboardInterrupt:
            logger.warning("ctrl-c: supervisor stopped; the current attempt"
                           " keeps running (cancel it with `tpx cancel`)")
            raise
        self._report(result)

    def _run_resume(self, runner: Runner, args: argparse.Namespace) -> None:
        from torchx_tpu.supervisor.api import Supervisor

        try:
            supervisor = Supervisor.resume(runner, args.resume)
        except (FileNotFoundError, ValueError, KeyError) as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)
        print(f"session: {supervisor.session} (reattaching)")
        try:
            result = supervisor.run()
        except KeyboardInterrupt:
            logger.warning("ctrl-c: supervisor stopped; the current attempt"
                           " keeps running (cancel it with `tpx cancel`)")
            raise
        self._report(result)

    def _report(self, result) -> None:  # noqa: ANN001
        if result.session:
            print(
                f"session: {result.session} (resume after a crash with:"
                f" tpx supervise --resume {result.session})"
            )
        for i, (handle, step) in enumerate(
            zip(result.handles, result.resume_steps), start=1
        ):
            resumed = f" (resumed from step {step})" if step is not None else ""
            print(f"attempt {i}: {handle}{resumed}")
        if result.status is not None:
            print(result.status.format())
        if result.budget_exhausted is not None:
            print(
                f"{result.budget_exhausted.value.lower()} retry budget"
                " exhausted",
                file=sys.stderr,
            )
        if not result.succeeded:
            sys.exit(1)
