"""``tpx tracker`` — query experiment tracking backends from the client.

Reference analog: torchx/cli/cmd_tracker.py (136 LoC). Subcommands operate
on the trackers configured in .tpxconfig ``[tracker:*]`` sections:

    tpx tracker list runs
    tpx tracker list metadata <run_id>
    tpx tracker list artifacts <run_id>
    tpx tracker lineage <run_id>
"""

from __future__ import annotations

import argparse
import json
import sys

from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.runner.config import load_tracker_sections
from torchx_tpu.tracker.api import TrackerBase, _load_tracker


def _trackers() -> dict[str, TrackerBase]:
    out = {}
    for name, config in load_tracker_sections().items():
        t = _load_tracker(name, config)
        if t is not None:
            out[name] = t
    if not out:
        print(
            "no trackers configured; add a [tracker:<name>] section to"
            " .tpxconfig (e.g. [tracker:fsspec] with config = <root-path>)",
            file=sys.stderr,
        )
        sys.exit(1)
    return out


class CmdTracker(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        sub = subparser.add_subparsers(dest="tracker_cmd", required=True)

        p_list = sub.add_parser("list", help="list runs / metadata / artifacts")
        p_list.add_argument(
            "what", choices=["runs", "metadata", "artifacts"], help="what to list"
        )
        p_list.add_argument("run_id", nargs="?", default=None)
        p_list.set_defaults(tracker_fn=self._list)

        p_lineage = sub.add_parser("lineage", help="show run lineage sources")
        p_lineage.add_argument("run_id")
        p_lineage.set_defaults(tracker_fn=self._lineage)

    def run(self, args: argparse.Namespace) -> None:
        args.tracker_fn(args)

    def _list(self, args: argparse.Namespace) -> None:
        trackers = _trackers()
        # with multiple backends, prefix each line so outputs are attributable
        prefix = (lambda name: f"[{name}] ") if len(trackers) > 1 else (lambda name: "")
        for name, tracker in trackers.items():
            if args.what == "runs":
                for run_id in tracker.run_ids():
                    print(f"{prefix(name)}{run_id}")
            elif args.what == "metadata":
                if not args.run_id:
                    print("run_id required for metadata", file=sys.stderr)
                    sys.exit(1)
                if len(trackers) > 1:
                    print(f"[{name}]")
                print(json.dumps(dict(tracker.metadata(args.run_id)), indent=2))
            elif args.what == "artifacts":
                if not args.run_id:
                    print("run_id required for artifacts", file=sys.stderr)
                    sys.exit(1)
                for artifact in tracker.artifacts(args.run_id).values():
                    print(f"{prefix(name)}{artifact.name}\t{artifact.path}")

    def _lineage(self, args: argparse.Namespace) -> None:
        trackers = _trackers()
        prefix = (lambda name: f"[{name}] ") if len(trackers) > 1 else (lambda name: "")
        for name, tracker in trackers.items():
            lineage = tracker.lineage(args.run_id)
            for src in lineage.sources:
                suffix = f" (artifact: {src.artifact_name})" if src.artifact_name else ""
                print(f"{prefix(name)}upstream: {src.source_run_id}{suffix}")
            for rid in lineage.descendants:
                print(f"{prefix(name)}downstream: {rid}")
