"""``tpx top`` — a live fleet dashboard over the control daemon.

Composes one screenful from the daemon's telemetry plane: the health
probe, the fleet queue snapshot, active SLO alerts with their burn
rates, and a few headline metric reductions (p99 TTFT, request rate,
step time, gang wait) from ``/v1/metrics/query``. ``--once`` prints a
single snapshot and exits (scripts/tests); the default is a
clear-and-redraw refresh loop until Ctrl-C.

Finds the daemon like every other proxied verb — ``$TPX_CONTROL_ADDR``
or the discovery file (``require_env=False``). Pure stdlib: the render
path is jax-free and testable as :func:`render_top` over a plain dict.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any

from torchx_tpu.cli.cmd_base import SubCommand

#: Headline reductions shown when the metric exists in the store:
#: (title, metric name, reducer, window seconds).
TOP_PANELS: list[tuple[str, str, str, float]] = [
    ("p99 TTFT", "tpx_serve_ttft_seconds", "p99", 60.0),
    ("req rate", "tpx_serve_requests_total", "rate", 60.0),
    ("p95 step time", "tpx_step_seconds", "p95", 300.0),
    ("p95 gang wait", "tpx_fleet_gang_wait_seconds", "p95", 600.0),
    # step-profiler gauges (obs/profile.py): published only by profiled
    # training runs, so the name-presence check below drops the panels
    # cleanly when no job is profiling
    ("train MFU", "tpx_profile_mfu", "last", 600.0),
    ("data wait", "tpx_profile_data_wait_frac", "last", 600.0),
]

_CLEAR = "\x1b[2J\x1b[H"


def build_snapshot(client: Any) -> dict:
    """One ``tpx top`` frame as a plain dict (the ``--json`` body).

    Every section degrades independently: a failing daemon verb becomes
    an ``{"error": ...}`` section instead of killing the dashboard."""
    from torchx_tpu.control.client import ControlClientError

    snap: dict[str, Any] = {"ts": time.time(), "addr": client.addr}
    for key, fetch in (
        ("health", client.healthz),
        ("queue", client.queue),
        ("alerts", client.alerts),
    ):
        try:
            snap[key] = fetch()
        except ControlClientError as e:
            snap[key] = {"error": e.message}
    # federation panel: the registry's cells, live-probed. Only present
    # when cells are registered — a single-daemon setup stays clean.
    try:
        from torchx_tpu.federation.cells import CellHandle, CellRegistry

        cells = {}
        for spec in CellRegistry().cells():
            probe = CellHandle(spec).probe()
            cells[spec.name] = {
                "state": (
                    probe["state"] if probe["reachable"] else "UNREACHABLE"
                ),
                "rehydrated": probe["rehydrated"],
                "burn": round(float(probe.get("burn", 0.0)), 3),
            }
        if cells:
            snap["cells"] = cells
    except OSError as e:
        snap["cells"] = {"error": str(e)}
    panels = []
    try:
        names = set(client.metrics_query().get("names", []))
        for title, name, reduce_, range_s in TOP_PANELS:
            # a histogram's series are its _bucket/_sum/_count components;
            # the base name itself never appears in the store's name list
            if name not in names and f"{name}_bucket" not in names:
                continue
            reply = client.metrics_query(
                name=name, reduce=reduce_, range_s=range_s
            )
            panels.append(
                {
                    "title": title,
                    "name": name,
                    "reduce": reduce_,
                    "range_s": range_s,
                    "result": reply.get("result", []),
                }
            )
    except ControlClientError as e:
        snap["metrics"] = {"error": e.message}
    else:
        snap["metrics"] = {"panels": panels}
    return snap


def _fmt_labels(labels: Any) -> str:
    if not labels:
        return ""
    items = ",".join(f"{k}={v}" for k, v in sorted(dict(labels).items()))
    return "{" + items + "}"


def _fmt_value(value: Any) -> str:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return str(value)
    if v != v:  # NaN (not enough samples in the window)
        return "-"
    return f"{v:.4g}"


def render_top(snap: dict) -> str:
    """Render one snapshot dict to the dashboard text (pure, jax-free)."""
    lines = []
    stamp = time.strftime("%H:%M:%S", time.localtime(snap.get("ts", 0)))
    health = snap.get("health", {})
    if "error" in health:
        head = f"daemon UNREACHABLE ({health['error']})"
    else:
        head = (
            f"jobs {health.get('jobs', 0)}"
            f"  fleet {'on' if health.get('fleet') else 'off'}"
        )
    lines.append(f"tpx top — {snap.get('addr', '?')}  {head}  {stamp}")

    alerts = snap.get("alerts", {})
    if "error" in alerts:
        lines.append(f"slo: error: {alerts['error']}")
    elif not alerts.get("enabled"):
        lines.append("slo: telemetry plane disabled")
    else:
        active = alerts.get("alerts", [])
        if active:
            for a in active:
                lines.append(
                    f"slo: [{str(a.get('severity', '')).upper()}]"
                    f" {a.get('slo')} burning"
                    f" {a.get('burn_short')}x/{a.get('burn_long')}x"
                    " (short/long)"
                )
        else:
            lines.append(f"slo: {len(alerts.get('slos', []))} spec(s), no alerts")
        burns = alerts.get("burns", {})
        if burns:
            lines.append(
                "burn: "
                + "  ".join(
                    f"{name} {b.get('short')}/{b.get('long')}"
                    for name, b in sorted(burns.items())
                )
            )

    cells = snap.get("cells")
    if cells:
        if "error" in cells:
            lines.append(f"cells: error: {cells['error']}")
        else:
            lines.append(
                "cells: "
                + "  ".join(
                    f"{name}={c.get('state')}"
                    f"(burn {c.get('burn', 0.0):g})"
                    + ("" if c.get("rehydrated") else " REHYDRATING")
                    for name, c in sorted(cells.items())
                )
            )

    queue = snap.get("queue", {})
    if "error" in queue:
        lines.append(f"fleet: error: {queue['error']}")
    elif queue.get("enabled"):
        fleet = queue.get("fleet", {})
        market = queue.get("market", {})
        lines.append(
            f"fleet: {fleet.get('chips_free')}/{fleet.get('chips_total')}"
            f" chips free | running {len(queue.get('running', []))}"
            f" queued {len(queue.get('queue', []))}"
            f" | shrinks {market.get('reshapes', 0)}"
            f" grows {market.get('growbacks', 0)}"
            f" kills {market.get('kills', 0)}"
        )
        for r in queue.get("running", []):
            shape = (
                f"SHRUNK {r.get('replicas')}/{r.get('launch_replicas')}"
                if r.get("shrunk")
                else f"x{r.get('replicas')}"
            )
            lines.append(
                f"  run  {str(r.get('job', '')):<12}"
                f" {str(r.get('class', '')):<12} {shape}"
            )
        for q in queue.get("queue", []):
            lines.append(
                f"  wait #{q.get('position'):<3}"
                f" {str(q.get('job', '')):<12}"
                f" {str(q.get('class', '')):<12} x{q.get('replicas')}"
            )

    metrics = snap.get("metrics", {})
    if "error" in metrics:
        lines.append(f"metrics: error: {metrics['error']}")
    else:
        panels = metrics.get("panels", [])
        if panels:
            lines.append("metrics:")
        for panel in panels:
            results = panel.get("result", [])
            if not results:
                lines.append(f"  {panel['title']:<16} -")
                continue
            for entry in results:
                lines.append(
                    f"  {panel['title']:<16}"
                    f" {_fmt_value(entry.get('value')):>10}"
                    f"  {_fmt_labels(entry.get('labels'))}"
                )
    return "\n".join(lines)


class CmdTop(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "--once",
            action="store_true",
            help="print one snapshot and exit (no screen clearing)",
        )
        subparser.add_argument(
            "--interval",
            type=float,
            default=2.0,
            metavar="SECONDS",
            help="refresh period for the live loop (default 2s)",
        )
        subparser.add_argument(
            "--json",
            action="store_true",
            help="print one raw snapshot as JSON and exit",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.control.client import ControlClientError, maybe_client

        try:
            client = maybe_client(require_env=False)
        except ControlClientError as e:
            print(f"top: {e.message}", file=sys.stderr)
            sys.exit(1)
        if client is None:
            print(
                "top: no control daemon found (start `tpx control` or set"
                " TPX_CONTROL_ADDR)",
                file=sys.stderr,
            )
            sys.exit(1)
        if args.json:
            print(json.dumps(build_snapshot(client), indent=2, sort_keys=True))
            return
        if args.once:
            print(render_top(build_snapshot(client)))
            return
        try:
            while True:
                frame = render_top(build_snapshot(client))
                sys.stdout.write(_CLEAR + frame + "\n")
                sys.stdout.flush()
                time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            print()
