"""``tpx run`` — materialize a component and submit it.

Reference analog: torchx/cli/cmd_run.py (505 LoC): component + args parsing
(with default component from .tpxconfig ``[cli:run]``), ``--dryrun``
printing the AppDef and materialized scheduler request, ``--wait`` /
``--log`` streaming, and auto-wait for local runs.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from torchx_tpu.analyze import LintError
from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.runner import config as tpx_config
from torchx_tpu.runner.api import Runner, get_runner
from torchx_tpu.specs.finder import (
    ComponentNotFoundException,
    ComponentValidationException,
)

logger = logging.getLogger(__name__)


class CmdRun(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "-s",
            "--scheduler",
            type=str,
            default=None,
            help="scheduler backend to submit to (default: first registered)",
        )
        subparser.add_argument(
            "-cfg",
            "--scheduler_args",
            type=str,
            default="",
            help="scheduler run config as comma-separated k=v pairs",
        )
        subparser.add_argument(
            "--dryrun",
            action="store_true",
            help="print the materialized AppDef and scheduler request, do not submit",
        )
        subparser.add_argument(
            "--wait",
            action="store_true",
            help="block until the app reaches a terminal state",
        )
        subparser.add_argument(
            "--log",
            action="store_true",
            help="stream all replica logs (implies --wait)",
        )
        subparser.add_argument(
            "--workspace",
            type=str,
            default=None,
            help="local workspace to package into the job image",
        )
        subparser.add_argument(
            "--parent_run_id", type=str, default=None, help="tracker parent run id"
        )
        subparser.add_argument(
            "--no-lint",
            action="store_true",
            help="skip the preflight analyzer gate (see `tpx lint`)",
        )
        subparser.add_argument(
            "--stdin",
            action="store_true",
            help="read an AppDef JSON job spec from stdin instead of a"
            " component (see torchx_tpu.specs.serialize)",
        )
        subparser.add_argument(
            "conf_args",
            nargs=argparse.REMAINDER,
            help="component name followed by its arguments"
            " (e.g. dist.spmd -j 1x4 --script train.py)",
        )

    def run(self, args: argparse.Namespace) -> None:
        if not args.dryrun and not args.stdin:
            from torchx_tpu.cli.cmd_base import control_client

            client = control_client()
            if client is not None:
                # daemon mode: submit/wait/log ride the control plane;
                # --dryrun and --stdin stay direct (they need the local
                # materialization machinery, not a running scheduler)
                self._run_proxied(client, args)
                return
        with get_runner(component_defaults=tpx_config.load_sections("component")) as runner:
            self._run(runner, args)

    def _run_proxied(self, client, args: argparse.Namespace) -> None:  # noqa: ANN001
        from torchx_tpu.control.client import ControlClientError

        scheduler = args.scheduler
        if scheduler is None:
            from torchx_tpu.schedulers import get_default_scheduler_name

            scheduler = (
                tpx_config.get_config("cli", "run", "scheduler")
                or get_default_scheduler_name()
            )
        component, component_args = self._parse_component(args.conf_args)
        try:
            app_handle = client.submit(
                component,
                component_args,
                scheduler,
                cfg_str=args.scheduler_args,
                workspace=args.workspace,
            )
        except ControlClientError as e:
            print(f"error: {e.message}", file=sys.stderr)
            sys.exit(1)
        print(app_handle)
        if not (args.wait or args.log or scheduler == "local"):
            return
        try:
            final = client.wait(app_handle)
        except KeyboardInterrupt:
            logger.warning("ctrl-c: cancelling %s", app_handle)
            client.cancel(app_handle)
            raise
        if args.log:
            # terminal logs, attached through the daemon, one role/replica
            # at a time (the direct path's live tee needs scheduler access)
            for role in final.get("roles", []):
                for rid in role.get("replicas", []):
                    for line in client.log_lines(
                        app_handle, role.get("role", "app"), k=rid
                    ):
                        print(f"{role.get('role')}/{rid} {line}")
        state = final.get("state")
        line = f"{app_handle}: {state}"
        if final.get("failure_class"):
            line += f" ({final['failure_class']})"
        print(line)
        if state != "SUCCEEDED":
            sys.exit(1)

    def _run(self, runner: Runner, args: argparse.Namespace) -> None:
        from torchx_tpu.obs import trace as obs_trace

        # one root span over submit + wait, so `tpx run --wait` leaves a
        # single trace instead of one per Runner call
        with obs_trace.span("tpx.run", session=runner._name):
            self._run_traced(runner, args)

    def _run_traced(self, runner: Runner, args: argparse.Namespace) -> None:
        scheduler = args.scheduler
        if scheduler is None:
            from torchx_tpu.schedulers import get_default_scheduler_name

            scheduler = (
                tpx_config.get_config("cli", "run", "scheduler")
                or get_default_scheduler_name()
            )

        cfg = runner.scheduler_run_opts(scheduler).cfg_from_str(args.scheduler_args)
        tpx_config.apply(scheduler, cfg)

        if args.stdin:
            leftover = [a for a in args.conf_args if a != "--"]
            if leftover:
                print(
                    f"error: --stdin reads the job spec from stdin; remove"
                    f" the component arguments {leftover!r}",
                    file=sys.stderr,
                )
                sys.exit(1)
            self._run_from_stdin(runner, args, scheduler, cfg)
            return

        component, component_args = self._parse_component(args.conf_args)

        try:
            if args.dryrun:
                dryrun_info = runner.dryrun_component(
                    component,
                    component_args,
                    scheduler,
                    cfg,
                    workspace=args.workspace,
                    parent_run_id=args.parent_run_id,
                    no_lint=args.no_lint,
                )
                print("=== APPLICATION ===")
                print(_pretty_app(dryrun_info._app))
                print("=== SCHEDULER REQUEST ===")
                print(dryrun_info)
                return
            app_handle = runner.run_component(
                component,
                component_args,
                scheduler,
                cfg,
                workspace=args.workspace,
                parent_run_id=args.parent_run_id,
                no_lint=args.no_lint,
            )
        except LintError as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)
        except (ComponentValidationException, ComponentNotFoundException) as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)
        except ValueError as e:
            # component functions raise ValueError for bad arg combinations
            # (e.g. malformed -j); show it cleanly, not as a traceback
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)

        print(app_handle)
        self._maybe_wait(runner, args, scheduler, app_handle)

    def _run_from_stdin(self, runner: Runner, args, scheduler: str, cfg) -> None:  # noqa: ANN001
        from torchx_tpu.specs.serialize import appdef_from_dict

        try:
            app = appdef_from_dict(json.load(sys.stdin))
        except (json.JSONDecodeError, ValueError, KeyError, TypeError, AttributeError) as e:
            print(f"error: invalid job spec on stdin: {e}", file=sys.stderr)
            sys.exit(1)
        try:
            if args.dryrun:
                info = runner.dryrun(
                    app,
                    scheduler,
                    cfg,
                    workspace=args.workspace,
                    parent_run_id=args.parent_run_id,
                    no_lint=args.no_lint,
                )
                print("=== APPLICATION ===")
                print(_pretty_app(info._app))
                print("=== SCHEDULER REQUEST ===")
                print(info)
                return
            handle = runner.run(
                app,
                scheduler,
                cfg,
                workspace=args.workspace,
                parent_run_id=args.parent_run_id,
                no_lint=args.no_lint,
            )
        except (LintError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            sys.exit(1)
        print(handle)
        self._maybe_wait(runner, args, scheduler, handle)

    def _maybe_wait(self, runner: Runner, args, scheduler: str, app_handle: str) -> None:  # noqa: ANN001
        """Local runs auto-wait (ctrl-c cleans up children); --wait/--log
        force it elsewhere (reference cmd_run.py:321-324)."""
        if not (args.wait or args.log or scheduler == "local"):
            return
        log_thread = None
        if args.log:
            from torchx_tpu.util.log_tee_helpers import tee_logs

            log_thread = tee_logs(runner, app_handle, should_tail=True)
        try:
            status = runner.wait(app_handle, wait_interval=1)
        except KeyboardInterrupt:
            logger.warning("ctrl-c: cancelling %s", app_handle)
            runner.cancel(app_handle)
            raise
        if log_thread is not None:
            log_thread.join(timeout=10)
        if status is None:
            print("job not found while waiting", file=sys.stderr)
            sys.exit(1)
        print(status.format())
        if status.state.name != "SUCCEEDED":
            sys.exit(1)

    def _parse_component(self, conf_args: list[str]) -> tuple[str, list[str]]:
        """First positional is the component name; a missing name falls back
        to .tpxconfig [cli:run] component= (reference cmd_run.py:120-180)."""
        if conf_args and conf_args[0] == "--":
            conf_args = conf_args[1:]
        if not conf_args or conf_args[0].startswith("-"):
            default = tpx_config.get_config("cli", "run", "component")
            if not default:
                print(
                    "error: no component specified and no default component"
                    " configured in .tpxconfig [cli:run]",
                    file=sys.stderr,
                )
                sys.exit(1)
            return default, conf_args
        return conf_args[0], conf_args[1:]


def _pretty_app(app) -> str:  # noqa: ANN001
    if app is None:
        return "<none>"
    out = {
        "name": app.name,
        "roles": [
            {
                "name": r.name,
                "image": r.image,
                "entrypoint": r.entrypoint,
                "args": r.args,
                "env": r.env,
                "num_replicas": r.num_replicas,
                "resource": {
                    "cpu": r.resource.cpu,
                    "memMB": r.resource.memMB,
                    "tpu": str(r.resource.tpu) if r.resource.tpu else None,
                },
            }
            for r in app.roles
        ],
    }
    return json.dumps(out, indent=2)
