"""``tpx tune`` — closed-loop config autotuner over the explain cost model.

Enumerates a declarative search space (mesh spec x remat policy x batch x
prefetch x int8 scope), prunes statically through the deep-preflight cost
model and the XLA AOT memory fit with ZERO device seconds, measures only
the surviving top-k via short seeded bench trials, and emits the winner
as a content-digested plan artifact that ``tpx run`` can pin
(``$TPX_PLAN_ARTIFACT`` -> TPX706/707 in the submit gate) and
``tpx explain --artifact`` can diff. Every measured trial folds its
prediction-vs-actual error back into the persisted per-generation
calibration table, so the cost model — and everything reading it: the
explain report, future tune runs, the fleet placer's HBM-refusal oracle —
gets sharper with every run.

Module level stays jax-free (``tpx tune --help`` must not import jax);
only the AOT-probe and measurement *subprocesses* touch a backend.

Exit codes: 0 winner emitted, 1 tune failed (all candidates pruned, all
measurements failed), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from torchx_tpu.cli.cmd_base import SubCommand

logger = logging.getLogger(__name__)


class CmdTune(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "--space",
            type=str,
            default="tiny-smoke",
            help="builtin search-space name (see --list-spaces) or a JSON"
            " file describing a SearchSpace",
        )
        subparser.add_argument(
            "--list-spaces",
            action="store_true",
            help="print the builtin search spaces and exit",
        )
        subparser.add_argument(
            "--devices",
            type=int,
            default=None,
            help="device count to tune for (default: $TPX_TUNE_DEVICES or 8)",
        )
        subparser.add_argument(
            "--hbm-gb",
            type=float,
            default=None,
            help="per-chip HBM budget in GiB (default: generation table)",
        )
        subparser.add_argument(
            "--generation",
            type=str,
            default="",
            help="accelerator generation for ranking + calibration"
            " (e.g. v5p; default: inferred, cpu-sim off-TPU)",
        )
        subparser.add_argument(
            "--top-k",
            type=int,
            default=3,
            help="how many ranked survivors get measured (default 3)",
        )
        subparser.add_argument(
            "--out-dir",
            type=str,
            default=None,
            help="journal/artifact directory (default:"
            " $TPX_TUNE_DIR/<space digest>; reuse to resume)",
        )
        subparser.add_argument(
            "--no-aot",
            action="store_true",
            help="skip the XLA AOT memory-fit prune stage",
        )
        subparser.add_argument(
            "--no-measure",
            action="store_true",
            help="static-only: rank and emit the predicted winner without"
            " running any trial",
        )
        subparser.add_argument(
            "--data-path",
            type=str,
            default=None,
            help="tokenized dataset for measured trials (default synthetic)",
        )
        subparser.add_argument(
            "--json",
            action="store_true",
            help="emit the full tune result as JSON",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.tune.space import BUILTIN_SPACES, SearchSpace

        if args.list_spaces:
            for name, factory in sorted(BUILTIN_SPACES.items()):
                space = factory()
                print(
                    f"{name}: config={space.config}"
                    f" candidates={len(space.candidates())}"
                    f" digest={space.digest()}"
                )
            return
        if args.space in BUILTIN_SPACES:
            space = BUILTIN_SPACES[args.space]()
        else:
            try:
                with open(args.space) as f:
                    space = SearchSpace.from_dict(json.load(f))
            except (OSError, json.JSONDecodeError, ValueError, KeyError) as e:
                print(
                    f"error: --space must be one of"
                    f" {sorted(BUILTIN_SPACES)} or a SearchSpace JSON file:"
                    f" {e}",
                    file=sys.stderr,
                )
                sys.exit(2)

        from torchx_tpu.settings import ENV_TPX_TUNE_DEVICES

        devices = args.devices or int(os.environ.get(ENV_TPX_TUNE_DEVICES, 8))
        from torchx_tpu.tune.driver import TuneError, run_tune

        try:
            result = run_tune(
                space,
                devices=devices,
                hbm_bytes=(
                    int(args.hbm_gb * 1024**3)
                    if args.hbm_gb is not None
                    else None
                ),
                generation=args.generation,
                out_dir=args.out_dir,
                top_k=args.top_k,
                aot=not args.no_aot,
                measure=not args.no_measure,
                data_path=args.data_path,
            )
        except TuneError as e:
            print(f"error: tune failed: {e}", file=sys.stderr)
            sys.exit(1)

        if args.json:
            print(json.dumps(result.to_dict(), indent=2, default=str))
        else:
            print(self._render(result))
        sys.exit(0 if result.winner is not None else 1)

    @staticmethod
    def _render(result) -> str:  # noqa: ANN001 - TuneResult
        r = result.report
        lines = [
            f"tune: {result.space.config} — {r['candidates']} candidate(s),"
            f" {r['pruned_static']} pruned static,"
            f" {r['pruned_aot']} pruned AOT, {r['measured']} measured"
            f" ({r['prune_rate']:.0%} decided with zero device seconds)"
        ]
        if r.get("pruned_by_code"):
            lines.append(
                "  pruned by: "
                + ", ".join(
                    f"{code}x{n}" for code, n in r["pruned_by_code"].items()
                )
            )
        for t in result.trials:
            if t.status not in ("measured", "measure_failed", "selected"):
                continue
            pred = (t.predicted.get("step_cost") or {}).get("step_s")
            pred_s = f" predicted {pred * 1e3:.1f}ms" if pred else ""
            meas = t.metrics.get("step_time_s")
            meas_s = f" measured {meas * 1e3:.1f}ms" if meas else ""
            tok = t.metrics.get("tokens_per_sec_per_chip")
            tok_s = f" {tok:,.0f} tok/s/chip" if tok else ""
            replay = " (replayed)" if t.replayed else ""
            lines.append(
                f"  {t.status:<15} {t.candidate.cid}{pred_s}{meas_s}"
                f"{tok_s}{replay}"
            )
        if result.winner is not None:
            lines.append(
                f"winner: {result.winner.candidate.cid}"
                f"\nartifact: {result.artifact_path}"
                "\npin it:  TPX_PLAN_ARTIFACT="
                f"{result.artifact_path} tpx run ..."
            )
        cal = result.calibration.get("step_time")
        if cal:
            lines.append(
                f"calibration: step-time error"
                f" {cal['err_before']:.1%} -> {cal['err_after']:.1%}"
                f" (generation {result.calibration['generation']})"
            )
        return "\n".join(lines)
