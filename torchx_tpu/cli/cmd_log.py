"""``tpx log`` — fan-out log tailing across replicas.

Reference analog: torchx/cli/cmd_log.py (211 LoC). Identifier grammar::

    SCHEDULER://[SESSION]/APP_ID[/ROLE[/REPLICA_IDS,..]]
"""

from __future__ import annotations

import argparse
import re
import sys

from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.runner.api import get_runner
from torchx_tpu.util.log_tee_helpers import (
    LineEmitter,
    find_role_replicas,
    wait_for_app_started,
)

_ID_RE = re.compile(
    r"^(?P<scheduler>\w+)://(?P<session>[^/]*)/(?P<app_id>[^/]+)"
    r"(?:/(?P<role>[^/]+)(?:/(?P<replicas>[\d,]+))?)?$"
)


class CmdLog(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "identifier", help="scheduler://session/app_id[/role[/replica,..]]"
        )
        subparser.add_argument("-t", "--tail", action="store_true", help="follow logs")
        subparser.add_argument("--regex", default=None, help="filter lines by regex")
        subparser.add_argument(
            "--since",
            default=None,
            help="window start: epoch seconds, relative (2h/30m/7d), or ISO time",
        )
        subparser.add_argument(
            "--until",
            default=None,
            help="window end: epoch seconds, relative (2h/30m/7d), or ISO time",
        )
        subparser.add_argument(
            "--streams",
            choices=["stdout", "stderr", "combined"],
            default=None,
            help="which stream to read (backend-dependent; default combined)",
        )

    def run(self, args: argparse.Namespace) -> None:
        m = _ID_RE.match(args.identifier)
        if not m:
            print(f"malformed identifier: {args.identifier}", file=sys.stderr)
            sys.exit(1)
        scheduler, session, app_id = (
            m.group("scheduler"),
            m.group("session"),
            m.group("app_id"),
        )
        role = m.group("role")
        replica_ids = (
            [int(r) for r in m.group("replicas").split(",")]
            if m.group("replicas")
            else None
        )
        from datetime import datetime

        from torchx_tpu.schedulers.api import Stream
        from torchx_tpu.util.times import parse_when

        try:
            since_ts = parse_when(args.since)
            until_ts = parse_when(args.until)
            since = (
                datetime.fromtimestamp(since_ts) if since_ts is not None else None
            )
            until = (
                datetime.fromtimestamp(until_ts) if until_ts is not None else None
            )
        except (ValueError, OverflowError, OSError) as e:
            print(f"cannot parse time window: {e}", file=sys.stderr)
            sys.exit(1)
        streams = Stream(args.streams) if args.streams else None

        app_handle = f"{scheduler}://{session}/{app_id}"
        from torchx_tpu.cli.cmd_base import control_client

        client = control_client()
        if client is not None and not (since or until or args.regex or streams):
            # daemon mode handles the plain attach path; windowed /
            # filtered / stream-selected reads stay direct (those options
            # ride scheduler-specific machinery the daemon doesn't proxy)
            self._run_proxied(client, app_handle, role, replica_ids, args)
            return
        with get_runner() as runner:
            status = wait_for_app_started(runner, app_handle)
            if status is None:
                print(f"app not found: {app_handle}", file=sys.stderr)
                sys.exit(1)
            pairs = find_role_replicas(status, role)
            if replica_ids is not None:
                pairs = [(r, i) for r, i in pairs if i in replica_ids]
            if not pairs:
                print("no matching replicas", file=sys.stderr)
                sys.exit(1)
            replicas: dict[str, list[int]] = {}
            for r, i in pairs:
                replicas.setdefault(r, []).append(i)
            # concurrent fan-out with a line-atomic emitter: streams are
            # read in parallel (runner.log_lines_multi pump threads) and
            # every emitted line is one complete write — no interleaved
            # partial lines under load
            emitter = LineEmitter(sys.stdout)
            for r, i, line in runner.log_lines_multi(
                app_handle,
                replicas,
                regex=args.regex,
                since=since,
                until=until,
                should_tail=args.tail,
                streams=streams,
            ):
                emitter.emit(f"{r}/{i}", line)

    def _run_proxied(
        self,
        client,  # noqa: ANN001
        app_handle: str,
        role: str,
        replica_ids,  # noqa: ANN001
        args: argparse.Namespace,
    ) -> None:
        """Log attach through the control daemon: resolve role/replica
        pairs from the daemon's status payload, then stream each replica's
        JSONL log feed."""
        from torchx_tpu.control.client import ControlClientError

        try:
            status = client.status(app_handle)
        except ControlClientError as e:
            if e.code == 404:
                print(f"app not found: {app_handle}", file=sys.stderr)
            else:
                print(f"control: {e.message}", file=sys.stderr)
            sys.exit(1)
        pairs = []
        for r in status.get("roles", []):
            if role and r.get("role") != role:
                continue
            for rid in r.get("replicas", []):
                if replica_ids is not None and rid not in replica_ids:
                    continue
                pairs.append((r.get("role", "app"), rid))
        if not pairs:
            print("no matching replicas", file=sys.stderr)
            sys.exit(1)
        try:
            for r, rid in pairs:
                for line in client.log_lines(
                    app_handle, r, k=rid, tail=args.tail
                ):
                    print(f"{r}/{rid} {line}")
        except ControlClientError as e:
            print(f"control: {e.message}", file=sys.stderr)
            sys.exit(1)
