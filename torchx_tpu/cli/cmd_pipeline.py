"""``tpx pipeline`` — submit and watch train→eval→promote DAGs.

Proxies the control daemon's ``/v1/pipelines`` verbs: ``submit`` POSTs a
:class:`~torchx_tpu.pipelines.dag.PipelineSpec` JSON file, ``status``
renders one pipeline's stage-by-stage record (or the full list plus the
current incumbent checkpoint), ``cancel`` stops a running pipeline.
Finds the daemon like every other proxied verb — ``$TPX_CONTROL_ADDR``
or the discovery file (``require_env=False``).
"""

from __future__ import annotations

import argparse
import json
import sys


from torchx_tpu.cli.cmd_base import SubCommand


class CmdPipeline(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        sub = subparser.add_subparsers(dest="action", required=True)

        submit = sub.add_parser(
            "submit", help="submit a pipeline spec (JSON file)"
        )
        submit.add_argument(
            "--file",
            "-f",
            required=True,
            help="path to a PipelineSpec JSON file"
            ' ({"name": ..., "stages": [...]})',
        )

        status = sub.add_parser(
            "status", help="one pipeline's stages, or all pipelines"
        )
        status.add_argument(
            "pipeline",
            nargs="?",
            default=None,
            help="pipeline id (pl_N); omit to list all",
        )
        status.add_argument(
            "--json",
            action="store_true",
            help="print the raw /v1/pipelines reply as JSON",
        )

        cancel = sub.add_parser("cancel", help="cancel a running pipeline")
        cancel.add_argument("pipeline", help="pipeline id (pl_N)")

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.control.client import ControlClientError, maybe_client

        try:
            client = maybe_client(require_env=False)
        except ControlClientError as e:
            print(f"pipeline: {e.message}", file=sys.stderr)
            sys.exit(1)
        if client is None:
            print(
                "pipeline: no control daemon found (start `tpx control"
                " ...` or set TPX_CONTROL_ADDR)",
                file=sys.stderr,
            )
            sys.exit(1)
        try:
            if args.action == "submit":
                with open(args.file) as f:
                    spec = json.load(f)
                reply = client.pipeline_submit(spec)
                print(reply.get("pipeline", ""))
            elif args.action == "cancel":
                reply = client.pipeline_cancel(args.pipeline)
                print(f"{args.pipeline}: {reply.get('state')}")
            else:
                reply = client.pipeline_status(args.pipeline)
                if args.json:
                    print(json.dumps(reply, indent=2, sort_keys=True))
                    return
                self._render(reply, args.pipeline)
        except OSError as e:
            print(f"pipeline: {e}", file=sys.stderr)
            sys.exit(1)
        except ControlClientError as e:
            print(f"pipeline: {e.message}", file=sys.stderr)
            sys.exit(1)

    def _render(self, reply: dict, pipeline: str | None) -> None:
        runs = [reply] if pipeline else reply.get("pipelines", [])
        incumbent = reply.get("incumbent")
        if incumbent:
            print(
                f"incumbent: {incumbent.get('ckpt')}"
                f" step {incumbent.get('step')}"
                f" score {incumbent.get('score')}"
            )
        if not runs:
            print("no pipelines")
            return
        for run in runs:
            reason = f"  ({run['reason']})" if run.get("reason") else ""
            print(
                f"{run['pipeline']:<8} {run['name']:<20}"
                f" {run['state']}{reason}"
            )
            for srun in run.get("stages", []):
                where = srun.get("handle") or srun.get("fleet_job") or ""
                err = f"  {srun['error']}" if srun.get("error") else ""
                art = srun.get("artifact") or {}
                tail = ""
                if art.get("kind") == "checkpoint":
                    tail = f"  step={art.get('step')}"
                elif art.get("kind") == "score":
                    tail = f"  score={art.get('score')}"
                print(
                    f"  {srun['name']:<16} {srun['kind']:<8}"
                    f" {srun['state']:<11} {where}{tail}{err}"
                )
