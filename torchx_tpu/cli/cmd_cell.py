"""``tpx cell`` — manage federation cells and their drain lifecycle.

Verbs over :mod:`torchx_tpu.federation`:

* ``tpx cell add NAME --addr URL [--token T]`` — register a cell in the
  durable registry (``$TPX_FEDERATION_DIR/cells.jsonl``). With no
  ``--addr``, the local daemon's discovery file is used.
* ``tpx cell remove NAME`` — forget a cell.
* ``tpx cell list [--json]`` — registry + live probe per cell
  (reachable, lifecycle state, rehydration, SLO burn).
* ``tpx cell status NAME`` — one cell's ``/v1/cell`` payload.
* ``tpx cell drain NAME`` — begin draining: in-flight work finishes,
  new submits bounce 503, the federation router routes away.
* ``tpx cell uncordon NAME`` — reopen a drained cell.

Lifecycle: HEALTHY → DRAINING → DRAINED → UNCORDONED (back to HEALTHY).
Mutating verbs re-run the TPX605 federation check and print its
warnings to stderr (single-cell federations cannot fail over).

Module level stays jax-free: ``tpx cell --help`` must not import jax —
the federation/control imports all happen inside ``run()``.

Exit codes: 0 ok, 1 cell unreachable/refused, 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from torchx_tpu.cli.cmd_base import SubCommand


class CmdCell(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        sub = subparser.add_subparsers(dest="action", required=True)

        add = sub.add_parser("add", help="register a cell's daemon")
        add.add_argument("name", help="cell name (the daemon's --cell)")
        add.add_argument(
            "--addr",
            default=None,
            help="daemon base URL (default: the local daemon's"
            " discovery file)",
        )
        add.add_argument(
            "--token",
            default=None,
            help="bearer token (default: the local discovery file's)",
        )

        remove = sub.add_parser("remove", help="forget a cell")
        remove.add_argument("name")

        lst = sub.add_parser(
            "list", help="registry + live probe of every cell"
        )
        lst.add_argument(
            "--json", action="store_true", help="machine-readable output"
        )

        for verb, help_text in (
            ("status", "one cell's /v1/cell payload"),
            ("drain", "drain a cell: finish in-flight, refuse new work"),
            ("uncordon", "reopen a drained cell for new traffic"),
        ):
            p = sub.add_parser(verb, help=help_text)
            p.add_argument("name")
            p.add_argument(
                "--json", action="store_true", help="machine-readable output"
            )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.federation.cells import CellRegistry

        registry = CellRegistry()
        if args.action == "add":
            self._add(registry, args)
        elif args.action == "remove":
            if not registry.remove(args.name):
                print(f"error: unknown cell {args.name!r}", file=sys.stderr)
                sys.exit(2)
            print(f"removed cell {args.name}")
        elif args.action == "list":
            self._list(registry, args)
        else:
            self._cell_verb(registry, args)
        if args.action in ("add", "remove", "drain"):
            self._warn_config(registry)

    # -- verbs -------------------------------------------------------------

    def _add(self, registry, args: argparse.Namespace) -> None:
        addr, token = args.addr, args.token
        if not addr or token is None:
            from torchx_tpu.control.client import _discovery

            found = _discovery()
            if found is None and not addr:
                print(
                    "error: no --addr and no local daemon discovery file;"
                    " start `tpx control` or pass --addr",
                    file=sys.stderr,
                )
                sys.exit(2)
            if found is not None:
                addr = addr or found[0]
                token = token if token is not None else found[1]
        spec = registry.add(args.name, addr, token or "")
        print(f"added cell {spec.name} -> {spec.addr}")

    def _handles(self, registry):
        from torchx_tpu.federation.cells import CellHandle

        return [CellHandle(spec) for spec in registry.cells()]

    def _list(self, registry, args: argparse.Namespace) -> None:
        rows = {}
        for handle in self._handles(registry):
            snap = handle.probe()
            rows[handle.name] = {
                "addr": handle.spec.addr,
                "reachable": snap["reachable"],
                "state": snap["state"] if snap["reachable"] else "UNREACHABLE",
                "rehydrated": snap["rehydrated"],
                "burn": round(float(snap.get("burn", 0.0)), 3),
            }
        if args.json:
            print(json.dumps({"cells": rows}, indent=2, sort_keys=True))
        else:
            if not rows:
                print("no cells registered (tpx cell add NAME --addr URL)")
            for name, row in sorted(rows.items()):
                print(
                    f"{name:16s} {row['state']:12s} burn={row['burn']:<6g}"
                    f" rehydrated={str(row['rehydrated']).lower():5s}"
                    f" {row['addr']}"
                )
        self._warn_config(registry)

    def _cell_verb(self, registry, args: argparse.Namespace) -> None:
        from torchx_tpu.control.client import ControlClient, ControlClientError

        spec = registry.get(args.name)
        if spec is None:
            print(f"error: unknown cell {args.name!r}", file=sys.stderr)
            sys.exit(2)
        client = ControlClient(spec.addr, spec.token, timeout=10.0)
        try:
            if args.action == "drain":
                payload = client.cell_drain()
            elif args.action == "uncordon":
                payload = client.cell_uncordon()
            else:
                payload = client.cell_status()
        except ControlClientError as e:
            print(
                f"error: cell {args.name}: {e.message} (code {e.code})",
                file=sys.stderr,
            )
            sys.exit(1)
        if args.json:
            print(json.dumps(payload, indent=2, sort_keys=True))
        else:
            reh = payload.get("rehydration") or {}
            print(
                f"cell {payload.get('cell')}: {payload.get('state')}"
                f" (inflight={payload.get('inflight', 0)},"
                f" rehydrated={str(payload.get('rehydrated')).lower()},"
                f" journal_jobs={reh.get('journal_jobs', 0)})"
            )

    def _warn_config(self, registry) -> None:
        from torchx_tpu.analyze.rules import check_federation_config

        config = {"cells": [s.to_json() for s in registry.cells()]}
        for diag in check_federation_config(config):
            print(
                f"{diag.severity.value}[{diag.code}]: {diag.message}"
                + (f"\n  hint: {diag.hint}" if diag.hint else ""),
                file=sys.stderr,
            )
