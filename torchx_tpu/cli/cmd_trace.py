"""``tpx trace`` — render a stored launch trace as an indented timeline.

Reads the JSONL trace files the obs subsystem writes under
``~/.torchx_tpu/obs/<session>/`` (see :mod:`torchx_tpu.obs.sinks`) — no
scheduler round-trips, so it works long after the job is gone::

    tpx trace local_cwd://tpx_ab12cd34/myapp_xyz
    tpx trace myapp_xyz --events
    tpx trace 4f1d...32-hex-trace-id... --metrics

The identifier may be a full app handle, a bare app id, or a raw trace
id. ``--events`` interleaves the TpxEvent audit records (supervisor
transitions and API calls) under their spans; ``--metrics`` appends the
session's aggregated Prometheus metrics table.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Any, Optional

from torchx_tpu.cli.cmd_base import SubCommand

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
_HANDLE_RE = re.compile(r"^\w+://[^/]*/(?P<app_id>[^/]+)")


def _app_id_of(identifier: str) -> str:
    """App id from a full handle, or the identifier itself when bare."""
    m = _HANDLE_RE.match(identifier)
    return m.group("app_id") if m else identifier


class CmdTrace(SubCommand):
    """Inspect stored traces (see module docstring)."""

    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "identifier",
            help="app handle (scheduler://session/app_id), bare app id,"
            " or 32-hex trace id",
        )
        subparser.add_argument(
            "--events",
            action="store_true",
            help="interleave TpxEvent records under their spans",
        )
        subparser.add_argument(
            "--metrics",
            action="store_true",
            help="append the session's aggregated metrics table",
        )
        subparser.add_argument(
            "--buckets",
            action="store_true",
            help="with --metrics: include histogram _bucket series",
        )
        subparser.add_argument(
            "--stitch",
            action="store_true",
            help="stitch one timeline across ALL session dirs (router,"
            " replicas, KV transfer, fleet daemon); identifier may also"
            " be a serve request_id or fleet job name",
        )
        subparser.add_argument(
            "--obs-dir",
            default=None,
            help="obs root to search (default: $TPX_OBS_DIR or"
            " ~/.torchx_tpu/obs)",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.obs import timeline

        if args.stitch:
            self._run_stitch(args)
            return

        files = list(timeline.iter_trace_files(args.obs_dir))
        if not files:
            print("no traces recorded yet", file=sys.stderr)
            sys.exit(1)

        # merge records across sessions: a client and its replicas normally
        # share one session dir, but a raw trace id may span several
        records: list[dict[str, Any]] = []
        file_of_record: list[str] = []
        for path in files:
            recs = timeline.load_records(path)
            records.extend(recs)
            file_of_record.extend([path] * len(recs))

        trace_id: Optional[str] = None
        if _TRACE_ID_RE.match(args.identifier):
            trace_id = args.identifier
        else:
            app_id = _app_id_of(args.identifier)
            trace_ids = timeline.find_trace_ids(records, app_id)
            if trace_ids:
                trace_id = trace_ids[0]  # files are newest-first: first hit
                if len(trace_ids) > 1:
                    print(
                        f"note: {len(trace_ids)} traces touched {app_id};"
                        f" showing the newest ({trace_id})",
                        file=sys.stderr,
                    )
        session_dirs = sorted(
            {
                os.path.dirname(f)
                for f, r in zip(file_of_record, records)
                if r.get("trace_id") == trace_id
            }
        )
        roots = timeline.build_timeline(records, trace_id) if trace_id else []
        if not roots:
            print(f"no trace found for: {args.identifier}", file=sys.stderr)
            sys.exit(1)

        print(f"trace {trace_id}")
        print(timeline.render_timeline(roots, include_events=args.events))

        if args.metrics:
            rows: list[tuple[str, str, float]] = []
            for d in session_dirs:
                rows.extend(timeline.load_metrics(d))
            print()
            print(
                timeline.render_metrics_table(
                    rows, include_buckets=args.buckets
                )
            )

    def _run_stitch(self, args: argparse.Namespace) -> None:
        from torchx_tpu.obs import stitch, timeline

        ident = _app_id_of(args.identifier)
        st = stitch.stitch(ident, obs_dir=args.obs_dir)
        if st is None:
            print(f"no trace found for: {args.identifier}", file=sys.stderr)
            sys.exit(1)
        print(stitch.render_stitched(st, include_events=args.events))
        if args.metrics:
            rows: list[tuple[str, str, float]] = []
            for d in st.sessions:
                rows.extend(timeline.load_metrics(d))
            print()
            print(
                timeline.render_metrics_table(
                    rows, include_buckets=args.buckets
                )
            )
