"""SubCommand base (reference analog: torchx/cli/cmd_base.py)."""

from __future__ import annotations

import argparse
from abc import ABC, abstractmethod


class SubCommand(ABC):
    @abstractmethod
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        ...

    @abstractmethod
    def run(self, args: argparse.Namespace) -> None:
        ...
