"""SubCommand base (reference analog: torchx/cli/cmd_base.py)."""

from __future__ import annotations

import argparse
import sys
from abc import ABC, abstractmethod
from typing import Any, Optional


class SubCommand(ABC):
    @abstractmethod
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        ...

    @abstractmethod
    def run(self, args: argparse.Namespace) -> None:
        ...


def control_client() -> Optional[Any]:
    """The CLI's proxy decision: a
    :class:`~torchx_tpu.control.client.ControlClient` when
    ``$TPX_CONTROL_ADDR`` points at a ``tpx control`` daemon, None for
    direct-runner mode. A set address with no reachable token is an
    operator error and exits 1 (silently falling back would run the job
    outside the daemon's tenancy caps)."""
    from torchx_tpu.control.client import ControlClientError, maybe_client

    try:
        return maybe_client()
    except ControlClientError as e:
        print(f"control: {e.message}", file=sys.stderr)
        sys.exit(1)
