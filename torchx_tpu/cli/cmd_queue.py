"""``tpx queue`` — the fleet scheduler's queue and placement view.

Asks the control daemon's ``/v1/queue`` for the scheduler snapshot:
queued gangs in scheduling order (priority class, fair share within the
class, FIFO), running placements (with shrink state), the modeled
fleet's inventory, and the preemption market's running totals. Finds the
daemon like every other proxied verb — ``$TPX_CONTROL_ADDR`` or the
discovery file (``require_env=False``, same as ``tpx control`` status
checks).
"""

from __future__ import annotations

import argparse
import json
import sys

from torchx_tpu.cli.cmd_base import SubCommand


class CmdQueue(SubCommand):
    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument(
            "--json",
            action="store_true",
            help="print the raw /v1/queue snapshot as JSON",
        )

    def run(self, args: argparse.Namespace) -> None:
        from torchx_tpu.control.client import ControlClientError, maybe_client

        try:
            client = maybe_client(require_env=False)
        except ControlClientError as e:
            print(f"queue: {e.message}", file=sys.stderr)
            sys.exit(1)
        if client is None:
            print(
                "queue: no control daemon found (start `tpx control"
                " --fleet ...` or set TPX_CONTROL_ADDR)",
                file=sys.stderr,
            )
            sys.exit(1)
        try:
            snap = client.queue()
        except ControlClientError as e:
            print(f"queue: {e.message}", file=sys.stderr)
            sys.exit(1)
        if args.json:
            print(json.dumps(snap, indent=2, sort_keys=True))
            return
        if not snap.get("enabled"):
            print("queue: daemon is running without a fleet scheduler")
            return
        fleet = snap.get("fleet", {})
        market = snap.get("market", {})
        print(
            f"fleet: {fleet.get('chips_free')}/{fleet.get('chips_total')}"
            f" chips free | reshapes {market.get('reshapes', 0)}"
            f" growbacks {market.get('growbacks', 0)}"
            f" kills {market.get('kills', 0)}"
        )
        running = snap.get("running", [])
        print(f"running ({len(running)}):")
        for r in running:
            shrunk = (
                f" SHRUNK {r['replicas']}/{r['launch_replicas']}"
                if r.get("shrunk")
                else f" x{r['replicas']}"
            )
            print(
                f"  {r['job']:<10} {r['class']:<12} {r['tenant']:<12}"
                f"{shrunk}  {r['handle']}"
            )
        queued = snap.get("queue", [])
        print(f"queued ({len(queued)}):")
        for q in queued:
            note = " (quota)" if q.get("quota_blocked") else ""
            print(
                f"  #{q['position']:<3} {q['job']:<10} {q['class']:<12}"
                f" {q['tenant']:<12} x{q['replicas']}"
                f" ({q['chips']} chips, waited {q['waited_seconds']}s){note}"
            )
