"""``tpx serve-pool`` — launcher-driven autoscaling generate_server pool.

Submits N ``generate_server`` replicas as one role through the Runner,
starts a least-loaded HTTP router in front of them, and runs the
probe -> autoscale -> ``Runner.resize`` control loop until interrupted::

    tpx serve-pool --config llama3_1b --replicas 2 --max-replicas 6 \\
        --base-port 8000 --router-port 9000 \\
        --target-queue-depth 4 --target-p99-ms 500

Every scale event is an ordinary ledgered resize — ``tpx trace`` shows
``serve.scale`` spans next to the ``runner.resize`` calls they made, and
``tpx_serve_replicas`` / ``tpx_serve_scale_events_total`` land in the
metrics sink. Ctrl-C cancels the app; replicas drain via their SIGTERM
handlers.

``--disaggregate`` splits serving into a prefill gang (cache-aware
chunked prefill over the radix prefix cache, client-facing) and a
decode gang (pure decode over KV blocks streamed from prefill via
``--kv-transfer``), each autoscaled independently::

    tpx serve-pool --config llama3_1b --disaggregate \\
        --prefill-replicas 1 --decode-replicas 2 \\
        --decode-base-port 8100 --prefix-cache-reserve 0.25
"""

from __future__ import annotations

import argparse
import logging
import threading

from torchx_tpu.cli.cmd_base import SubCommand
from torchx_tpu.runner.api import get_runner

logger = logging.getLogger(__name__)


class CmdServePool(SubCommand):
    """Run the serving control plane (see module docstring)."""

    def add_arguments(self, subparser: argparse.ArgumentParser) -> None:
        subparser.add_argument("--config", required=True, help="model config")
        subparser.add_argument(
            "-s",
            "--scheduler",
            default="local",
            help="scheduler backend for the replicas",
        )
        subparser.add_argument(
            "--replicas", type=int, default=1, help="initial replica count"
        )
        subparser.add_argument("--min-replicas", type=int, default=1)
        subparser.add_argument("--max-replicas", type=int, default=4)
        subparser.add_argument(
            "--base-port",
            type=int,
            default=8000,
            help="replica i serves on base-port + port-stride * i",
        )
        subparser.add_argument("--port-stride", type=int, default=1)
        subparser.add_argument(
            "--router-port",
            type=int,
            default=9000,
            help="least-loaded proxy port (0 = ephemeral)",
        )
        subparser.add_argument(
            "--target-queue-depth",
            type=float,
            default=4.0,
            help="per-replica queue depth that triggers scale-up",
        )
        subparser.add_argument(
            "--target-p99-ms",
            type=float,
            default=None,
            help="TTFT p99 SLO in ms; breaches also trigger scale-up",
        )
        subparser.add_argument(
            "--cooldown-s",
            type=float,
            default=60.0,
            help="minimum seconds between resizes",
        )
        subparser.add_argument(
            "--interval",
            type=float,
            default=5.0,
            help="control-loop probe interval seconds",
        )
        subparser.add_argument(
            "--iterations",
            type=int,
            default=None,
            help="stop after N control iterations (default: run forever)",
        )
        subparser.add_argument(
            "--engine", choices=("continuous", "coalesce"), default="continuous"
        )
        subparser.add_argument("--max-batch", type=int, default=16)
        subparser.add_argument("--ckpt-dir", default=None)
        subparser.add_argument(
            "--disaggregate",
            action="store_true",
            help="split serving into a prefill gang (cache-aware chunked"
            " prefill) and a decode gang (pure decode over transferred KV)"
            " with independent autoscale policies",
        )
        subparser.add_argument(
            "--prefill-replicas",
            type=int,
            default=1,
            help="initial prefill gang size (disaggregated mode)",
        )
        subparser.add_argument(
            "--decode-replicas",
            type=int,
            default=1,
            help="initial decode gang size (disaggregated mode)",
        )
        subparser.add_argument(
            "--decode-base-port",
            type=int,
            default=8100,
            help="decode replica i serves on decode-base-port + stride * i",
        )
        subparser.add_argument(
            "--kv-transfer",
            default=None,
            help="prefill->decode KV transfer spec (local | file:<dir> |"
            " http:<url>[,...]); default: http over the decode port range",
        )
        subparser.add_argument(
            "--prefix-cache-reserve",
            type=float,
            default=0.0,
            help="cap cached prefix blocks at this fraction of each"
            " replica's KV pool (0 = share the whole pool)",
        )
        subparser.add_argument(
            "--no-prefix-cache",
            action="store_true",
            help="disable the radix prefix cache on replicas",
        )

    def run(self, args: argparse.Namespace) -> None:
        # heavy imports deferred: `tpx --help` must stay jax-free
        from torchx_tpu.components.serve import (
            generate_server,
            generate_server_disagg,
        )
        from torchx_tpu.serve.pool import (
            AutoscalePolicy,
            DisaggServePool,
            ServePool,
            serve_router,
        )

        policy = AutoscalePolicy(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            target_queue_depth=args.target_queue_depth,
            target_p99_s=(
                args.target_p99_ms / 1000.0
                if args.target_p99_ms is not None
                else None
            ),
            cooldown_s=args.cooldown_s,
        )
        if args.disaggregate:
            app = generate_server_disagg(
                args.config,
                prefill_port=args.base_port,
                decode_port=args.decode_base_port,
                ckpt_dir=args.ckpt_dir,
                max_batch=args.max_batch,
                prefill_replicas=args.prefill_replicas,
                decode_replicas=args.decode_replicas,
                port_stride=args.port_stride,
                kv_transfer=args.kv_transfer,
                prefix_cache_reserve=args.prefix_cache_reserve,
            )
        else:
            app = generate_server(
                args.config,
                port=args.base_port,
                ckpt_dir=args.ckpt_dir,
                engine=args.engine,
                max_batch=args.max_batch,
                num_replicas=args.replicas,
                port_stride=args.port_stride,
                prefix_cache=not args.no_prefix_cache,
                prefix_cache_reserve=args.prefix_cache_reserve,
            )
        with get_runner() as runner:
            if args.disaggregate:
                pool = DisaggServePool(
                    runner,
                    app,
                    scheduler=args.scheduler,
                    prefill_base_port=args.base_port,
                    decode_base_port=args.decode_base_port,
                    port_stride=args.port_stride,
                    prefill_policy=policy,
                    decode_policy=policy,
                )
            else:
                pool = ServePool(
                    runner,
                    app,
                    scheduler=args.scheduler,
                    base_port=args.base_port,
                    port_stride=args.port_stride,
                    policy=policy,
                )
            handle = pool.start()
            router = serve_router(pool, args.router_port)
            rport = router.server_address[1]
            threading.Thread(
                target=router.serve_forever, name="tpx-router", daemon=True
            ).start()
            print(f"serve pool {handle}: routing on :{rport}", flush=True)
            try:
                pool.run(interval_s=args.interval, iterations=args.iterations)
            except KeyboardInterrupt:
                print("interrupted; cancelling pool", flush=True)
            finally:
                router.shutdown()
                pool.stop()
